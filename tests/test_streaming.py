"""Streaming ingest: bounded-memory readers + stream training + scoring.

Reference: the streaming-capable readers (readers/src/main/scala/
ImageReader.scala:85-98, BinaryFileFormat.scala:118-179). Here the whole
path is streamed: chunked decode → fixed-shape rebatching → mesh-sharded
training, never materializing the dataset."""

import os

import numpy as np
import pytest

from mmlspark_tpu.data.readers import (
    read_images, stream_binary_files, stream_images,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import ConvNetCifar, get_model
from mmlspark_tpu.train import TrainConfig, Trainer
from mmlspark_tpu.train.loop import _rebatch


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    import cv2
    root = tmp_path_factory.mktemp("stream_imgs")
    r = np.random.default_rng(0)
    # class-dependent brightness so a streamed model can actually learn
    for i in range(60):
        label = i % 2
        img = (r.integers(0, 100, (32, 32, 3)) + 120 * label
               ).astype(np.uint8)
        # index-first names: the sorted stream interleaves classes
        cv2.imwrite(str(root / f"{i:03d}_c{label}.png"), img)
    return str(root)


class TestRebatch:
    def test_uneven_chunks_to_fixed_batches(self):
        chunks = [(np.arange(i * 10, i * 10 + n, dtype=np.float32
                             ).reshape(-1, 1), np.full(n, i))
                  for i, n in enumerate([3, 7, 5, 2, 6])]  # 23 rows
        out = list(_rebatch(iter(chunks), 8))
        assert [int(b[2].sum()) for b in out] == [8, 8, 7]
        assert all(b[0].shape == (8, 1) for b in out)
        # every source row appears exactly once, in order
        got = np.concatenate([b[0][b[2] > 0, 0] for b in out])
        want = np.concatenate([c[0][:, 0] for c in chunks])
        np.testing.assert_array_equal(got, want)

    def test_mismatched_chunk_raises(self):
        with pytest.raises(ValueError, match="length mismatch"):
            list(_rebatch(iter([(np.zeros((3, 1)), np.zeros(2))]), 4))


class TestStreamReaders:
    def test_chunks_are_bounded_and_complete(self, image_dir):
        chunks = list(stream_images(image_dir, chunk_rows=16))
        assert [len(c) for c in chunks] == [16, 16, 16, 12]
        merged_paths = [v["path"] for c in chunks for v in c["image"]]
        full = read_images(image_dir)
        assert merged_paths == [v["path"] for v in full["image"]]

    def test_binary_stream_matches_materialized(self, image_dir):
        chunks = list(stream_binary_files(image_dir, chunk_rows=25))
        assert [len(c) for c in chunks] == [25, 25, 10]
        total = sum(len(b) for c in chunks for b in c["bytes"])
        assert total > 0

    def test_abandoned_stream_shuts_decode_pool(self, image_dir):
        """Pool-lifetime contract: a consumer that abandons the stream
        mid-iteration (close / break / GC) must not leak decode threads
        — shutdown is synchronous, so the workers are GONE when close()
        returns."""
        import threading
        import time

        from mmlspark_tpu.data.readers import DECODE_THREAD_PREFIX

        def decode_threads():
            return [t for t in threading.enumerate()
                    if t.name.startswith(DECODE_THREAD_PREFIX)]

        stream = stream_images(image_dir, chunk_rows=16, num_threads=4)
        first = next(stream)
        assert len(first) == 16
        assert decode_threads()  # the pool actually spun up
        stream.close()  # consumer abandons the stream mid-iteration
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and decode_threads():
            time.sleep(0.02)
        assert not decode_threads(), (
            f"leaked decode threads after close: {decode_threads()}")

    def test_resize_opt_in_and_source_resolution_passthrough(
            self, image_dir):
        # default: source resolution passes through untouched (the
        # thin-wire form — device preprocessing replays geometry)
        chunk = next(stream_images(image_dir, chunk_rows=8))
        assert all(np.asarray(v["data"]).shape == (32, 32, 3)
                   for v in chunk["image"])
        # explicit host resize: the legacy host-preprocess wire form
        resized = next(stream_images(image_dir, chunk_rows=8,
                                     resize=(16, 12)))
        assert all(np.asarray(v["data"]).shape == (16, 12, 3)
                   for v in resized["image"])
        # one-shot reader grows the same explicit opt-in
        full = read_images(image_dir, resize=(8, 8))
        assert all(np.asarray(v["data"]).shape == (8, 8, 3)
                   for v in full["image"])

    def test_sharded_streams_are_disjoint(self, image_dir):
        a = [p for c in stream_binary_files(image_dir, num_shards=2,
                                            shard_index=0, chunk_rows=8)
             for p in c["path"]]
        b = [p for c in stream_binary_files(image_dir, num_shards=2,
                                            shard_index=1, chunk_rows=8)
             for p in c["path"]]
        assert not (set(a) & set(b))
        assert len(a) + len(b) == 60


class TestStreamTraining:
    def test_convnet_trains_from_chunked_stream(self, image_dir):
        """The VERDICT item: train the CIFAR ConvNet from a chunked stream
        without ever materializing the dataset."""
        def source():
            for chunk in stream_images(image_dir, chunk_rows=16):
                imgs = np.stack([np.asarray(v["data"], np.float32) / 255.0
                                 for v in chunk["image"]])
                labels = np.asarray(
                    [int(os.path.basename(v["path"]).split("_c")[1][0])
                     for v in chunk["image"]], dtype=np.int64)
                yield imgs, labels

        module = ConvNetCifar(num_classes=2, widths=(8, 16), dense_width=32)
        cfg = TrainConfig(batch_size=16, epochs=3, learning_rate=3e-3,
                          log_every=1)
        tr = Trainer(module, cfg)
        tr.fit_stream(source)
        # 60 rows / bs16 → 4 steps per epoch (last padded), 3 epochs
        assert int(tr.state["step"]) == 12
        assert tr.history[-1] < tr.history[0]

    def test_stream_matches_arrays_numerics(self):
        # same data via fit_stream (uneven chunks) and fit_arrays must give
        # the same final params when the batch walk matches (no shuffling in
        # the stream path → compare against a stream of the shuffled walk)
        r = np.random.default_rng(1)
        x = r.normal(size=(48, 6)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int64)

        cfg = TrainConfig(batch_size=16, epochs=1, learning_rate=1e-2,
                          donate_state=False)
        mlp = get_model("MLP", input_dim=6, num_outputs=2)

        tr_s = Trainer(type(mlp.module)(features=(64,), num_outputs=2), cfg)
        # stream the exact shuffled batch order fit_arrays would use
        from mmlspark_tpu.train.loop import _batches
        def source():
            for bx, by, _ in _batches(x, y, 16, cfg.seed):
                yield bx, by
        tr_s.fit_stream(source)

        tr_a = Trainer(type(mlp.module)(features=(64,), num_outputs=2), cfg)
        tr_a.fit_arrays(x, y)

        import jax
        for a, b in zip(jax.tree_util.tree_leaves(tr_s.params),
                        jax.tree_util.tree_leaves(tr_a.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_multi_epoch_plain_iterator_rejected(self):
        cfg = TrainConfig(batch_size=8, epochs=2)
        tr = Trainer(ConvNetCifar(num_classes=2, widths=(4,), dense_width=8),
                     cfg)
        with pytest.raises(ValueError, match="callable source"):
            tr.fit_stream(iter([]))


class TestStreamScoring:
    def test_transform_stream_matches_batch(self, image_dir):
        bundle = get_model("ConvNet_CIFAR10", widths=(8, 16),
                           dense_width=32)
        jm = JaxModel(model=bundle, input_col="image", output_col="scores",
                      minibatch_size=16)
        streamed = [np.stack(list(out["scores"]))
                    for out in jm.transform_stream(
                        stream_images(image_dir, chunk_rows=20))]
        full = jm.transform(read_images(image_dir))
        np.testing.assert_allclose(
            np.concatenate(streamed), np.stack(list(full["scores"])),
            rtol=1e-5, atol=1e-5)


def test_empty_stream_raises():
    tr = Trainer(ConvNetCifar(num_classes=2, widths=(4,), dense_width=8),
                 TrainConfig(batch_size=8, epochs=1))
    with pytest.raises(ValueError, match="yielded no data"):
        tr.fit_stream(iter([]))


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    raise RuntimeError("no VmRSS")


class TestScaleBoundedStreaming:
    """The ImageNet-shard claim (BASELINE config 3): ~50k images flow
    through stream_images → ImageTransformer → JaxModel.transform_stream
    with host memory bounded by the chunk size, never the dataset. A
    materialized pass would hold ≈614 MB of decoded 64×64 pixels (plus
    scores); the streamed pass must stay far under that."""

    N_IMAGES = 50_000

    @pytest.fixture(scope="class")
    def big_zip(self, tmp_path_factory):
        import io
        import zipfile

        import cv2
        root = tmp_path_factory.mktemp("bigstream")
        zpath = str(root / "shard0.zip")
        r = np.random.default_rng(0)
        # 64 unique images re-used under distinct names: realistic decode
        # work per row without 50k encode calls
        blobs = []
        for _ in range(64):
            img = r.integers(0, 255, (64, 64, 3)).astype(np.uint8)
            ok, enc = cv2.imencode(".png", img)
            assert ok
            blobs.append(enc.tobytes())
        with zipfile.ZipFile(zpath, "w", zipfile.ZIP_STORED) as z:
            for i in range(self.N_IMAGES):
                z.writestr(f"img_{i:06d}.png", blobs[i % len(blobs)])
        return zpath

    @pytest.mark.slow
    def test_50k_images_stream_with_bounded_rss(self, big_zip):
        from mmlspark_tpu.stages.image import ImageTransformer

        bundle = get_model("ConvNet_CIFAR10", widths=(8, 16),
                           dense_width=32)
        jm = JaxModel(model=bundle, input_col="image", output_col="scores",
                      minibatch_size=1024)
        tf = ImageTransformer().resize(32, 32)

        chunks = stream_images(big_zip, inspect_zip=True, chunk_rows=512)
        rows = 0
        score_sum = 0.0
        baseline = None
        peak = 0.0
        for out in jm.transform_stream(tf.transform(c) for c in chunks):
            rows += len(out)
            score_sum += float(np.sum(np.stack(list(out["scores"]))))
            if baseline is None:
                # after the first chunk: compile + runtimes are resident
                baseline = _rss_mb()
            peak = max(peak, _rss_mb())
        assert rows == self.N_IMAGES
        assert np.isfinite(score_sum)
        growth = peak - baseline
        # chunk-bounded memory: the bound is RELATIVE to what a
        # materialized pass would pin (~614 MB of decoded pixels) with
        # generous slack for allocator-arena/BLAS-pool jitter, since
        # absolute VmRSS depends on what earlier tests left resident
        assert growth < 400, (
            f"streaming RSS grew {growth:.0f} MB over the run — "
            "memory is scaling with the dataset, not the chunk")

"""A minimal stand-in Spark engine for driving ``bridge/spark.py`` in CI.

pyspark cannot be installed in this environment, but the one-call wrapper
(`spark_transform` / `output_spark_schema`) is real product code and must
execute, not just parse. This stub provides exactly the surface that code
touches, with the REAL calling conventions:

* ``StubDataFrame.limit(n).toPandas()`` — the driver-side schema probe,
* ``StubDataFrame.mapInArrow(fn, schema)`` — calls ``fn`` once per
  partition with an *iterator of pyarrow.RecordBatch* and expects an
  iterator of ``RecordBatch`` back, concatenated in partition order —
  byte-for-byte the contract documented for
  ``pyspark.sql.DataFrame.mapInArrow``,
* a fake ``pyspark`` package (``sys.modules`` injection) whose
  ``pyspark.sql.pandas.types.from_arrow_schema`` records the Arrow schema
  it was asked to convert.

The real-Spark integration tests (importorskip-gated) remain the
engine-level proof; this makes the wrapper's logic CI-covered. Analog of
the reference's notebook-on-cluster validation
(tools/notebook/tester/TestNotebooksOnHdi.py:10-36) scaled down to a
process-local fake.
"""

from __future__ import annotations

import sys
import types
from typing import Any, Callable, Iterator, Sequence


class StubSparkSchema:
    """What our fake ``from_arrow_schema`` returns: remembers the Arrow
    schema so tests can assert the wrapper inferred the right one."""

    def __init__(self, arrow_schema: Any):
        self.arrow_schema = arrow_schema

    def __eq__(self, other):
        return (isinstance(other, StubSparkSchema)
                and self.arrow_schema == other.arrow_schema)


class StubDataFrame:
    """An Arrow-table-backed fake of the two DataFrame methods the bridge
    wrapper uses. Partitioning is explicit so mapInArrow exercises the
    one-bridge-per-partition path."""

    def __init__(self, tables: Sequence[Any]):
        import pyarrow as pa
        self._parts: list[pa.Table] = [pa.table(t) if not isinstance(
            t, pa.Table) else t for t in tables]

    @classmethod
    def from_pandas(cls, pdf: Any, num_partitions: int = 2
                    ) -> "StubDataFrame":
        import pyarrow as pa
        tab = pa.Table.from_pandas(pdf)
        n = len(tab)
        if n == 0 or num_partitions <= 1:
            return cls([tab])
        per = max(1, n // num_partitions)
        parts = [tab.slice(s, per) for s in range(0, n, per)]
        return cls(parts)

    # --- the surface bridge/spark.py touches ---

    def limit(self, n: int) -> "StubDataFrame":
        import pyarrow as pa
        remaining, out = n, []
        for p in self._parts:
            take = min(remaining, len(p))
            if take:
                out.append(p.slice(0, take))
            remaining -= take
            if remaining <= 0:
                break
        return StubDataFrame(out or [self._parts[0].slice(0, 0)])

    def toPandas(self):
        import pyarrow as pa
        return pa.concat_tables(self._parts).to_pandas()

    def mapInArrow(self, fn: Callable[[Iterator], Iterator],
                   schema: Any) -> "StubDataFrame":
        """Run ``fn`` per partition over an iterator of RecordBatches —
        the exact executor calling convention — eagerly (the stub has no
        lazy plan; what matters is the protocol)."""
        import pyarrow as pa
        out_parts = []
        self.applied_schema = schema
        for part in self._parts:
            out_batches = list(fn(iter(part.to_batches())))
            if out_batches:
                out_parts.append(pa.Table.from_batches(out_batches))
        return StubDataFrame(out_parts or
                             [self._parts[0].slice(0, 0)])

    def to_arrow(self):
        import pyarrow as pa
        return pa.concat_tables(self._parts)


def install(monkeypatch) -> types.ModuleType:
    """Register the fake ``pyspark`` package in ``sys.modules`` (via
    monkeypatch, so it cleanly uninstalls) and return it."""
    pyspark = types.ModuleType("pyspark")
    sql = types.ModuleType("pyspark.sql")
    pandas_mod = types.ModuleType("pyspark.sql.pandas")
    types_mod = types.ModuleType("pyspark.sql.pandas.types")
    types_mod.from_arrow_schema = StubSparkSchema
    pandas_mod.types = types_mod
    sql.pandas = pandas_mod
    pyspark.sql = sql
    for name, mod in (("pyspark", pyspark), ("pyspark.sql", sql),
                      ("pyspark.sql.pandas", pandas_mod),
                      ("pyspark.sql.pandas.types", types_mod)):
        monkeypatch.setitem(sys.modules, name, mod)
    return pyspark

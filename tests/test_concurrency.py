"""The whole-repo concurrency verifier and its runtime witness.

Three layers under test, mirroring docs/concurrency.md:

* the **static pass** (``mmlspark_tpu/analysis/concurrency.py``):
  flagged + clean fixture pairs pin every rule (CC101–CC105), the
  pragma policy (CC100 on an unjustified suppression), and the
  repo-level zero-findings gate;
* the **runtime lock-order witness** (``mmlspark_tpu/obs/lockwitness.py``):
  held-stack edge recording, condition-wait truthfulness, the
  crosscheck labels, and the ABBA fixture driven to the brink of a
  real deadlock (timeout-guarded) with both conflicting orders
  recorded;
* the **lock-scope regression tests** for the bugs the verifier found
  in ``serve/server.py`` and ``serve/batcher.py`` — batcher drains
  must not run under the server/tick locks, and the ``lane_down``
  hook must fire with no scheduler lock held.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import jax

from mmlspark_tpu.analysis.concurrency import (
    RULES, analyze_paths, analyze_repo, analyze_sources,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.obs import lockwitness as lw
from mmlspark_tpu.serve import (
    ModelServer, ServeConfig, ServerClosed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IN_DIM = 6


def run_fixture(*sources):
    """Analyze (module, source) pairs as one program."""
    return analyze_sources([(src, f"{mod.replace('.', '/')}.py", mod)
                            for mod, src in sources])


def rules_of(an):
    return sorted(f.rule for f in an.findings)


def mlp_model(seed=0):
    module = MLP(features=(8,), num_outputs=4)
    params = module.init(jax.random.PRNGKey(seed),
                         np.zeros((1, IN_DIM), np.float32))["params"]
    bundle = ModelBundle(
        module=module,
        params=jax.tree_util.tree_map(np.asarray, params),
        input_spec=(IN_DIM,), output_names=("features", "logits"),
        name="mlp")
    return JaxModel(model=bundle, input_col="x", output_col="s")


def vec_table(n, seed=0):
    rows = np.random.default_rng(seed).normal(
        size=(n, IN_DIM)).astype(np.float32)
    return DataTable({"x": list(rows)})


@pytest.fixture(autouse=True)
def _witness_off():
    yield
    lw.disable()
    lw.reset()


# ---- static pass: fixture pairs per rule ----


ABBA_SRC = '''
import threading

_a = threading.Lock()
_b = threading.Lock()


def forward():
    with _a:
        with _b:
            pass


def backward():
    with _b:
        with _a:
            pass
'''

ABBA_CLEAN_SRC = ABBA_SRC.replace(
    "    with _b:\n        with _a:", "    with _a:\n        with _b:")


class TestCC101LockOrderCycle:
    def test_abba_flagged_with_both_witness_paths(self):
        an = run_fixture(("fix.abba", ABBA_SRC))
        assert rules_of(an) == ["CC101"]
        msg = an.findings[0].message
        # both directions of the cycle must be spelled out, each with
        # its own file:line witness — an unactionable cycle report is
        # as good as none
        assert "fix.abba._a -> fix.abba._b" in msg
        assert "fix.abba._b -> fix.abba._a" in msg
        assert msg.count("fix/abba.py:") == 2

    def test_consistent_order_clean(self):
        an = run_fixture(("fix.abba", ABBA_CLEAN_SRC))
        assert rules_of(an) == []
        assert ("fix.abba._a", "fix.abba._b") in an.static_edges()

    def test_cycle_through_callee_flagged(self):
        src = '''
import threading

_a = threading.Lock()
_b = threading.Lock()


def inner_b():
    with _b:
        pass


def forward():
    with _a:
        inner_b()


def backward():
    with _b:
        inner_a()


def inner_a():
    with _a:
        pass
'''
        an = run_fixture(("fix.chain", src))
        assert "CC101" in rules_of(an)


class TestCC102BlockingUnderLock:
    def test_sleep_under_lock_flagged(self):
        src = '''
import threading
import time

_lk = threading.Lock()


def hold():
    with _lk:
        time.sleep(0.5)
'''
        an = run_fixture(("fix.sleepy", src))
        assert rules_of(an) == ["CC102"]
        assert "fix.sleepy._lk" in an.findings[0].message

    def test_sleep_after_release_clean(self):
        src = '''
import threading
import time

_lk = threading.Lock()


def hold():
    with _lk:
        pass
    time.sleep(0.5)
'''
        assert rules_of(run_fixture(("fix.sleepy", src))) == []

    def test_blocking_reached_through_callee_flagged(self):
        src = '''
import threading
import time

_lk = threading.Lock()


def slow_io():
    time.sleep(0.5)


def hold():
    with _lk:
        slow_io()
'''
        an = run_fixture(("fix.deep", src))
        assert rules_of(an) == ["CC102"]

    def test_condition_wait_is_not_blocking(self):
        # cv.wait() releases the lock it waits on — the one blocking
        # call that is legal (and idiomatic) under its own lock
        src = '''
import threading

_cv = threading.Condition()


def waiter():
    with _cv:
        while True:
            _cv.wait(timeout=1.0)
'''
        assert rules_of(run_fixture(("fix.cv", src))) == []


class TestCC103UnguardedAcquire:
    def test_bare_acquire_flagged(self):
        src = '''
import threading

_lk = threading.Lock()


def bad():
    _lk.acquire()
    do_work()
    _lk.release()


def do_work():
    pass
'''
        an = run_fixture(("fix.acq", src))
        assert "CC103" in rules_of(an)

    def test_try_finally_clean(self):
        src = '''
import threading

_lk = threading.Lock()


def good():
    _lk.acquire()
    try:
        do_work()
    finally:
        _lk.release()


def do_work():
    pass
'''
        assert rules_of(run_fixture(("fix.acq", src))) == []


class TestCC104JoinlessThread:
    def test_nondaemon_unjoined_flagged(self):
        src = '''
import threading


def spawn():
    t = threading.Thread(target=work)
    t.start()


def work():
    pass
'''
        an = run_fixture(("fix.thr", src))
        assert rules_of(an) == ["CC104"]

    def test_daemon_clean(self):
        src = '''
import threading


def spawn():
    t = threading.Thread(target=work, daemon=True)
    t.start()


def work():
    pass
'''
        assert rules_of(run_fixture(("fix.thr", src))) == []

    def test_joined_clean(self):
        src = '''
import threading


def spawn():
    t = threading.Thread(target=work)
    t.start()
    t.join()


def work():
    pass
'''
        assert rules_of(run_fixture(("fix.thr", src))) == []


class TestCC105CallbackUnderLock:
    def test_callback_under_lock_flagged(self):
        src = '''
import threading

_lk = threading.Lock()


def fire(on_done):
    with _lk:
        on_done()
'''
        an = run_fixture(("fix.cb", src))
        assert rules_of(an) == ["CC105"]

    def test_callback_after_release_clean(self):
        src = '''
import threading

_lk = threading.Lock()


def fire(on_done):
    with _lk:
        pass
    on_done()
'''
        assert rules_of(run_fixture(("fix.cb", src))) == []


class TestSuppressionPolicy:
    SLEEPY = '''
import threading
import time

_lk = threading.Lock()


def hold():
    with _lk:
        time.sleep(0.5)  # concurrency: allow(CC102){just}
'''

    def test_unjustified_pragma_is_itself_a_finding(self):
        src = self.SLEEPY.replace("{just}", "")
        an = run_fixture(("fix.prag", src))
        assert rules_of(an) == ["CC100"]
        assert not an.suppressed

    def test_justified_pragma_suppresses_and_records(self):
        src = self.SLEEPY.replace("{just}", ": warming is the contract")
        an = run_fixture(("fix.prag", src))
        assert rules_of(an) == []
        assert len(an.suppressed) == 1
        f, why = an.suppressed[0]
        assert f.rule == "CC102"
        assert why == "warming is the contract"

    def test_rule_catalogue_documented(self):
        for r in ("CC100", "CC101", "CC102", "CC103", "CC104", "CC105"):
            assert r in RULES and RULES[r]


# ---- static pass: the repo itself ----


class TestRepoGate:
    def test_repo_has_zero_unsuppressed_findings(self):
        an = analyze_repo()
        assert [str(f) for f in an.findings] == []

    def test_every_repo_suppression_is_justified(self):
        an = analyze_repo()
        assert an.suppressed, "the curated suppression list went empty"
        for f, why in an.suppressed:
            assert why.strip(), f"unjustified suppression: {f}"

    def test_server_fixes_are_not_suppressions(self):
        # the PR's serve/server.py lock-scope bugs were FIXED; pin that
        # no CC102 is hiding behind a pragma there instead
        an = analyze_repo()
        for f, _why in an.suppressed:
            assert not (f.rule == "CC102"
                        and f.path.endswith("serve/server.py")), str(f)

    def test_witness_identities_align_with_static_graph(self):
        # the string passed to a named_* factory IS the identity the
        # analyzer derives — the two graphs must join on these names
        an = analyze_repo()
        names = {ld.name for ld in an.locks.values()}
        for hot in ("serve.batcher.DynamicBatcher._cv",
                    "serve.batcher.DynamicBatcher._sched_cv",
                    "serve.server.ModelServer._lock",
                    "serve.lifecycle.CanaryState.tick_lock",
                    "serve.lifecycle.DecisionJournal._lock",
                    "obs.metrics.Counter._lock",
                    "obs.runtime._lock",
                    "obs.slo.SLOTracker._lock",
                    "obs.flight.FlightRecorder._lock"):
            assert hot in names, f"witnessed lock {hot} left the inventory"
        assert ("serve.batcher.DynamicBatcher._cv",
                "obs.metrics.Counter._lock") in an.static_edges()

    def test_analyzer_never_imports_analyzed_code(self):
        # a poisoned module must be analyzable, not executed
        src = 'raise RuntimeError("imported!")\n'
        an = run_fixture(("fix.poison", src))
        assert rules_of(an) == []


# ---- the runtime witness ----


class TestWitnessRecording:
    def test_disabled_records_nothing(self):
        a = lw.named_lock("w.a")
        with a:
            pass
        assert lw.edges() == {}
        assert lw.acquire_counts() == {}

    def test_nested_acquisition_records_edge(self):
        a, b = lw.named_lock("w.a"), lw.named_lock("w.b")
        lw.enable()
        with a:
            with b:
                pass
        assert ("w.a", "w.b") in lw.edges()
        assert ("w.b", "w.a") not in lw.edges()
        assert lw.acquire_counts() == {"w.a": 1, "w.b": 1}

    def test_release_pops_held_stack(self):
        a, b = lw.named_lock("w.a"), lw.named_lock("w.b")
        lw.enable()
        with a:
            pass
        with b:
            pass
        assert lw.edges() == {}  # never held together

    def test_enable_resets_previous_run(self):
        a, b = lw.named_lock("w.a"), lw.named_lock("w.b")
        lw.enable()
        with a:
            with b:
                pass
        lw.enable()
        assert lw.edges() == {}

    def test_condition_wait_keeps_held_stack_truthful(self):
        cv = lw.named_condition("w.cv")
        other = lw.named_lock("w.other")
        lw.enable()
        woke = threading.Event()

        def waiter():
            with cv:
                cv.wait(timeout=10.0)
            # the wait RELEASED w.cv — locks taken while blocked in
            # wait() on another thread must not edge from it
            woke.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        with other:  # acquired while waiter sits inside cv.wait()
            pass
        with cv:
            cv.notify_all()
        t.join(timeout=10.0)
        assert woke.is_set()
        assert ("w.cv", "w.other") not in lw.edges()
        assert lw.violations() == []

    def test_violations_report_both_directions(self):
        a, b = lw.named_lock("w.a"), lw.named_lock("w.b")
        lw.enable()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert lw.violations() == [("w.a", "w.b")]

    def test_crosscheck_labels(self):
        a, b = lw.named_lock("w.a"), lw.named_lock("w.b")
        c = lw.named_lock("w.c")
        lw.enable()
        with a:
            with b:
                pass
        with a:
            with c:
                pass
        cross = lw.crosscheck([("w.a", "w.b"), ("w.x", "w.y")])
        assert cross["confirmed"] == [("w.a", "w.b")]
        assert cross["plausible"] == [("w.x", "w.y")]
        assert cross["novel"] == [("w.a", "w.c")]
        assert cross["violations"] == []


class TestABBABrink:
    def test_abba_driven_to_the_brink_records_conflict(self):
        """Two threads each hold their first lock and try the other's
        under a timeout — the real ABBA interleaving, survived because
        every blocking acquire is bounded. The witness must come back
        with both orders (a CC101's runtime shadow) and the test must
        finish: the fixture deadlocks precisely when the timeouts are
        removed."""
        a = lw.named_lock("abba.A")
        b = lw.named_lock("abba.B")
        lw.enable()
        barrier = threading.Barrier(2, timeout=10.0)
        outcomes = {}

        def cross(name, first, second):
            with first:
                barrier.wait()  # both now hold their first lock
                got = second.acquire(timeout=0.25)  # the brink
                if got:
                    second.release()
                outcomes[name] = got

        t1 = threading.Thread(target=cross, args=("t1", a, b), daemon=True)
        t2 = threading.Thread(target=cross, args=("t2", b, a), daemon=True)
        t0 = time.monotonic()
        t1.start(); t2.start()
        t1.join(timeout=10.0); t2.join(timeout=10.0)
        assert not t1.is_alive() and not t2.is_alive(), "ABBA deadlocked"
        assert time.monotonic() - t0 < 10.0
        # whichever thread's timed acquire won (possibly both, after
        # the loser released), seal both orders deterministically
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert ("abba.A", "abba.B") in lw.edges()
        assert ("abba.B", "abba.A") in lw.edges()
        assert lw.violations() == [("abba.A", "abba.B")]
        cross_report = lw.crosscheck([("abba.A", "abba.B")])
        assert cross_report["violations"] == [("abba.A", "abba.B")]


# ---- regression tests: the lock-scope bugs the verifier found ----


class _BlockingClose:
    """Patch target: makes a batcher's close() block on an event so the
    test can prove no server lock is held across the drain."""

    def __init__(self, monkeypatch):
        from mmlspark_tpu.serve.batcher import DynamicBatcher
        self.entered = threading.Event()
        self.release = threading.Event()
        orig = DynamicBatcher.close
        blocker = self

        def slow_close(bself, drain=True):
            blocker.entered.set()
            assert blocker.release.wait(timeout=30.0)
            return orig(bself, drain=drain)

        monkeypatch.setattr(DynamicBatcher, "close", slow_close)


class TestServeLockScopeRegressions:
    def test_add_model_on_closed_server_drains_outside_lock(
            self, monkeypatch):
        """The CC102 fix: the closed-race cleanup close() (which joins
        lane threads) must run after ``ModelServer._lock`` is released
        — a server hit by a slow drain must keep answering reads."""
        server = ModelServer(ServeConfig(buckets=(1,)))
        server.close()
        blocker = _BlockingClose(monkeypatch)
        errs = []

        def loser():
            try:
                server.add_model("late", mlp_model(),
                                 example=vec_table(1))
            except Exception as e:  # noqa: BLE001 - recorded for assert
                errs.append(e)

        t = threading.Thread(target=loser, daemon=True)
        t.start()
        assert blocker.entered.wait(timeout=30.0)
        # the drain is mid-flight; the server lock must be free
        t0 = time.monotonic()
        assert server.models() == []
        assert time.monotonic() - t0 < 1.0, (
            "ModelServer._lock held across a batcher drain")
        blocker.release.set()
        t.join(timeout=30.0)
        assert len(errs) == 1 and isinstance(errs[0], ServerClosed)

    def test_rollback_drains_outside_tick_lock(self, monkeypatch):
        """The CC102 fix in the lifecycle: a rollback's full drain must
        not run under ``CanaryState.tick_lock`` — concurrent ticks must
        see the detached canary and return immediately instead of
        queueing behind the drain."""
        server = ModelServer(ServeConfig(buckets=(1,)))
        try:
            server.add_model("m", mlp_model(0), example=vec_table(1))
            server.deploy_canary("m", mlp_model(1), mode="shadow",
                                 fraction=1.0, version="v2")
            canary = server._models["m"].canary
            blocker = _BlockingClose(monkeypatch)
            results = {}

            def roll():
                results["rollback"] = server.rollback("m")

            t = threading.Thread(target=roll, daemon=True)
            t.start()
            assert blocker.entered.wait(timeout=30.0)
            # drain mid-flight: tick_lock is free and a concurrent tick
            # sees the already-detached canary
            t0 = time.monotonic()
            assert canary.tick_lock.acquire(timeout=1.0), (
                "tick_lock held across the canary drain")
            canary.tick_lock.release()
            assert server.lifecycle_tick("m") is None
            assert time.monotonic() - t0 < 2.0
            blocker.release.set()
            t.join(timeout=30.0)
            assert results["rollback"]["action"] == "rollback"
        finally:
            server.close()

    def test_lane_down_hook_fires_with_no_scheduler_lock_held(self):
        """The CC105 fix: the ``lane_down`` notification must fire
        after ``_sched_cv`` is released, so a listener may re-enter the
        batcher (queued(), the scheduler cv) without deadlocking."""
        from mmlspark_tpu.core.retry import RetryPolicy
        from mmlspark_tpu.serve import (
            FaultPlan, FaultSpec, LaneFailed, faults,
        )
        server = ModelServer(ServeConfig(
            buckets=(1, 2), max_queue=16,
            lane_restart=RetryPolicy(max_attempts=1, jitter=0.0)))
        reentered = threading.Event()
        try:
            server.add_model("m", mlp_model(), example=vec_table(1))
            batcher = server._models["m"].batcher
            journal_hook = batcher.on_lane_event

            def reentrant_hook(kind, payload):
                if kind == "lane_down":
                    # both batcher locks must be acquirable from the
                    # hook — this deadlocked when the notification
                    # fired under _sched_cv
                    assert batcher.queued >= 0  # takes _cv
                    with batcher._sched_cv:
                        pass
                    reentered.set()
                if journal_hook is not None:
                    journal_hook(kind, payload)

            batcher.on_lane_event = reentrant_hook
            plan = FaultPlan([FaultSpec("lane_death", model="m")])
            with faults.inject(plan):
                h = server.submit("m", vec_table(2))
                with pytest.raises(LaneFailed):
                    h.result(timeout=30)
            assert reentered.wait(timeout=30.0), (
                "lane_down hook never completed — deadlocked against "
                "the scheduler cv")
        finally:
            server.close()
        from conftest import assert_no_leaked_threads
        from mmlspark_tpu.serve.batcher import THREAD_PREFIX
        assert_no_leaked_threads(THREAD_PREFIX)


# ---- CLI surfaces ----


class TestCLI:
    def test_analyze_concurrency_json_schema(self, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import analyze
        rc = analyze.main(["concurrency", "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        rep = json.loads(out)
        assert set(rep) == {"locks", "threads", "edges", "findings",
                            "suppressed"}
        assert rep["findings"] == []
        for s in rep["suppressed"]:
            assert {"rule", "path", "line", "message", "justification",
                    "pragma"} <= set(s)
            assert s["pragma"] == "allowed"
            assert s["justification"].strip()

    def test_analyze_concurrency_flags_fixture(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import analyze
        bad = tmp_path / "abba.py"
        bad.write_text(ABBA_SRC)
        rc = analyze.main(["concurrency", str(bad)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "CC101" in out

    def test_analyze_concurrency_missing_path_exit_2(self, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import analyze
        assert analyze.main(
            ["concurrency", "/nonexistent/nope.py"]) == 2

    def test_lint_json_matches_schema(self, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import lint_jax
        rc = lint_jax.main(["--json"])
        out = capsys.readouterr().out
        assert rc == 0
        rep = json.loads(out)
        assert set(rep) == {"findings", "suppressed"}
        assert rep["findings"] == []
        for s in rep["suppressed"]:
            assert {"rule", "path", "line", "message", "justification",
                    "pragma"} <= set(s)

"""Packaging checks (reference: tools/pip/setup.py:1-35 — the pip wheel).

The package must be installable (`pip install -e .`), expose console entry
points, and ship the native kernel source as package data so installed
wheels can source-build it (NativeLoader analog)."""

import os

import pytest

import mmlspark_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_version_consistent_with_pyproject():
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        py = f.read()
    assert 'dynamic = ["version"]' in py
    assert mmlspark_tpu.__version__.count(".") == 2


def test_native_source_is_package_data():
    # the wheel ships src/imgops.cpp; the loader builds it on first use
    src = os.path.join(os.path.dirname(mmlspark_tpu.__file__),
                       "native", "src", "imgops.cpp")
    assert os.path.exists(src)
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        assert 'src/*.cpp' in f.read()


def test_console_entry_points_resolve():
    from importlib import metadata
    try:
        dist = metadata.distribution("mmlspark-tpu")
    except metadata.PackageNotFoundError:
        pytest.skip("package not pip-installed in this environment")
    eps = {e.name: e for e in dist.entry_points
           if e.group == "console_scripts"}
    assert {"mmlspark-tpu-build-repo", "mmlspark-tpu-docgen"} <= set(eps)
    for e in eps.values():
        assert callable(e.load())


def test_installed_package_serves_the_stage_registry():
    from mmlspark_tpu.core.registry import all_stages
    assert len(all_stages()) >= 50

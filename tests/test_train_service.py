"""Elastic fault-tolerant training service (round 11): the recovery
policy as a pure signal→action function, the deterministic elastic
ingest walk, in-process reshard/rescale bit-preservation, the worker
liveness beacon, and the supervisor e2e over real worker processes —
restart on transient crash, hang detection via beacon deadlines,
straggler eviction, and shutdown hygiene (heartbeat rows forgotten, no
leaked threads)."""

import json
import os
import sys
import time

import numpy as np
import pytest

import jax

from conftest import assert_no_leaked_threads

from mmlspark_tpu.core.retry import RetryPolicy, call_with_retry
from mmlspark_tpu.models.zoo import MLP
from mmlspark_tpu.parallel.mesh import (
    MeshSpec, make_mesh, state_shardings,
)
from mmlspark_tpu.train.checkpoint import reshard_state
from mmlspark_tpu.train.loop import TrainConfig, Trainer
from mmlspark_tpu.train.service import (
    BEACON_THREAD, ENV_CKPT, ENV_DIR, ENV_GENERATION, ENV_RANK, ENV_WORLD,
    Fail, Ledger, Proceed, RecoveryPolicy, Rescale, Restart, ServiceBeacon,
    ServiceConfig, ServiceWorkerInfo, Topology, TrainSupervisor,
    WorkerExit, WorkerHang, WorkerStraggling, elastic_batch_indices,
    elastic_stream, service_context,
)


def xor_data(n=128, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 8)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    return x, y


# ---------------------------------------------------------------------------
# retry policy (core/retry.py)
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_delays_grow_and_cap(self):
        p = RetryPolicy(max_attempts=5, base_delay_s=1.0, max_delay_s=3.0,
                        multiplier=2.0, jitter=0.0)
        assert list(p.delays()) == [1.0, 2.0, 3.0, 3.0]

    def test_jitter_bounded(self):
        p = RetryPolicy(max_attempts=10, base_delay_s=1.0,
                        max_delay_s=1.0, jitter=0.5)
        for d in p.delays():
            assert 0.5 <= d <= 1.0

    def test_call_with_retry_succeeds_after_transients(self):
        calls = {"n": 0}
        retried = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        out = call_with_retry(
            flaky, RetryPolicy(max_attempts=3, base_delay_s=0.0),
            on_retry=lambda a, e, d: retried.append((a, str(e))),
            sleep=lambda s: None)
        assert out == "ok" and calls["n"] == 3
        assert [a for a, _ in retried] == [1, 2]

    def test_exhausted_raises_last_error(self):
        def always():
            raise OSError("down")

        with pytest.raises(OSError, match="down"):
            call_with_retry(always,
                            RetryPolicy(max_attempts=2, base_delay_s=0.0),
                            sleep=lambda s: None)

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def typed():
            calls["n"] += 1
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            call_with_retry(typed, RetryPolicy(max_attempts=5,
                                               base_delay_s=0.0),
                            sleep=lambda s: None)
        assert calls["n"] == 1

    def test_retry_if_predicate_refines_type_match(self):
        calls = {"n": 0}

        def permanent():
            calls["n"] += 1
            raise OSError(404, "not found")

        with pytest.raises(OSError):
            call_with_retry(
                permanent,
                RetryPolicy(max_attempts=5, base_delay_s=0.0,
                            retry_if=lambda e: e.args[0] != 404),
                sleep=lambda s: None)
        assert calls["n"] == 1  # predicate said permanent: no retry

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(multiplier=0.5)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)


# ---------------------------------------------------------------------------
# recovery policy: pure signal → action
# ---------------------------------------------------------------------------


def _policy(**kw):
    kw.setdefault("restart_backoff",
                  RetryPolicy(max_attempts=8, base_delay_s=0.0,
                              jitter=0.0))
    return RecoveryPolicy(**kw)


class TestRecoveryPolicy:
    def test_preempt_code_rescales_immediately(self):
        p = _policy(max_restarts=5)
        a = p.decide(WorkerExit(rank=1, code=75),
                     Ledger(rungs_total=2))
        assert isinstance(a, Rescale) and a.evict_rank == 1

    def test_preempt_without_smaller_rung_fails(self):
        a = _policy().decide(WorkerExit(0, 75), Ledger(rungs_total=1))
        assert isinstance(a, Fail)

    def test_crash_restarts_within_budget_then_rescales(self):
        p = _policy(max_restarts=2)
        led = Ledger(rungs_total=2)
        assert isinstance(p.decide(WorkerExit(0, 1), led), Restart)
        led.restarts_used = 2
        assert isinstance(p.decide(WorkerExit(0, 1), led), Rescale)

    def test_crash_exhausted_no_rung_fails(self):
        p = _policy(max_restarts=0)
        a = p.decide(WorkerExit(0, 1),
                     Ledger(restarts_used=0, rungs_total=1))
        assert isinstance(a, Fail)

    def test_hang_takes_the_crash_path(self):
        p = _policy(max_restarts=1, hang_timeout_s=1.0)
        led = Ledger(rungs_total=2)
        assert isinstance(p.decide(WorkerHang(0, 2.0), led), Restart)
        led.restarts_used = 1
        assert isinstance(p.decide(WorkerHang(0, 2.0), led), Rescale)

    def test_straggler_below_threshold_proceeds(self):
        p = _policy(evict_straggler_after=3)
        a = p.decide(WorkerStraggling(1, 2), Ledger(rungs_total=2))
        assert isinstance(a, Proceed)

    def test_straggler_at_threshold_evicts(self):
        p = _policy(evict_straggler_after=3)
        a = p.decide(WorkerStraggling(1, 3), Ledger(rungs_total=2))
        assert isinstance(a, Rescale) and a.evict_rank == 1

    def test_clean_exit_proceeds(self):
        assert isinstance(_policy().decide(WorkerExit(0, 0),
                                           Ledger()), Proceed)

    def test_restart_backoff_schedule(self):
        p = RecoveryPolicy(max_restarts=3, restart_backoff=RetryPolicy(
            max_attempts=8, base_delay_s=1.0, max_delay_s=4.0,
            multiplier=2.0, jitter=0.0))
        led = Ledger(rungs_total=1)
        delays = []
        for k in range(3):
            led.restarts_used = k
            a = p.decide(WorkerExit(0, 1), led)
            delays.append(a.delay_s)
        assert delays == [1.0, 2.0, 4.0]

    def test_topology_ladder_must_shrink(self):
        with pytest.raises(ValueError, match="must not GROW"):
            ServiceConfig(cmd=("true",), service_dir="/tmp/x",
                          topologies=(Topology(1), Topology(2)))
        # devices are capacity too: a rung must not gain virtual devices
        with pytest.raises(ValueError, match="must not GROW"):
            ServiceConfig(cmd=("true",), service_dir="/tmp/x",
                          topologies=(Topology(1, devices=4),
                                      Topology(1, devices=8)))


# ---------------------------------------------------------------------------
# deterministic elastic ingest
# ---------------------------------------------------------------------------


class TestElasticStream:
    def test_global_batches_topology_independent(self):
        """The process-order concat of every world's slices equals the
        global walk — elastic re-scale replays the same global batches
        at any world size."""
        x, y = xor_data(96)
        for world in (2, 4):
            solo = list(elastic_stream(x, y, batch_size=32, seed=7)())
            sharded = [list(elastic_stream(
                x, y, batch_size=32, seed=7, rank=r, world=world)())
                for r in range(world)]
            assert all(len(s) == len(solo) for s in sharded)
            for k, (gx, gy) in enumerate(solo):
                cx = np.concatenate([sharded[r][k][0]
                                     for r in range(world)])
                cy = np.concatenate([sharded[r][k][1]
                                     for r in range(world)])
                np.testing.assert_array_equal(gx, cx)
                np.testing.assert_array_equal(gy, cy)

    def test_epoch_walks_differ_but_cover_all_rows(self):
        x, y = xor_data(64)
        idx0 = list(elastic_batch_indices(64, 16, seed=0, epoch=0))
        idx1 = list(elastic_batch_indices(64, 16, seed=0, epoch=1))
        assert not all(np.array_equal(a, b)
                       for a, b in zip(idx0, idx1))
        for walk in (idx0, idx1):
            assert sorted(np.concatenate(walk).tolist()) == list(range(64))

    def test_validation(self):
        x, y = xor_data(32)
        with pytest.raises(ValueError, match="rank"):
            elastic_stream(x, y, batch_size=16, seed=0, rank=2, world=2)
        with pytest.raises(ValueError, match="divide"):
            elastic_stream(x, y, batch_size=15, seed=0, world=2)

    def test_sharded_walk_refuses_partial_tail(self):
        """A short tail batch slices unevenly across ranks and would
        silently desynchronize the per-rank chunk streams from the next
        epoch on — a loud error, not a masked tail (world=1 keeps the
        masked-tail behavior)."""
        x, y = xor_data(100)  # 100 % 32 != 0
        with pytest.raises(ValueError, match="partial tail"):
            elastic_stream(x, y, batch_size=32, seed=0, rank=0, world=2)
        # solo walks may keep the masked tail
        chunks = list(elastic_stream(x, y, batch_size=32, seed=0)())
        assert [len(c[0]) for c in chunks] == [32, 32, 32, 4]

    def test_trainer_consumes_same_losses_at_any_world(self):
        """fit_stream over rank slices committed through
        make_array-style concat is exercised in the multihost harness;
        single-process, the walk must reproduce the fit_arrays-style
        deterministic schedule run-to-run."""
        x, y = xor_data(128)
        runs = []
        for _ in range(2):
            cfg = TrainConfig(batch_size=32, epochs=1, log_every=1,
                              seed=0, donate_state=False)
            tr = Trainer(MLP(features=(16,), num_outputs=2), cfg,
                         mesh=make_mesh(MeshSpec(dp=2),
                                        jax.devices()[:2]))
            tr.fit_stream(elastic_stream(x, y, batch_size=32, seed=0,
                                         epochs=2), input_spec=(8,))
            runs.append(tr.history)
        assert runs[0] == runs[1]


# ---------------------------------------------------------------------------
# reshard_state / Trainer.rescale
# ---------------------------------------------------------------------------


class TestElasticReshard:
    def test_reshard_preserves_bits_and_reshards_layout(self):
        x, y = xor_data()
        mesh8 = make_mesh(MeshSpec(dp=4, fsdp=2), jax.devices()[:8])
        mesh4 = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
        cfg = TrainConfig(batch_size=32, epochs=1, donate_state=False)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh8)
        tr.fit_arrays(x, y)
        moved = reshard_state(tr.state, mesh8, mesh4)
        for a, b in zip(jax.tree_util.tree_leaves(tr.state),
                        jax.tree_util.tree_leaves(moved)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        kernel = moved["params"]["dense0"]["kernel"]
        assert kernel.sharding.mesh.devices.size == 4
        assert "fsdp" in str(kernel.sharding.spec)

    def test_reshard_to_single_device_uses_plain_placement(self):
        mesh2 = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
        mesh1 = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
        cfg = TrainConfig(batch_size=8, epochs=1, donate_state=False)
        tr = Trainer(MLP(features=(4,), num_outputs=2), cfg, mesh=mesh2)
        tr.state = tr.init_state((8,))
        moved = reshard_state(tr.state, mesh2, mesh1)
        from jax.sharding import SingleDeviceSharding
        leaf = moved["params"]["dense0"]["kernel"]
        assert isinstance(leaf.sharding, SingleDeviceSharding)

    def test_state_shardings_match_init_state_layout(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4), jax.devices()[:8])
        cfg = TrainConfig(batch_size=16, epochs=1)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh)
        state = tr.init_state((8,))
        targets = state_shardings(mesh, state)
        for leaf, target in zip(jax.tree_util.tree_leaves(state),
                                jax.tree_util.tree_leaves(targets)):
            assert leaf.sharding == target, (leaf.sharding, target)

    def test_state_shardings_moments_mirror_rule_placed_params(self):
        """Optimizer moments are params-structured subtrees: they must
        take the params shardings leaf for leaf — INCLUDING module-rule
        placements a per-leaf generic pass cannot reproduce (the MoE
        expert-stack case)."""
        from jax.sharding import PartitionSpec as P
        mesh = make_mesh(MeshSpec(dp=2, ep=4), jax.devices()[:8])
        params = {"experts": np.zeros((4, 8, 8), np.float32),
                  "dense": np.zeros((8, 3), np.float32)}
        state = {
            "params": params,
            # adam-like: (scalar count, params-structured mu)
            "opt_state": (np.zeros((), np.int32),
                          {"experts": np.zeros((4, 8, 8), np.float32),
                           "dense": np.zeros((8, 3), np.float32)}),
            "step": np.zeros((), np.int32),
        }

        def rules(path, leaf):
            return P("ep") if path == "experts" else None

        targets = state_shardings(mesh, state, rules=rules)
        assert targets["params"]["experts"].spec == P("ep")
        mu = targets["opt_state"][1]
        assert mu["experts"].spec == P("ep"), (
            "rule-placed param's moment did not mirror the rule")
        assert mu["dense"] == targets["params"]["dense"]
        # scalar leaves replicate
        assert targets["opt_state"][0].spec == P()
        assert targets["step"].spec == P()

    def test_rescale_continues_bit_identically(self):
        """Training N more steps after an 8→4 device rescale equals
        training them on a fresh 4-device trainer seeded with the same
        state — the in-process elastic path adds zero numerical drift."""
        x, y = xor_data()
        mesh8 = make_mesh(MeshSpec(dp=4, fsdp=2), jax.devices()[:8])
        mesh4 = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
        cfg = TrainConfig(batch_size=32, epochs=1, log_every=1, seed=1,
                          donate_state=False)
        tr = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh8)
        tr.fit_arrays(x, y)

        ref = Trainer(MLP(features=(16,), num_outputs=2), cfg, mesh=mesh4)
        ref.state = reshard_state(tr.state, mesh8, mesh4)

        tr.rescale(mesh=mesh4)
        assert tr.mesh is mesh4
        tr.fit_arrays(x, y)
        ref.fit_arrays(x, y)
        assert tr.history[-4:] == ref.history[-4:]
        for a, b in zip(jax.tree_util.tree_leaves(tr.params),
                        jax.tree_util.tree_leaves(ref.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# worker beacon + service context
# ---------------------------------------------------------------------------


class TestServiceBeacon:
    def _env(self, monkeypatch, tmp_path, **extra):
        monkeypatch.setenv(ENV_DIR, str(tmp_path))
        monkeypatch.setenv(ENV_RANK, "0")
        monkeypatch.setenv(ENV_WORLD, "1")
        monkeypatch.setenv(ENV_GENERATION, "2")
        for k, v in extra.items():
            monkeypatch.setenv(k, v)

    def test_outside_service_yields_none(self, monkeypatch):
        monkeypatch.delenv(ENV_DIR, raising=False)
        with service_context() as info:
            assert info is None

    def test_beacon_publishes_flight_progress(self, monkeypatch,
                                              tmp_path):
        from mmlspark_tpu.obs import flight
        self._env(monkeypatch, tmp_path,
                  **{ENV_CKPT: str(tmp_path / "ck")})
        flight.enable(str(tmp_path / "flight"), poll_s=0.05)
        try:
            with service_context(beacon_interval_s=0.05) as info:
                assert info == ServiceWorkerInfo(
                    service_dir=str(tmp_path), rank=0, world=1,
                    generation=2, devices=None,
                    checkpoint_dir=str(tmp_path / "ck"))
                rec = flight.recorder()
                rec.arm("train/fit_stream")
                for _ in range(3):
                    rec.beat("train/fit_stream")
                deadline = time.monotonic() + 5.0
                beacon = None
                while time.monotonic() < deadline:
                    try:
                        with open(info.beacon_path()) as f:
                            beacon = json.load(f)
                        if beacon["progress"] >= 3:
                            break
                    except (OSError, ValueError):
                        pass
                    time.sleep(0.02)
                assert beacon is not None and beacon["progress"] >= 3
                assert beacon["busy"] is True
                assert beacon["generation"] == 2
                assert beacon["status"] == "running"
        finally:
            from mmlspark_tpu import obs
            flight.disable()
            obs.disable()
            obs.clear()
        # terminal write + no leaked beacon thread
        with open(os.path.join(str(tmp_path), "beacon_0.json")) as f:
            assert json.load(f)["status"] == "exited"
        assert_no_leaked_threads(BEACON_THREAD)

    def test_beacon_reports_crash_status(self, monkeypatch, tmp_path):
        self._env(monkeypatch, tmp_path)
        with pytest.raises(RuntimeError):
            with service_context(beacon_interval_s=0.05):
                raise RuntimeError("worker died")
        with open(os.path.join(str(tmp_path), "beacon_0.json")) as f:
            assert json.load(f)["status"] == "crashed"
        assert_no_leaked_threads(BEACON_THREAD)


# ---------------------------------------------------------------------------
# supervisor e2e over trivial (jax-free) worker processes
# ---------------------------------------------------------------------------


def _run_supervisor(tmp_path, worker_src, topologies, policy, **cfg_kw):
    sup = TrainSupervisor(ServiceConfig(
        cmd=(sys.executable, "-c", worker_src),
        service_dir=str(tmp_path), topologies=topologies, policy=policy,
        worker_obs=False, worker_flight=False, poll_s=0.05,
        grace_seconds=5.0, **cfg_kw))
    return sup.run()


FLAKY_WORKER = """
import os, sys
d = os.environ["MMLSPARK_TPU_SERVICE_DIR"]
flag = os.path.join(d, "crashed_once")
if not os.path.exists(flag):
    open(flag, "w").close()
    sys.exit(3)
sys.exit(0)
"""

HANG_WORKER = """
import json, os, sys, time
d = os.environ["MMLSPARK_TPU_SERVICE_DIR"]
rank = os.environ["MMLSPARK_TPU_SERVICE_RANK"]
gen = int(os.environ["MMLSPARK_TPU_SERVICE_GENERATION"])
flag = os.path.join(d, "hung_once")
if os.path.exists(flag):
    sys.exit(0)
open(flag, "w").close()
while True:  # busy but frozen: progress never advances
    payload = {"rank": int(rank), "generation": gen, "ts": time.time(),
               "progress": 1, "busy": True, "stragglers": 0,
               "host_step_ms": {}}
    tmp = os.path.join(d, f"beacon_{rank}.json.tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, os.path.join(d, f"beacon_{rank}.json"))
    time.sleep(0.05)
"""

NO_BEACON_WORKER = """
import os, sys, time
d = os.environ["MMLSPARK_TPU_SERVICE_DIR"]
flag = os.path.join(d, "wedged_once")
if os.path.exists(flag):
    sys.exit(0)
open(flag, "w").close()
time.sleep(3600)  # wedged before the first beacon ever publishes
"""

# BOTH ranks publish the SAME global straggler verdict count (the real
# fenced exchange increments every process's counter identically) —
# pinning that the supervisor counts verdict WINDOWS (max across
# beacons), not per-beacon increments (which would evict world x early)
STRAGGLER_WORLD = """
import json, os, sys, time
d = os.environ["MMLSPARK_TPU_SERVICE_DIR"]
rank = os.environ["MMLSPARK_TPU_SERVICE_RANK"]
gen = int(os.environ["MMLSPARK_TPU_SERVICE_GENERATION"])
if gen > 0:
    sys.exit(0)  # the re-scaled generation completes immediately
n = 0
while True:
    n += 1
    payload = {"rank": int(rank), "generation": gen, "ts": time.time(),
               "progress": n, "busy": True,
               "stragglers": n // 8,
               "host_step_ms": {"0": 10.0, "1": 80.0}}
    tmp = os.path.join(d, f"beacon_{rank}.json.tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, os.path.join(d, f"beacon_{rank}.json"))
    time.sleep(0.05)
"""


class TestTrainSupervisor:
    def test_transient_crash_restarts_and_completes(self, tmp_path):
        report = _run_supervisor(
            tmp_path, FLAKY_WORKER, (Topology(world=1),),
            _policy(max_restarts=1))
        assert report.ok
        assert report.restarts == 1 and report.rescales == 0
        assert len(report.generations) == 2
        assert report.generations[0].signal == WorkerExit(0, 3)
        assert isinstance(report.generations[0].action, Restart)

    def test_restart_budget_exhausted_without_rung_fails(self, tmp_path):
        always_crash = "import sys; sys.exit(3)"
        report = _run_supervisor(
            tmp_path, always_crash, (Topology(world=1),),
            _policy(max_restarts=1))
        assert not report.ok
        assert report.restarts == 1
        assert "restart budget" in report.reason

    def test_hang_detected_via_beacon_deadline(self, tmp_path):
        # 2s deadline: python startup on a loaded CI box can exceed a
        # sub-second timeout BEFORE the worker writes its flag/beacon,
        # which would hang-kill a healthy worker and flake the restart
        # accounting
        report = _run_supervisor(
            tmp_path, HANG_WORKER, (Topology(world=1),),
            _policy(max_restarts=1, hang_timeout_s=2.0))
        assert report.ok
        assert report.restarts == 1
        sig = report.generations[0].signal
        assert isinstance(sig, WorkerHang) and sig.stalled_s >= 2.0

    def test_straggler_evicted_and_world_rescaled(self, tmp_path):
        report = _run_supervisor(
            tmp_path, STRAGGLER_WORLD,
            (Topology(world=2), Topology(world=1)),
            _policy(evict_straggler_after=2))
        assert report.ok
        assert report.evictions == 1 and report.rescales == 1
        sig = report.generations[0].signal
        assert isinstance(sig, WorkerStraggling)
        assert sig.rank == 1  # host 1 is the slow one (80 ms vs 10 ms)
        # both ranks report the SAME global verdict count: the eviction
        # must land at the configured threshold, not world x earlier
        assert sig.count == 2
        assert report.final_topology.world == 1

    def test_worker_wedged_before_first_beacon_hits_deadline(self,
                                                             tmp_path):
        """A worker that hangs BEFORE its first beacon (backend init, a
        dead beacon thread) must still trip the deadline — absence of
        the liveness signal past the timeout is itself the hang
        signal."""
        from mmlspark_tpu.obs import flight
        # supervisor's own recorder with a LOW threshold: the per-worker
        # service/ heartbeat rows must stay IDLE without beacon evidence
        # — an armed-busy row here would ripen into spurious watchdog
        # hang dumps while the deadline machinery is still within budget
        flight.enable(str(tmp_path / "flight"), hang_threshold_s=0.5,
                      poll_s=0.05)
        try:
            report = _run_supervisor(
                tmp_path, NO_BEACON_WORKER, (Topology(world=1),),
                _policy(max_restarts=1, hang_timeout_s=2.0))
            import glob
            hang_dumps = glob.glob(
                str(tmp_path / "flight" / "flight_hang_*.json"))
            service_blamed = []
            for p in hang_dumps:
                with open(p) as f:
                    extra = json.load(f).get("extra", {})
                if str(extra.get("heartbeat", "")).startswith("service/"):
                    service_blamed.append(p)
            assert not service_blamed, (
                "supervisor's idle worker rows produced spurious flight "
                f"hang dumps: {service_blamed}")
        finally:
            from mmlspark_tpu import obs
            flight.disable()
            obs.disable()
            obs.clear()
        assert report.ok
        assert report.restarts == 1
        sig = report.generations[0].signal
        assert isinstance(sig, WorkerHang) and sig.stalled_s >= 2.0

    def test_decisions_logged_and_no_stray_threads(self, tmp_path):
        report = _run_supervisor(
            tmp_path, FLAKY_WORKER, (Topology(world=1),),
            _policy(max_restarts=1))
        assert report.ok
        with open(tmp_path / "decisions.jsonl") as f:
            entries = [json.loads(ln) for ln in f]
        kinds = [e["kind"] for e in entries]
        assert kinds.count("launch") == 2
        assert "restart" in kinds and "done" in kinds
        from mmlspark_tpu.train.service import WATCH_THREAD
        assert_no_leaked_threads(WATCH_THREAD)

    def test_supervisor_forgets_worker_heartbeats(self, tmp_path):
        """The satellite fix: dead workers' supervisor-side flight
        heartbeat rows are forgotten at shutdown — model/generation
        churn must not bloat dumps or ripen dead busy rows into
        spurious hang dumps."""
        from mmlspark_tpu.obs import flight
        flight.enable(str(tmp_path / "flight"), poll_s=0.05)
        try:
            report = _run_supervisor(
                tmp_path, FLAKY_WORKER, (Topology(world=1),),
                _policy(max_restarts=1))
            assert report.ok
            rows = flight.recorder().heartbeats()
            assert not [n for n in rows if n.startswith("service/")], rows
        finally:
            from mmlspark_tpu import obs
            flight.disable()
            obs.disable()
            obs.clear()

    def test_service_events_and_gauges_when_obs_enabled(self, tmp_path):
        from mmlspark_tpu import obs
        obs.disable()
        obs.clear()
        obs.registry().reset()
        obs.enable()
        try:
            report = _run_supervisor(
                tmp_path, FLAKY_WORKER, (Topology(world=1),),
                _policy(max_restarts=1))
            assert report.ok
            reg = obs.registry()
            assert reg.value("train.service.restarts") == 1
            # one exit per generation: the crash (3) and the clean 0
            assert reg.value("train.service.worker_exits") == 2
            assert reg.gauge("train.service.generation").value == 1
            names = {getattr(r, "name", "") for r in obs.captured()}
            assert "service/restart" in names
            assert "service/worker_exit" in names
        finally:
            obs.disable()
            obs.clear()
            obs.registry().reset()

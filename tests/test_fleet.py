"""Fleet telemetry plane (obs/fleet.py + obs/timeseries.py): cross-
process snapshot export/merge must be BIT-exact for counters, the fleet
timeline must correct injected wall-clock skew at the fence seams, the
exporter must leave no stray threads and flush a final snapshot on crash
through the flight-recorder hook (order pinned), and the timeseries
sampler must persist a queryable gauge history."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from conftest import assert_no_leaked_threads

from mmlspark_tpu import obs
from mmlspark_tpu.obs import fleet as obs_fleet
from mmlspark_tpu.obs import flight as obs_flight
from mmlspark_tpu.obs import timeseries as obs_ts
from mmlspark_tpu.obs.fleet import (
    FleetCollector, FleetReadError, TelemetryExporter,
)
from mmlspark_tpu.obs.metrics import (
    Counter, MetricsRegistry, format_series,
)
from mmlspark_tpu.obs.timeseries import MetricHistory, TimeSeriesSampler


@pytest.fixture(autouse=True)
def obs_isolated():
    obs_fleet.disable()
    obs_ts.disable()
    obs.disable()
    obs.clear()
    obs.registry().reset()
    yield
    obs_fleet.disable()
    obs_ts.disable()
    obs_flight.disable()
    obs.disable()
    obs.clear()
    obs.registry().reset()


def _counter_truth(regs) -> dict:
    out: dict = {}
    for reg in regs:
        for m in reg.iter_metrics():
            if isinstance(m, Counter):
                key = format_series(m.name, m.labels)
                out[key] = out.get(key, 0.0) + m.value
    return out


# ---------------------------------------------------------------------------
# two jax-free supervisor workers -> bit-equal merge
# ---------------------------------------------------------------------------

FLEET_TEST_WORKER = """
import json, os, time
from mmlspark_tpu import obs
from mmlspark_tpu.obs import fleet
from mmlspark_tpu.obs.metrics import Counter, format_series
from mmlspark_tpu.train.service import service_context

with service_context(beacon_interval_s=0.05) as info:
    reg = obs.registry()
    # distinct per-rank totals so the merged sums are non-trivial
    for k in range(10 + info.rank * 5):
        with obs.span("train/step", "train"):
            pass
        reg.counter("train.steps").add()
        reg.counter("serve.test_bytes").add(3.5)
    reg.gauge("train.host_step_ms", host=str(info.rank)).set(
        10.0 + info.rank)
    reg.gauge("train.input.wait_fraction").set(0.25 * (info.rank + 1))
    truth = {format_series(m.name, m.labels): m.value
             for m in reg.iter_metrics() if isinstance(m, Counter)}
    with open(os.path.join(info.service_dir,
                           "truth_%d.json" % info.rank), "w") as f:
        json.dump(truth, f)
    fleet.disable()  # final snapshot after the truth capture
"""


def test_two_worker_snapshots_merge_bit_equal(tmp_path):
    """Two supervised jax-free workers exporting under one fleet dir:
    the collector's merged counters equal the sum of the per-process
    registry truths bit-for-bit, and per-process gauges stay
    distinguishable (pid label) even on one host."""
    from mmlspark_tpu.train.service import (
        RecoveryPolicy, ServiceConfig, Topology, TrainSupervisor,
    )
    fleet_dir = str(tmp_path / "fleet")
    svc_dir = str(tmp_path / "svc")
    report = TrainSupervisor(ServiceConfig(
        cmd=(sys.executable, "-c", FLEET_TEST_WORKER),
        service_dir=svc_dir, topologies=(Topology(world=2),),
        policy=RecoveryPolicy(), poll_s=0.05, grace_seconds=10.0,
        worker_obs=True, worker_flight=False,
        extra_env={"MMLSPARK_TPU_FLEET": fleet_dir})).run()
    assert report.ok, report.reason

    expected: dict = {}
    for rank in (0, 1):
        with open(os.path.join(svc_dir, f"truth_{rank}.json")) as fh:
            for k, v in json.load(fh).items():
                expected[k] = expected.get(k, 0.0) + v
    view = FleetCollector(fleet_dir).collect()
    merged = _counter_truth([view.registry])
    assert merged == expected  # bit-for-bit: sums of exact increments
    assert merged["train.steps"] == 25  # 10 + 15
    assert merged["serve.test_bytes"] == 3.5 * 25

    # per-process gauges distinguishable: same metric, same host label,
    # two pid labels
    gauges = view.registry.snapshot()["gauges"]
    wait = {k: v for k, v in gauges.items()
            if k.startswith("train.input.wait_fraction")}
    assert len(wait) == 2
    assert sorted(wait.values()) == [0.25, 0.5]
    # a series that already carries host= keeps its own attribution
    step_ms = {k: v for k, v in gauges.items()
               if k.startswith("train.host_step_ms")}
    assert sorted(step_ms.values()) == [10.0, 11.0]
    assert any("host=0" in k for k in step_ms)
    assert any("host=1" in k for k in step_ms)


# ---------------------------------------------------------------------------
# clock skew: injected ±50 ms corrected at the fence seam
# ---------------------------------------------------------------------------


def _write_snapshot(fleet_dir, host, pid, wall_s, records, seq=1):
    pdir = os.path.join(fleet_dir, f"proc_{host}_{pid}")
    os.makedirs(pdir, exist_ok=True)
    payload = {
        "fleet": 1, "host": host, "pid": pid, "seq": seq,
        "reason": "interval",
        "stamp": {"wall_s": wall_s, "perf_ns": 0},
        "registry": [],
        "ring": records,
    }
    with open(os.path.join(pdir, f"snap_{seq:06d}.json"), "w") as fh:
        json.dump(payload, fh)


def _span(name, start_ms, dur_ms, span_id, tid=1):
    return {"name": name, "cat": "train",
            "start_ns": int(start_ms * 1e6), "dur_ns": int(dur_ms * 1e6),
            "tid": tid, "thread_name": "T", "span_id": span_id,
            "parent_id": None, "depth": 0, "labels": {}}


def test_injected_50ms_skew_corrected_at_fence_seam(tmp_path):
    """Host B's wall clock reads +50 ms ahead of host A's. The fenced
    span (train/liveness_sync) ends at the same REAL instant on both —
    after correction the fleet export must order B's pre-fence span
    BEFORE A's post-fence span (naive wall ordering has it after), and
    the two fence midpoints must land within ~2 ms of each other."""
    d = str(tmp_path / "fleet")
    # host A (reference): fence spans ending at perf 100 ms and 200 ms,
    # a post-fence span at 101 ms
    _write_snapshot(d, "hostA", 11, 1000.0, [
        _span("train/liveness_sync", 95.0, 5.0, 1),
        _span("train/liveness_sync", 195.0, 5.0, 2),
        _span("after_fence", 101.0, 1.0, 3),
    ])
    # host B: SAME perf timeline (its fences end at the same real
    # instants), but its wall stamp is +50 ms skewed
    _write_snapshot(d, "hostB", 22, 1000.050, [
        _span("train/liveness_sync", 95.0, 5.0, 1),
        _span("train/liveness_sync", 195.0, 5.0, 2),
        _span("before_second_fence", 150.0, 1.0, 3),
    ])
    view = FleetCollector(d).collect()
    by_name = {p.host: p for p in view.processes}
    assert by_name["hostA"].skew_ms == 0.0
    assert by_name["hostB"].skew_ms == pytest.approx(-50.0, abs=0.5)

    trace = view.chrome_trace()
    meta = trace["fleetMeta"]
    assert meta["unaligned"] == []
    assert meta["stitched_flows"] == 2  # both fences cross 2 processes
    spans = {(ev["args"]["host"], ev["name"]): ev
             for ev in trace["traceEvents"] if ev.get("ph") == "X"}
    fence_a = spans[("hostA", "train/liveness_sync")]
    fence_b = spans[("hostB", "train/liveness_sync")]
    assert abs((fence_a["ts"] + fence_a["dur"])
               - (fence_b["ts"] + fence_b["dur"])) < 2e3  # < 2 ms (µs)
    # ordering across hosts is REAL-time: B's 150 ms span precedes A's
    # second fence (naive wall clock would put it 50 ms later)
    assert spans[("hostB", "before_second_fence")]["ts"] \
        < spans[("hostA", "train/liveness_sync")]["ts"] + 100e3


def test_fence_matching_is_per_name_and_tail_aligned(tmp_path):
    """Ring retention drops the OLDEST records: a process that lost its
    early fence spans must still pair its surviving fences with the
    reference's corresponding ones (tail alignment — head alignment
    would compute a correction of the wrong SIGN here), and a process
    whose only fences are a different collective (serve lockstep vs
    train liveness) must not be matched against it at all."""
    d = str(tmp_path / "fleet")
    # host A (reference): lost its first fence to ring eviction — keeps
    # the fences ending at real 200 ms and 300 ms
    _write_snapshot(d, "hostA", 11, 1000.0, [
        _span("train/liveness_sync", 195.0, 5.0, 1),
        _span("train/liveness_sync", 295.0, 5.0, 2),
    ])
    # host B: +50 ms wall skew, all three fences retained
    _write_snapshot(d, "hostB", 22, 1000.050, [
        _span("train/liveness_sync", 95.0, 5.0, 1),
        _span("train/liveness_sync", 195.0, 5.0, 2),
        _span("train/liveness_sync", 295.0, 5.0, 3),
    ])
    # host C: a serve process whose fences are a DIFFERENT collective
    # at unrelated times — no shared fence name with the reference, so
    # it must keep correction 0, never a bogus median
    _write_snapshot(d, "hostC", 33, 1000.0, [
        _span("serve/lockstep_agree", 40.0, 2.0, 1),
        _span("serve/lockstep_agree", 70.0, 2.0, 2),
    ])
    view = FleetCollector(d).collect()
    skew = {p.host: p.skew_ms for p in view.processes}
    assert skew["hostA"] == 0.0
    assert skew["hostB"] == pytest.approx(-50.0, abs=0.5)
    assert skew["hostC"] == 0.0
    # stitching pairs from the tail too: B's LAST two fences join A's,
    # its orphaned earliest fence stitches nothing
    assert view.chrome_trace()["fleetMeta"]["stitched_flows"] == 2


def test_missing_stamp_pair_reported_unaligned(tmp_path):
    d = str(tmp_path / "fleet")
    _write_snapshot(d, "hostA", 11, 1000.0, [_span("s", 1.0, 1.0, 1)])
    pdir = os.path.join(d, "proc_hostB_22")
    os.makedirs(pdir)
    with open(os.path.join(pdir, "snap_000001.json"), "w") as fh:
        json.dump({"fleet": 1, "host": "hostB", "pid": 22, "seq": 1,
                   "reason": "interval", "registry": [],
                   "ring": [_span("s", 1.0, 1.0, 1)]}, fh)
    view = FleetCollector(d).collect()
    assert view.unaligned() == ["proc_hostB_22"]
    meta = view.chrome_trace()["fleetMeta"]
    assert meta["unaligned"] == ["proc_hostB_22"]
    # the unplaceable process's records are excluded from the timeline
    pids = {ev.get("pid") for ev in view.chrome_trace()["traceEvents"]
            if ev.get("ph") == "X"}
    assert pids == {11}


# ---------------------------------------------------------------------------
# exporter hygiene: threads, retention, crash snapshot via flight hook
# ---------------------------------------------------------------------------


def test_exporter_no_stray_threads_and_retention(tmp_path):
    d = str(tmp_path / "fleet")
    exp = obs_fleet.enable(d, interval_s=30.0, retention=3)
    assert obs_ts.enabled()  # the sampler rides the exporter
    for _ in range(6):
        exp.snapshot("interval")
    snaps = [n for n in os.listdir(exp.proc_dir)
             if n.startswith("snap_")]
    assert len(snaps) == 3  # bounded retention, newest kept
    obs_fleet.disable()
    assert not obs_ts.enabled()
    assert_no_leaked_threads("FleetExporter", "TimeSeriesSampler")
    # the exit snapshot is the final word
    view = FleetCollector(d).collect()
    assert view.processes[0].reason == "exit"


def test_exporter_seq_resumes_past_existing_snapshots(tmp_path):
    """A disable()/enable() cycle (or reconfigure) in one process must
    resume seq past the snapshots already on disk — restarting at 0
    would make the name-sorted retention sweep prune the FRESH
    snapshots while keeping stale ones as 'newest truth'."""
    d = str(tmp_path / "fleet")
    exp = obs_fleet.enable(d, interval_s=30.0, retention=3)
    obs.registry().counter("serve.gen").add(1)
    for _ in range(4):
        exp.snapshot("interval")
    obs_fleet.disable()  # exit snapshot; highest seq on disk
    obs.registry().counter("serve.gen").add(1)  # now 2
    exp2 = obs_fleet.enable(d, interval_s=30.0, retention=3)
    path = exp2.snapshot("interval")
    assert path is not None and os.path.exists(path)  # not self-pruned
    obs_fleet.disable()
    view = FleetCollector(d).collect()
    assert view.counter_value("serve.gen") == 2  # the NEW truth won
    assert view.processes[0].seq > 5


def test_publish_fleet_survives_rank_labeled_worker_counter(tmp_path):
    """Worker code is arbitrary: a train.* counter already labeled
    rank= must not TypeError the supervisor's watch loop — the fleet
    rank dimension overrides it."""
    from mmlspark_tpu.train.service import (
        RecoveryPolicy, ServiceConfig, TrainSupervisor, _Worker,
    )
    obs.enable()
    sup = TrainSupervisor(ServiceConfig(
        cmd=("true",), service_dir=str(tmp_path),
        policy=RecoveryPolicy()))
    w = _Worker.__new__(_Worker)
    w.rank, w.counter_last, w.straggler_hits = 0, {}, 0
    beacons = {0: {"progress": 1, "stragglers": 0, "host_step_ms": {},
                   "counters": [["train.custom", {"rank": "9"}, 5.0]]}}
    sup._publish_fleet([w], beacons, sup._fleet_aggregates(beacons))
    assert obs.registry().value("train.fleet.custom", rank=0) == 5


def test_enable_idempotent_same_dir(tmp_path):
    d = str(tmp_path / "fleet")
    exp1 = obs_fleet.enable(d, interval_s=30.0)
    exp2 = obs_fleet.enable(d, interval_s=30.0)
    assert exp1 is exp2  # no teardown/rebuild on an ensure-on call


def test_flight_crash_dump_flushes_fleet_snapshot_order_pinned(tmp_path):
    """The pinned hook order: the flight post-mortem lands on disk
    FIRST, then the fleet exporter flushes a snapshot whose extra
    names that dump path — so the fleet plane's last word about a
    crashed process both exists and points at the local forensics."""
    obs.enable()
    obs_flight.enable(str(tmp_path / "flight"), poll_s=30.0)
    obs_fleet.enable(str(tmp_path / "fleet"), interval_s=30.0)
    try:
        exc = ValueError("induced crash")
        dump_path = obs_flight.on_crash(exc, context="test")
        assert dump_path is not None and os.path.exists(dump_path)
        proc_dir = obs_fleet.exporter().proc_dir
        snaps = sorted(n for n in os.listdir(proc_dir)
                       if n.startswith("snap_"))
        with open(os.path.join(proc_dir, snaps[-1])) as fh:
            snap = json.load(fh)
        assert snap["reason"] == "flight_crash"
        assert snap["extra"]["flight_dump"] == dump_path
        # order pinned: the snapshot's registry already carries the
        # flight.dumps counter bump — proof the dump completed first
        dumps = [r for r in snap["registry"]
                 if r["name"] == "flight.dumps"]
        assert dumps and dumps[0]["value"] == 1
    finally:
        obs_fleet.disable()
        obs_flight.disable()
    assert_no_leaked_threads("FleetExporter", "FlightWatchdog")


def test_collector_missing_dir_typed(tmp_path):
    with pytest.raises(FleetReadError):
        FleetCollector(str(tmp_path / "nope")).collect()
    os.makedirs(str(tmp_path / "empty"))
    with pytest.raises(FleetReadError):
        FleetCollector(str(tmp_path / "empty")).collect()


def test_histogram_merge_window_holds_every_process(tmp_path):
    """The fleet histogram's window is sized to the whole merged
    concatenation — interning at the default window would evict the
    first processes' samples in directory order and bias the fleet
    quantiles toward whichever process merged last."""
    d = str(tmp_path / "fleet")
    # two processes each exporting a FULL default-sized window: the
    # naive merge would keep only the last 4096 of the 8192 values
    for pid, base in ((11, 0.0), (22, 10000.0)):
        pdir = os.path.join(d, f"proc_h_{pid}")
        os.makedirs(pdir)
        values = [base + k for k in range(4096)]
        with open(os.path.join(pdir, "snap_000001.json"), "w") as fh:
            json.dump({
                "fleet": 1, "host": "h", "pid": pid, "seq": 1,
                "reason": "exit",
                "stamp": {"wall_s": 1.0, "perf_ns": 0},
                "registry": [{"kind": "histogram", "name": "serve.e2e_ms",
                              "labels": [["model", "m"]],
                              "count": len(values), "sum": sum(values),
                              "window": values}],
                "ring": []}, fh)
    view = FleetCollector(d).collect()
    h = view.registry.histogram("serve.e2e_ms", model="m")
    assert h.count == 8192
    assert len(h.values()) == 8192  # both processes' windows retained
    pct = h.percentiles()
    assert 2000.0 < pct["p50"] < 10000.0  # spans BOTH processes


def test_registry_only_collect_reads_newest_snapshot(tmp_path):
    d = str(tmp_path / "fleet")
    pdir = os.path.join(d, "proc_h_11")
    os.makedirs(pdir)
    for seq, total in ((1, 5.0), (2, 9.0)):
        with open(os.path.join(pdir, f"snap_{seq:06d}.json"), "w") as fh:
            json.dump({
                "fleet": 1, "host": "h", "pid": 11, "seq": seq,
                "reason": "interval",
                "stamp": {"wall_s": 1.0, "perf_ns": 0},
                "registry": [{"kind": "counter", "name": "serve.total",
                              "labels": [], "value": total}],
                "ring": [_span("s", 1.0, 1.0, seq)]}, fh)
    view = FleetCollector(d).collect(include_ring=False)
    assert view.counter_value("serve.total") == 9.0  # newest wins
    assert view.processes[0].records == []  # ring skipped entirely
    # a torn newest snapshot falls back to the previous one
    with open(os.path.join(pdir, "snap_000003.json"), "w") as fh:
        fh.write("{torn")
    view = FleetCollector(d).collect(include_ring=False)
    assert view.counter_value("serve.total") == 9.0


def test_abandoned_server_source_is_not_pinned(tmp_path):
    """A ModelServer discarded WITHOUT close() (e.g. after a failed
    load) must not be kept alive — and kept exporting its dead series
    — by the module-global registry-source list."""
    import gc
    import weakref as _weakref

    from mmlspark_tpu.serve import ModelServer, ServeConfig

    server = ModelServer(ServeConfig(buckets=(1,)))
    n_before = len(obs_fleet.all_registries())
    ref = _weakref.ref(server)
    del server
    gc.collect()
    assert ref() is None  # the source list held it only weakly
    assert len(obs_fleet.all_registries()) == n_before  # no dead entry


def test_histograms_merge_windows_and_counts(tmp_path):
    d = str(tmp_path / "fleet")
    for pid, values in ((11, [1.0, 2.0]), (22, [3.0, 4.0, 5.0])):
        pdir = os.path.join(d, f"proc_h_{pid}")
        os.makedirs(pdir)
        with open(os.path.join(pdir, "snap_000001.json"), "w") as fh:
            json.dump({
                "fleet": 1, "host": "h", "pid": pid, "seq": 1,
                "reason": "exit",
                "stamp": {"wall_s": 1.0, "perf_ns": 0},
                "registry": [{"kind": "histogram", "name": "serve.e2e_ms",
                              "labels": [["model", "m"]],
                              "count": len(values), "sum": sum(values),
                              "window": values}],
                "ring": []}, fh)
    view = FleetCollector(d).collect()
    h = view.registry.histogram("serve.e2e_ms", model="m")
    assert h.count == 5 and h.sum == 15.0
    assert sorted(h.values()) == [1.0, 2.0, 3.0, 4.0, 5.0]


# ---------------------------------------------------------------------------
# timeseries: ring + JSONL + query API
# ---------------------------------------------------------------------------


def test_metric_history_ring_query_and_rate(tmp_path):
    hist = MetricHistory(maxlen=4)
    for k in range(6):
        hist.append(100.0 + k, "serve.queue_depth{model=m}", 2.0 * k)
    got = hist.range("serve.queue_depth")
    assert list(got) == ["serve.queue_depth{model=m}"]
    samples = got["serve.queue_depth{model=m}"]
    assert len(samples) == 4  # ring bound: oldest evicted
    assert samples[0] == (102.0, 4.0) and samples[-1] == (105.0, 10.0)
    # time-bounded range
    got = hist.range("serve.queue_depth", t0=104.0)
    assert len(got["serve.queue_depth{model=m}"]) == 2
    # last-N
    assert hist.last("serve.queue_depth", n=1)[
        "serve.queue_depth{model=m}"] == [(105.0, 10.0)]
    # rate over the full ring: dv/dt = 6/3
    rates = hist.rate("serve.queue_depth")
    assert rates["serve.queue_depth{model=m}"] == pytest.approx(2.0)


def test_sampler_selects_prefixes_and_persists_jsonl(tmp_path):
    path = str(tmp_path / "ts.jsonl")
    reg = MetricsRegistry()
    reg.gauge("serve.slo_burn_short", model="m").set(3.0)
    reg.counter("train.service.restarts").add(2)
    reg.counter("plan.h2d_uploads").add(9)  # not a sampled prefix
    sampler = TimeSeriesSampler(registries=lambda: [reg], path=path,
                                interval_s=30.0)
    n = sampler.sample(now=100.0)
    reg.gauge("serve.slo_burn_short", model="m").set(4.0)
    n2 = sampler.sample(now=101.0)
    assert n == 2 and n2 == 2
    burn = sampler.history.range("serve.slo_burn_short")
    assert burn["serve.slo_burn_short{model=m}"] == [(100.0, 3.0),
                                                     (101.0, 4.0)]
    assert not sampler.history.range("plan.h2d_uploads")
    sampler.close()
    # the JSONL round-trips to the same observations
    loaded = MetricHistory.load(path)
    assert loaded.range("serve.slo_burn_short")[
        "serve.slo_burn_short{model=m}"][:2] == [(100.0, 3.0),
                                                 (101.0, 4.0)]
    assert "train.service.restarts" in {
        k.split("{")[0] for k in loaded.keys()}


def test_timeseries_module_enable_disable_threads():
    obs_ts.enable(interval_s=30.0)
    assert obs_ts.enabled()
    assert any(t.name == "TimeSeriesSampler"
               for t in threading.enumerate())
    obs_ts.disable()
    assert_no_leaked_threads("TimeSeriesSampler")
    assert obs_ts.range_("serve.slo_burn_short") == {}


# ---------------------------------------------------------------------------
# supervisor fleet aggregation (unit: beacons in, train.fleet.* out)
# ---------------------------------------------------------------------------


def test_supervisor_publishes_fleet_aggregates_from_beacons(tmp_path):
    from mmlspark_tpu.train.service import (
        RecoveryPolicy, ServiceConfig, TrainSupervisor, _Worker,
    )

    class _P:  # a poll-able stand-in for subprocess.Popen
        pid = 1

        def poll(self):
            return None

    obs.enable()
    sup = TrainSupervisor(ServiceConfig(
        cmd=("true",), service_dir=str(tmp_path),
        policy=RecoveryPolicy()))
    w0, w1 = _Worker.__new__(_Worker), _Worker.__new__(_Worker)
    for i, w in enumerate((w0, w1)):
        w.rank, w.proc, w.counter_last = i, _P(), {}
        w.straggler_hits = 0
    beacons = {
        0: {"progress": 7, "stragglers": 2,
            "host_step_ms": {"0": 5.0, "1": 40.0},
            "counters": [["train.steps", {}, 7.0]]},
        1: {"progress": 9, "stragglers": 2, "host_step_ms": {},
            "counters": [["train.steps", {}, 9.0]]},
    }
    agg = sup._fleet_aggregates(beacons)
    assert agg == {"workers": 2, "progress": 16,
                   "straggler_windows": 2,
                   "host_step_ms": {"0": 5.0, "1": 40.0}}
    sup._publish_fleet([w0, w1], beacons, agg)
    reg = obs.registry()
    assert reg.value("train.fleet.workers") == 2
    assert reg.value("train.fleet.progress") == 16
    assert reg.value("train.fleet.straggler_windows") == 2
    assert reg.value("train.fleet.host_step_ms", host="1") == 40.0
    assert reg.value("train.fleet.steps", rank=0) == 7
    assert reg.value("train.fleet.steps", rank=1) == 9
    # second poll: only the DELTA accumulates
    beacons[0]["counters"] = [["train.steps", {}, 12.0]]
    sup._publish_fleet([w0, w1], beacons,
                       sup._fleet_aggregates(beacons))
    assert reg.value("train.fleet.steps", rank=0) == 12
    # a backward value (worker restart, fresh registry) re-accumulates
    beacons[0]["counters"] = [["train.steps", {}, 3.0]]
    sup._publish_fleet([w0, w1], beacons,
                       sup._fleet_aggregates(beacons))
    assert reg.value("train.fleet.steps", rank=0) == 15
    # terminal beacons (the final read after a clean completion) fold
    # in counter deltas but are NOT live workers — the liveness gauge
    # must not report dead workers on an idle supervisor
    for b in beacons.values():
        b["status"] = "exited"
    agg = sup._fleet_aggregates(beacons)
    assert agg["workers"] == 0 and agg["progress"] == 16
    sup._publish_fleet([w0, w1], beacons, agg)
    assert reg.value("train.fleet.workers") == 0


# ---------------------------------------------------------------------------
# the serve /fleet endpoint
# ---------------------------------------------------------------------------


def test_http_fleet_endpoint_json_prometheus_and_404(tmp_path):
    import urllib.error
    import urllib.request

    from mmlspark_tpu.serve import ModelServer, ServeConfig
    from mmlspark_tpu.serve.http import start_http_server

    server = ModelServer(ServeConfig(buckets=(1,)))
    httpd = start_http_server(server, host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    try:
        # no fleet dir configured -> typed 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet", timeout=10)
        assert ei.value.code == 404
        assert json.loads(ei.value.read())["error"] == \
            "FleetNotConfigured"

        obs.enable()
        obs.registry().counter("serve.test_total").add(4)
        exp = obs_fleet.enable(str(tmp_path / "fleet"), interval_s=30.0)
        exp.snapshot("manual")
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10).read())
        assert body["fleet"] == 1
        assert len(body["processes"]) == 1
        assert body["metrics"]["counters"]["serve.test_total"] == 4
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fleet",
            headers={"Accept": "text/plain"})
        text = urllib.request.urlopen(req, timeout=10).read().decode()
        assert "# HELP serve_test_total" in text
        assert "# TYPE serve_test_total counter" in text
        assert "serve_test_total 4" in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.close()


# ---------------------------------------------------------------------------
# tools/fleet.py CLI
# ---------------------------------------------------------------------------


def _load_fleet_cli():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mmlspark_tools_fleet",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "fleet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_cli_status_metrics_trace_watch(tmp_path, capsys):
    cli = _load_fleet_cli()
    d = str(tmp_path / "fleet")
    obs.enable()
    obs.registry().counter("serve.cli_total").add(2)
    with obs.span("train/step", "train"):
        time.sleep(0.001)
    exp = obs_fleet.enable(d, interval_s=30.0)
    exp.snapshot("manual")

    assert cli.main(["status", d]) == 0
    out = capsys.readouterr().out
    assert "1 process(es)" in out and "manual" in out

    assert cli.main(["metrics", d]) == 0
    body = json.loads(capsys.readouterr().out)
    assert body["metrics"]["counters"]["serve.cli_total"] == 2

    assert cli.main(["metrics", d, "--prom"]) == 0
    assert "# TYPE serve_cli_total counter" in capsys.readouterr().out

    trace_out = str(tmp_path / "fleet_trace.json")
    assert cli.main(["trace", d, "--out", trace_out]) == 0
    line = json.loads(capsys.readouterr().out)
    assert line["trace"] == trace_out and line["unaligned"] == []
    assert os.path.exists(trace_out)

    assert cli.main(["watch", d, "--interval", "0.01",
                     "--iterations", "2"]) == 0
    assert capsys.readouterr().out.count("1 process(es)") == 2

    # missing dir: one typed line, exit 2
    assert cli.main(["metrics", str(tmp_path / "nope")]) == 2
    assert "fleet:" in capsys.readouterr().err
    # an existing-but-empty dir: status fails typed too (a deploy gate
    # scripting `status && ...` must not pass on an empty fleet);
    # watch stays tolerant — waiting for the first export is its job
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert cli.main(["status", empty]) == 2
    assert "no process snapshot" in capsys.readouterr().err
    assert cli.main(["watch", empty, "--interval", "0.01",
                     "--iterations", "1"]) == 0
    assert "0 process(es)" in capsys.readouterr().out
    # watch also tolerates a NOT-YET-CREATED dir (exporters create it
    # lazily on enable — waiting for the first process is watch's job)
    assert cli.main(["watch", str(tmp_path / "later"), "--interval",
                     "0.01", "--iterations", "2"]) == 0
    assert capsys.readouterr().out.count("not created yet") == 2

"""The symbolic SPMD verifier (analysis/spmd.py + analysis/collectives.py).

Three tiers of evidence, all on the 8-virtual-device CPU mesh:

* **predictions = observations** — the verifier's collective schedule
  for every ``parallel/`` entry point, a fused plan segment, and the
  Trainer's jitted step on the MULTICHIP dryrun meshes (dp×pp pipelined
  ViT, dp×ep MoE tagger — the configs MULTICHIP_r05.json trains) equals
  the StableHLO collective ops of the actually-lowered program;
* **the pre-fix implementations are flagged** — fixtures reproducing
  the two seed-failing bugs (per-source-shard MoE capacity slots; the
  trace-time-stacked pipeline params fed to shard_map unpinned) draw
  SPMD104 / SPMD103 findings, while the fixed modules verify clean;
* **each rule fires on its fixture** — SPMD101–SPMD203 semantic checks
  and the JX201–JX204 AST lint rules, with clean counterparts.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from jax.sharding import PartitionSpec as P  # noqa: E402

from mmlspark_tpu.analysis.collectives import (  # noqa: E402
    check_fence_discipline, compare_schedules, extract_schedule,
    lowered_collective_counts,
)
from mmlspark_tpu.analysis.spmd import (  # noqa: E402
    ENTRY_POINTS, ShardState, audit_plan_spmd, check_divisibility,
    verify_entry_point, verify_function,
)
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh, shard_map  # noqa: E402

from lint_jax import lint_source  # noqa: E402


# ---- predictions = observations: the parallel layer ----

EXPECTED_SCHEDULES = {
    # (kind, axes) sequences — the declared collective contract of each
    # parallel module; a change here is a change to the wire protocol
    "moe_apply": [("all_gather", ("ep",)), ("psum_scatter", ("ep",)),
                  ("all_gather", ("ep",)),
                  ("psum", ("dp", "fsdp", "ep")),
                  ("psum", ("dp", "fsdp", "ep")),
                  ("psum", ("dp", "fsdp", "ep"))],
    "pipeline_apply": [("ppermute", ("pp",)), ("psum", ("pp",))],
    "ring_attention": [("ppermute", ("sp",))] * 9,
    "ulysses_attention": [("all_to_all", ("sp",))] * 3
                         + [("all_gather", ("sp",)),
                            ("all_to_all", ("sp",))],
    # the sharded serve entries: a DP replica's segment and the
    # GSPMD-tp-sharded segment are manual-collective-FREE by contract
    # (XLA-inserted resharding only); the pipelined pp serve segment
    # speaks the pipeline_apply wire protocol over pp alone
    "serve_dp_replica": [],
    "serve_tp_segment": [],
    "serve_pp_segment": [("ppermute", ("pp",)), ("psum", ("pp",))],
    # the int8w+bf16 quantized serve segments: the precision pass
    # (dequant + activation casts) is pure elementwise math — it must
    # introduce NO collectives on a replica nor under GSPMD-tp
    "serve_int8w_replica": [],
    "serve_int8w_tp": [],
    # the continuous-batching decode step: a DP replica owns its slot
    # table and KV cache, so its token loop is manual-collective-free —
    # a collective here would lockstep independent replicas' decodes
    "serve_decode_replica": [],
}

# shard_map sites per entry point: 1 for every manual-collective module,
# 0 for the GSPMD-only serve segments (no shard_map at all)
EXPECTED_SITES = {"serve_dp_replica": 0, "serve_tp_segment": 0,
                  "serve_int8w_replica": 0, "serve_int8w_tp": 0,
                  "serve_decode_replica": 0}


@pytest.mark.parametrize("ep", ENTRY_POINTS, ids=lambda e: e.name)
def test_entry_point_verifies_clean_and_matches_lowered_program(ep):
    report = verify_entry_point(ep)
    assert report.findings == [], "\n".join(str(f) for f in
                                            report.findings)
    assert len(report.sites) == EXPECTED_SITES.get(ep.name, 1)
    got = [(op.kind, op.axes) for op in report.schedule.ops]
    assert got == EXPECTED_SCHEDULES[ep.name], got
    # the contract: the module communicates only over its declared axes
    assert report.schedule.axes_used() <= set(ep.expect_axes)
    # predicted = observed: the jaxpr schedule equals the StableHLO
    # collectives of the lowered program, op for op
    mesh = make_mesh(ep.mesh_spec)
    fn, args = ep.build(mesh)
    observed = lowered_collective_counts(jax.jit(fn).lower(*args).as_text())
    assert report.schedule.stablehlo_counts() == observed


def test_cross_host_agreement_of_entry_point_schedules():
    """Two independent traces of the same entry point must fingerprint
    identically — the property that keeps multi-host processes in
    collective lockstep."""
    for ep in ENTRY_POINTS:
        a = verify_entry_point(ep).schedule
        b = verify_entry_point(ep).schedule
        assert compare_schedules(a, b, ep.name) == []


# ---- predictions = observations: the fused plan segment ----

def _canonical_pipeline():
    from perf_smoke import canonical_pipeline
    return canonical_pipeline()


def test_fused_plan_segment_is_collective_free_and_dp_divisible():
    from mmlspark_tpu.core import plan

    pm, table, n, minibatch = _canonical_pipeline()
    audit = audit_plan_spmd(pm.stages,
                            lambda col: plan._entry_meta(table, col),
                            n_rows=n)
    assert audit.ok, audit.format()
    assert len(audit.segments) == 1
    seg = audit.segments[0]
    assert seg.stages == ["ImageTransformer", "UnrollImage", "JaxModel"]
    assert seg.schedule.ops == []          # inference: XLA-inserted only
    assert seg.minibatches == -(-n // minibatch)
    assert seg.entry_state.dims[0] == ("dp", "fsdp")
    # observed: the segment's composite lowers with zero manual
    # collectives too
    pseg = plan.collect_segment(pm.stages, 0,
                                lambda col: plan._entry_meta(table, col))
    fn, dev_params, _target, _dp = plan._compile_segment(pseg)
    entry = jax.ShapeDtypeStruct(
        (16,) + tuple(pseg.entry_meta.shape), pseg.entry_meta.dtype)
    low = fn.lower(dev_params, entry).as_text()
    assert lowered_collective_counts(low) == {}


def test_lone_model_stage_audits_as_one_segment():
    """Serving dispatches even a single JaxModel through the fused path
    (transform_async, min_stages=1), so the multi-chip audit must cover
    a one-stage plan instead of silently reporting zero segments."""
    from mmlspark_tpu.core import plan
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.models.zoo import get_model

    jm = JaxModel(model=get_model("ConvNet_CIFAR10", widths=(8, 16),
                                  dense_width=32),
                  input_col="image", output_col="scores")
    table = DataTable({"image": [np.zeros(32 * 32 * 3, np.float32)]})
    audit = audit_plan_spmd([jm],
                            lambda col: plan._entry_meta(table, col),
                            n_rows=48)
    assert len(audit.segments) == 1, audit.format()
    assert audit.ok and audit.segments[0].schedule.ops == []


def test_stateful_decode_audit_pins_donation_safety():
    """audit_stateful_spmd on the REAL continuous-batching decode build
    (the same program serve/generate.py jits with donate_argnums=(0,)):
    collective-free AND donation-safe — the returned KV-cache subtree
    matches the input leaf-for-leaf, so XLA aliases the buffers in
    place. A step that shrinks the cache draws SPMD106: donation would
    silently degrade to a full cache copy per token."""
    from mmlspark_tpu.analysis.spmd import (audit_stateful_spmd,
                                            serve_decode_build)

    step, args = serve_decode_build(None)
    bufs, rest = args[0], args[1:]
    report = audit_stateful_spmd(step, bufs, rest, name="decode_step")
    assert report.findings == [], "\n".join(str(f) for f in
                                            report.findings)
    assert report.schedule.ops == []

    def shrinking(state, *a):
        new_state, nxt = step(state, *a)
        return {"k": new_state["k"][:2], "v": new_state["v"]}, nxt

    bad = audit_stateful_spmd(shrinking, bufs, rest, name="shrunk")
    assert [f.code for f in bad.findings] == ["SPMD106"]
    assert "donated" in bad.findings[0].message


# ---- predictions = observations: Trainer steps on the dryrun meshes ----

def _step_args(tr, input_shape, y_dtype=jnp.int64):
    state = tr.init_state(input_shape)
    bs = tr.cfg.batch_size
    return (state,
            jax.ShapeDtypeStruct((bs,) + tuple(input_shape), jnp.float32),
            jax.ShapeDtypeStruct((bs,), y_dtype),
            jax.ShapeDtypeStruct((bs,), jnp.float32))


def test_trainer_dp_pp_step_verifies_and_matches_lowered_program():
    """The dp×pp pipelined ViT step (the MULTICHIP_r05 dryrun config):
    clean under the verifier — including the commit_replicated pin on
    the trace-stacked layer params — with schedule = lowered program."""
    from mmlspark_tpu.models.vit import ViT
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    module = ViT(num_classes=4, patch=8, dim=32, depth=4, heads=4,
                 mlp_dim=64, dtype=jnp.float32, pipeline_microbatches=4)
    tr = Trainer(module, TrainConfig(batch_size=16,
                                     mesh_spec={"dp": 2, "pp": 4}))
    args = _step_args(tr, (16, 16, 3))
    report = verify_function(tr.step_masked, *args, name="vit_dp_pp_step")
    assert report.findings == [], "\n".join(str(f) for f in
                                            report.findings)
    assert len(report.sites) == 2          # forward + its transpose
    counts = report.schedule.counts()
    assert counts["ppermute"] == 2         # fwd ring + reversed bwd ring
    observed = lowered_collective_counts(
        tr.step_masked.lower(*args).as_text())
    assert report.schedule.stablehlo_counts() == observed
    # two traces agree — the multi-host lockstep pin
    again = verify_function(tr.step_masked, *args, name="vit_dp_pp_step")
    assert compare_schedules(report.schedule, again.schedule) == []


def test_trainer_dp_ep_step_verifies_and_matches_lowered_program():
    """The dp×ep MoE tagger step (the MULTICHIP_r05 dryrun config):
    clean — including the capacity-dispatch count-exchange rule the old
    per-shard slot arithmetic violates — with schedule = lowered."""
    from mmlspark_tpu.models.sequence import TransformerTagger
    from mmlspark_tpu.train.loop import TrainConfig, Trainer

    module = TransformerTagger(vocab_size=64, embed_dim=16, num_heads=2,
                               num_layers=1, mlp_dim=32, num_tags=4,
                               max_len=16, moe_experts=4, pad_token_id=0,
                               dtype=jnp.float32)
    tr = Trainer(module, TrainConfig(batch_size=16,
                                     mesh_spec={"dp": 2, "ep": 2}))
    state = tr.init_state((16,))
    args = (state, jax.ShapeDtypeStruct((16, 16), jnp.int32),
            jax.ShapeDtypeStruct((16, 16), jnp.int64),
            jax.ShapeDtypeStruct((16,), jnp.float32))
    report = verify_function(tr.step_masked, *args, name="tagger_dp_ep",
                             capacity_dispatch=True)
    assert report.findings == [], "\n".join(str(f) for f in
                                            report.findings)
    kinds = {op.kind for op in report.schedule.ops}
    assert {"all_gather", "psum_scatter"} <= kinds
    observed = lowered_collective_counts(
        tr.step_masked.lower(*args).as_text())
    assert report.schedule.stablehlo_counts() == observed


# ---- the pre-fix implementations are statically flagged ----

def _old_moe_body_fn(mesh):
    """The pre-fix MoE dispatch: capacity slots from a LOCAL cumsum,
    all_to_all regrouping, no cross-shard count exchange — a token's
    survival depended on which shard its padding landed on."""
    E, C, ep = 8, 2, mesh.shape["ep"]

    def body(p, xl):
        d = xl.shape[-1]
        onehot = jax.nn.one_hot(jnp.argmax(xl @ p["gate"], -1), E,
                                dtype=jnp.int32)
        pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot
        keep = (jnp.sum(pos, axis=-1) < C).astype(jnp.float32)
        slots = jnp.einsum("ne,nd->ed",
                           onehot.astype(jnp.float32) * keep[:, None], xl)
        slots = jax.lax.all_to_all(
            slots[:, None, :].reshape(ep, E // ep, d), "ep",
            split_axis=0, concat_axis=0, tiled=False)
        return jnp.broadcast_to(slots.reshape(E, d).sum(0), xl.shape)

    def fn(p, xs):
        return shard_map(body, mesh=mesh,
                         in_specs=({"gate": P()}, P(("dp", "fsdp", "ep"))),
                         out_specs=P(("dp", "fsdp", "ep")),
                         check_vma=False)(p, xs)

    return fn


def test_pre_fix_moe_capacity_is_flagged_fixed_is_clean():
    mesh = make_mesh(MeshSpec(dp=1, ep=4))
    fn = _old_moe_body_fn(mesh)
    p = {"gate": jax.ShapeDtypeStruct((16, 8), jnp.float32)}
    xs = jax.ShapeDtypeStruct((64, 16), jnp.float32)
    report = verify_function(fn, p, xs, name="old_moe",
                             capacity_dispatch=True)
    codes = [f.code for f in report.findings]
    assert "SPMD104" in codes, codes
    assert "count exchange" in \
        next(f for f in report.findings if f.code == "SPMD104").message
    # the fixed module's dispatch passes the same rule (entry-point test
    # asserts zero findings with capacity_dispatch=True)
    fixed = verify_entry_point(ENTRY_POINTS[0])   # moe_apply
    assert fixed.findings == []


def test_pre_fix_pipeline_stacking_is_flagged_fixed_is_clean():
    """The dp×pp seed bug: layer params stacked at trace time and fed to
    shard_map with dp unmentioned in their in_spec hit the GSPMD
    full-to-shard edge (each shard sees dp-extent × the true value).
    The verifier flags the unpinned operand; the fixed pipeline_apply
    (commit_replicated) verifies clean."""
    mesh = make_mesh(MeshSpec(dp=2, pp=4))

    def old_pipeline(per_layer, x):
        stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls),
                                         *per_layer)

        def body(st, xl):
            def blk(h, layer):
                return h + jnp.tanh(h @ layer["w"]), None
            h, _ = jax.lax.scan(blk, xl, st)
            h = jnp.where(jax.lax.axis_index("pp") == 3, h, 0.0)
            return jax.lax.psum(h, "pp")

        return shard_map(body, mesh=mesh,
                         in_specs=(P("pp"), P(None, ("dp",))),
                         out_specs=P(None, ("dp",)),
                         check_vma=False)(stacked, x)

    layers = [{"w": jax.ShapeDtypeStruct((16, 16), jnp.float32)}
              for _ in range(8)]
    x = jax.ShapeDtypeStruct((4, 16, 16), jnp.float32)
    report = verify_function(old_pipeline, layers, x, name="old_pipeline")
    codes = [f.code for f in report.findings]
    assert codes == ["SPMD103"], codes
    assert "UNREDUCED PARTIAL SUM" in report.findings[0].message
    # the fixed pipeline_apply — same trace-time stacking, now pinned —
    # is clean (ENTRY_POINTS builds it exactly that way)
    fixed = verify_entry_point(ENTRY_POINTS[1])   # pipeline_apply
    assert fixed.findings == []


# ---- each semantic rule fires on its fixture ----

@pytest.fixture(scope="module")
def mesh_dp_pp():
    return make_mesh(MeshSpec(dp=2, pp=4))


def test_spmd201_collective_under_data_dependent_cond(mesh_dp_pp):
    def fn(x, pred):
        def body(v, pr):
            return jax.lax.cond(pr[0] > 0,
                                lambda u: jax.lax.psum(u, "pp"),
                                lambda u: u, v)
        return shard_map(body, mesh=mesh_dp_pp, in_specs=(P(), P()),
                         out_specs=P(), check_vma=False)(x, pred)

    report = verify_function(fn, jax.ShapeDtypeStruct((4,), jnp.float32),
                             jax.ShapeDtypeStruct((1,), jnp.int32),
                             name="cond_coll")
    assert [f.code for f in report.findings] == ["SPMD201"]
    op = report.schedule.conditional_ops()[0]
    assert op.kind == "psum"
    assert any(c.startswith("cond.branch") for c in op.context)


def test_spmd202_divergent_schedules(mesh_dp_pp):
    def mk(coll):
        def fn(x):
            return shard_map(lambda v: coll(v, "pp"), mesh=mesh_dp_pp,
                             in_specs=(P(),), out_specs=P(),
                             check_vma=False)(x)
        return fn

    x = jax.ShapeDtypeStruct((4,), jnp.float32)
    a = extract_schedule(mk(jax.lax.psum), x)
    b = extract_schedule(mk(jax.lax.pmax), x)
    assert [f.code for f in compare_schedules(a, b)] == ["SPMD202"]
    assert compare_schedules(a, a) == []


def test_spmd203_fence_discipline():
    bad = ("def run(loader, blocks):\n"
           "    for block in blocks:\n"
           "        counts = multihost_utils.process_allgather(block)\n"
           "        step(counts)\n")
    assert [f.code for f in check_fence_discipline(bad)] == ["SPMD203"]
    good = ("def run(loader, blocks):\n"
            "    for block in blocks:\n"
            "        loader.drain_barrier()\n"
            "        counts = multihost_utils.process_allgather(block)\n"
            "        step(counts)\n")
    assert check_fence_discipline(good) == []


def test_spmd103_partial_sum_escape_from_body(mesh_dp_pp):
    """The replication-claim check check_vma=False turns off, done
    statically: an output varying over dp escaping as replicated."""
    def fn(x):
        def body(xl):
            return xl.sum(0, keepdims=True) \
                * (jax.lax.axis_index("dp") + 1)
        return shard_map(body, mesh=mesh_dp_pp, in_specs=(P(("dp",)),),
                         out_specs=P(), check_vma=False)(x)

    report = verify_function(fn, jax.ShapeDtypeStruct((8,), jnp.float32),
                             name="escape")
    assert [f.code for f in report.findings] == ["SPMD103"]
    # the out state reports the partial axes
    assert report.sites[0].out_states[0].partial == frozenset({"dp"})
    # reducing before returning clears it
    def fixed(x):
        def body(xl):
            return jax.lax.psum(xl.sum(0, keepdims=True), "dp")
        return shard_map(body, mesh=mesh_dp_pp, in_specs=(P(("dp",)),),
                         out_specs=P(), check_vma=False)(x)

    assert verify_function(fixed, jax.ShapeDtypeStruct((8,), jnp.float32),
                           name="fixed").findings == []


def test_spmd101_contract_violation(mesh_dp_pp):
    def fn(x):
        return shard_map(lambda v: jax.lax.psum(v, "dp"),
                         mesh=mesh_dp_pp, in_specs=(P(),), out_specs=P(),
                         check_vma=False)(x)

    report = verify_function(fn, jax.ShapeDtypeStruct((4,), jnp.float32),
                             name="contract", expect_axes=("pp",))
    assert [f.code for f in report.findings] == ["SPMD101"]


def test_spmd104_divisibility():
    state = ShardState((("ep",), ()))
    finds = check_divisibility(state, (10, 3), {"ep": 4}, "x")
    assert [f.code for f in finds] == ["SPMD104"]
    assert check_divisibility(state, (12, 3), {"ep": 4}, "x") == []


def test_obs_counters_register_through_the_substrate(mesh_dp_pp):
    """Verification work records through mmlspark_tpu/obs — the one
    telemetry substrate — when tracing is on, and not otherwise."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.obs.metrics import registry

    def fn(x):
        return shard_map(lambda v: jax.lax.psum(v, "pp"),
                         mesh=mesh_dp_pp, in_specs=(P(),), out_specs=P(),
                         check_vma=False)(x)

    x = jax.ShapeDtypeStruct((4,), jnp.float32)
    registry().reset()
    obs.enable()
    try:
        verify_function(fn, x, name="probe")
        counters = registry().snapshot()["counters"]
        spans = [s.name for s in obs.captured()]
    finally:
        obs.disable()
        obs.clear()
        registry().reset()
    assert counters.get("analysis.spmd.functions_verified") == 1
    assert counters.get("analysis.spmd.findings", 0) == 0
    assert "spmd/verify" in spans


# ---- the JX201–JX204 lint rules: fixture modules ----

FIXTURE_JX201 = '''
import jax

def step(v, pred):
    def reduce_all(u):
        return jax.lax.psum(u, "pp")
    def keep(u):
        return u
    return jax.lax.cond(pred, reduce_all, keep, v)
'''

FIXTURE_JX202 = '''
import jax

def body(v):
    i = jax.lax.axis_index("batch")
    return jax.lax.psum(v, "model") + i
'''

FIXTURE_JX203 = '''
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from mmlspark_tpu.parallel.mesh import shard_map

def apply(params, x, mesh):
    def body(p, xl):
        return (xl @ p).sum(0, keepdims=True)
    return shard_map(body, mesh=mesh, in_specs=(P("pp"), P(None, ("dp",))),
                     out_specs=P(None, ("dp",)), check_vma=False)(params, x)
'''

FIXTURE_JX204 = '''
import jax, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from mmlspark_tpu.parallel.mesh import shard_map

def dispatch(params, x, mesh):
    def body(p, xl):
        onehot = jax.nn.one_hot(jnp.argmax(xl @ p, -1), 8, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) - onehot
        slots = jnp.einsum("ne,nd->ed", onehot.astype(jnp.float32), xl)
        slots = jax.lax.all_to_all(slots.reshape(4, 2, -1), "ep", 0, 0)
        return slots.reshape(xl.shape[0], -1) + pos.sum()
    return shard_map(body, mesh=mesh, in_specs=(P(), P(("ep",))),
                     out_specs=P(("ep",)), check_vma=False)(params, x)
'''


def test_jx201_collective_in_cond_branch():
    assert [f.rule for f in lint_source(FIXTURE_JX201)] == ["JX201"]
    clean = FIXTURE_JX201.replace(
        "return jax.lax.cond(pred, reduce_all, keep, v)",
        "return jax.lax.psum(jax.lax.cond(pred, keep, keep, v), \"pp\")")
    assert [f.rule for f in lint_source(clean)] == []


def test_jx202_non_canonical_axis_names():
    findings = lint_source(FIXTURE_JX202)
    assert [f.rule for f in findings] == ["JX202", "JX202"]
    canon = FIXTURE_JX202.replace('"batch"', '"dp"').replace(
        '"model"', '"tp"')
    assert lint_source(canon) == []


def test_jx203_unreduced_axis_escape():
    findings = lint_source(FIXTURE_JX203)
    assert [f.rule for f in findings] == ["JX203"]
    assert "'pp'" in findings[0].message
    fixed = FIXTURE_JX203.replace(
        "return (xl @ p).sum(0, keepdims=True)",
        "return jax.lax.psum((xl @ p).sum(0, keepdims=True), \"pp\")")
    assert lint_source(fixed) == []


def test_jx204_per_shard_capacity_cumsum():
    findings = lint_source(FIXTURE_JX204)
    assert [f.rule for f in findings] == ["JX204"]
    fixed = FIXTURE_JX204.replace(
        "pos = jnp.cumsum(onehot, axis=0) - onehot",
        "counts = jax.lax.all_gather(onehot.sum(0), \"ep\")\n"
        "        pos = jnp.cumsum(onehot, axis=0) - onehot + counts.sum()")
    assert lint_source(fixed) == []


def test_jx2xx_pragma_suppresses():
    src = FIXTURE_JX202.replace(
        'i = jax.lax.axis_index("batch")',
        'i = jax.lax.axis_index("batch")  # lint-jax: allow(JX202)')
    assert [f.rule for f in lint_source(src)] == ["JX202"]  # the psum one


def test_parallel_modules_pass_their_own_lint():
    """The real (fixed) parallel sources pass JX201–JX204 — the moe fix
    is exactly what turns JX204 off (all_gather of the routed counts)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for mod in ("moe", "pipeline", "ring_attention", "mesh"):
        path = os.path.join(repo, "mmlspark_tpu", "parallel", f"{mod}.py")
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        findings = [f for f in lint_source(src, path)
                    if f.rule.startswith("JX2")]
        assert findings == [], findings

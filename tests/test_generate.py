"""Autoregressive token serving (serve/generate.py, round 18).

The engine's one correctness anchor, pinned from every surface: a
request's token stream is **bit-identical** whether it decodes alone
(:meth:`GenerateBatcher.oneshot` — fresh buffers, synchronous) or packed
into the continuously-batched slot plane with churning neighbors — the
row-independence property that makes iteration-level scheduling safe.
Around it, the operational semantics: admission validation is typed and
load-fast, a churn cancel delivers a *prefix* (never a wrong token), the
compiled-program budget stays ≤ ``len(prefill_buckets) + 1``, the
:class:`SlotTable` raises on ownership violations instead of corrupting
the cache, shutdown resolves every admitted stream, and the server /
Client / HTTP ``:generate`` surfaces all speak the same contract."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from mmlspark_tpu.models.sequence import TransformerTagger
from mmlspark_tpu.serve import (
    THREAD_PREFIX, BadRequest, Client, ModelLoadError, ModelNotFound,
    ModelServer, Overloaded, ServeConfig, ServerClosed, faults,
)
from mmlspark_tpu.serve.config import GenerateConfig
from mmlspark_tpu.serve.faults import FaultPlan, FaultSpec
from mmlspark_tpu.serve.generate import (
    GenerateBatcher, GenerateRequest, SlotTable, TokenStream,
)

VOCAB = 32


def lm_module():
    return TransformerTagger(vocab_size=VOCAB, embed_dim=16, num_heads=2,
                             num_layers=2, mlp_dim=32, num_tags=VOCAB,
                             max_len=32, causal=True)


def small_cfg(**kw):
    base = dict(slots=4, t_max=32, prefill_buckets=(4, 8),
                prefill_rows=2, max_new_tokens=6, max_queue=32)
    base.update(kw)
    return GenerateConfig(**base)


def prompts(n, seed=0, lo=2, hi=8):
    r = np.random.default_rng(seed)
    return [[int(t) for t in r.integers(1, VOCAB, int(r.integers(lo, hi + 1)))]
            for _ in range(n)]


@pytest.fixture(scope="module")
def lm():
    model = lm_module()
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def engine(lm):
    model, params = lm
    eng = GenerateBatcher("lm", model, params, config=small_cfg())
    yield eng
    eng.close()


def slow_decode_attention(hold_s=0.004):
    """decode_attention with a host hold riding the device computation —
    makes slot/queue occupancy deterministic for the admission tests."""
    import time

    import jax as _jax

    from mmlspark_tpu.ops.pallas.attention import decode_attention

    def hold(x):
        time.sleep(hold_s)
        return x

    def fn(q, k_layer, v_layer, keep):
        out = decode_attention(q, k_layer, v_layer, kv_mask=keep)
        return _jax.pure_callback(
            hold, _jax.ShapeDtypeStruct(out.shape, out.dtype), out)

    return fn


# ---- the bit-identity anchor ----


class TestBitIdentity:
    def test_batched_streams_equal_oneshot(self, engine):
        ps = prompts(10, seed=1)
        refs = [engine.oneshot(p, max_new_tokens=5) for p in ps]
        streams = [engine.submit(p, max_new_tokens=5) for p in ps]
        got = [s.result(timeout=60) for s in streams]
        assert got == refs
        assert not any(s.cancelled for s in streams)

    def test_churn_cancel_delivers_a_prefix(self, engine):
        ps = prompts(8, seed=2)
        refs = [engine.oneshot(p, max_new_tokens=6) for p in ps]
        plan = FaultPlan([FaultSpec("generate_cancel", model="lm",
                                    after=2, times=2)], seed=3)
        with faults.inject(plan):
            streams = [engine.submit(p, max_new_tokens=6) for p in ps]
            got = [s.result(timeout=60) for s in streams]
        cancelled = [i for i, s in enumerate(streams) if s.cancelled]
        assert cancelled, "churn plan never fired"
        for i, (toks, ref) in enumerate(zip(got, refs)):
            if i in cancelled:
                assert 1 <= len(toks) < len(ref)
                assert toks == ref[:len(toks)]  # prefix, never wrong
            else:
                assert toks == ref

    def test_program_budget_holds_after_mixed_traffic(self, engine):
        # both buckets and the decode loop have run by now: the engine's
        # whole compiled footprint is the ladder + ONE decode program
        budget = len(engine.config.prefill_buckets) + 1
        assert engine.compiled_programs() <= budget

    def test_eos_token_stops_stream_and_oneshot_alike(self, lm):
        model, params = lm
        probe = GenerateBatcher("probe", model, params,
                                config=small_cfg())
        # untrained greedy decode often locks onto one token — probe a
        # few prompts for a run that visits a second one
        try:
            p = free_run = eos = None
            for seed in range(4, 24):
                cand = prompts(1, seed=seed)[0]
                run = probe.oneshot(cand, max_new_tokens=6)
                if any(t != run[0] for t in run[1:]):
                    p, free_run = cand, run
                    break
        finally:
            probe.close()
        assert p is not None, "no probe prompt produced 2 distinct tokens"
        # greedy decode is deterministic: the first token that differs
        # from the opener WILL reappear at the same step under eos
        # gating, so the truncation point is known in advance
        eos = next(t for t in free_run[1:] if t != free_run[0])
        stop = free_run.index(eos)
        eng = GenerateBatcher("eos", model, params,
                              config=small_cfg(eos_token=eos))
        try:
            ref = eng.oneshot(p, max_new_tokens=6)
            got = eng.submit(p, max_new_tokens=6).result(timeout=60)
        finally:
            eng.close()
        assert got == ref == free_run[:stop + 1]


# ---- admission validation (typed, before any device work) ----


class TestValidation:
    def test_empty_prompt_rejected(self, engine):
        with pytest.raises(BadRequest, match="empty prompt"):
            engine.submit([])

    def test_nonpositive_budget_rejected(self, engine):
        with pytest.raises(BadRequest, match="max_new_tokens"):
            engine.submit([1, 2], max_new_tokens=0)

    def test_prompt_beyond_ladder_rejected(self, engine):
        with pytest.raises(BadRequest, match="largest prefill bucket"):
            engine.submit(list(range(1, 10)))  # 9 > bucket 8

    def test_cache_horizon_overflow_rejected(self, engine):
        with pytest.raises(BadRequest, match="cache horizon"):
            engine.submit([1] * 8, max_new_tokens=25)  # 8 + 25 > 32

    def test_non_causal_model_rejected_at_construction(self):
        acausal = TransformerTagger(vocab_size=VOCAB, embed_dim=16,
                                    num_heads=2, num_layers=1, mlp_dim=32,
                                    num_tags=VOCAB, max_len=32)
        with pytest.raises(BadRequest, match="causal"):
            GenerateBatcher("acausal", acausal, params=None)

    def test_config_validation_is_load_fast(self):
        with pytest.raises(ValueError, match="t_max"):
            small_cfg(t_max=8)  # cannot hold bucket 8 + one token
        with pytest.raises(ValueError, match="slots"):
            small_cfg(slots=0)
        with pytest.raises(ModelLoadError):
            small_cfg(prefill_buckets=(8, 4))  # not ascending

    def test_overload_backpressure_then_abort_fails_typed(self, lm):
        # one slot + one queue seat, decode slowed to a crawl: the third
        # admission MUST bounce Overloaded; drain=False then fails the
        # outstanding streams with ServerClosed instead of stranding them
        model, params = lm
        eng = GenerateBatcher(
            "tiny", model, params,
            config=small_cfg(slots=1, max_queue=1),
            decode_attention_fn=slow_decode_attention())
        streams = []
        try:
            with pytest.raises(Overloaded):
                for _ in range(200):  # submits are µs, decode ~100ms:
                    #                   the one queue seat must fill
                    streams.append(eng.submit([1, 2], max_new_tokens=20))
                pytest.fail("queue never filled")  # pragma: no cover
        finally:
            eng.close(drain=False)
        assert streams
        failed = 0
        for stream in streams:
            try:
                stream.result(timeout=10)
            except ServerClosed:
                failed += 1
        assert failed >= 1, "abort close let every slow stream finish"


# ---- the slot ledger ----


class TestSlotTable:
    def mk_req(self):
        return GenerateRequest([1], 1, TokenStream("m"))

    def test_assign_release_and_free_accounting(self):
        st = SlotTable(2)
        a, b = self.mk_req(), self.mk_req()
        assert st.assign(a) == 0 and st.assign(b) == 1
        assert st.free == 0 and st.assign(self.mk_req()) is None
        st.release(a)
        assert st.free == 1 and st.owner(0) is None
        assert st.owner(1) is b

    def test_double_assignment_raises(self):
        st = SlotTable(2)
        req = self.mk_req()
        st.assign(req)
        with pytest.raises(RuntimeError, match="already owns"):
            st.assign(req)

    def test_release_by_non_owner_raises(self):
        st = SlotTable(1)
        req = self.mk_req()
        st.assign(req)
        st.release(req)
        with pytest.raises(RuntimeError, match="non-owner"):
            st.release(req)


# ---- stream + lifecycle semantics ----


class TestStreamAndLifecycle:
    def test_iteration_matches_result_and_terminates(self):
        ts = TokenStream("m")
        for t in (3, 1, 4):
            ts._push(t)
        ts._finish()
        assert list(ts) == [3, 1, 4] == ts.result()
        assert ts.done and not ts.cancelled

    def test_failed_stream_raises_from_both_surfaces(self):
        ts = TokenStream("m")
        ts._push(7)
        ts._fail(Overloaded("m", 1, 1))
        with pytest.raises(Overloaded):
            list(ts)
        with pytest.raises(Overloaded):
            ts.result()

    def test_result_timeout_is_typed(self):
        ts = TokenStream("m")
        with pytest.raises(TimeoutError, match="not terminal"):
            ts.result(timeout=0.05)

    def test_close_drains_everything_and_joins_the_thread(self, lm):
        model, params = lm
        eng = GenerateBatcher("drain", model, params, config=small_cfg())
        ps = prompts(6, seed=5)
        refs = [eng.oneshot(p) for p in ps]
        streams = [eng.submit(p) for p in ps]
        eng.close(drain=True)
        assert [s.result(timeout=1) for s in streams] == refs
        with pytest.raises(ServerClosed):
            eng.submit([1, 2])
        eng.close()  # idempotent
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith(f"{THREAD_PREFIX}[drain]")]
        assert leaked == []


# ---- the server / Client / HTTP surfaces ----


@pytest.fixture(scope="module")
def generate_server(lm):
    from mmlspark_tpu.serve.http import start_http_server
    model, params = lm
    server = ModelServer(ServeConfig())
    server.add_generator("lm", model, params, config=small_cfg())
    httpd = start_http_server(server, host="127.0.0.1", port=0)
    yield server, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()
    server.close()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req)


class TestServerSurfaces:
    def test_client_generate_blocking_and_streaming(self, generate_server):
        server, _base = generate_server
        client = Client(server)
        p = prompts(1, seed=6)[0]
        ref = server.generate_oneshot("lm", p, max_new_tokens=5)
        assert client.generate("lm", p, max_new_tokens=5) == ref
        stream = client.generate("lm", p, max_new_tokens=5, stream=True)
        assert list(stream) == ref

    def test_unknown_generator_and_name_collision(self, generate_server,
                                                  lm):
        server, _base = generate_server
        model, params = lm
        assert server.generators() == ["lm"]
        with pytest.raises(ModelNotFound):
            server.generate("nope", [1, 2])
        from mmlspark_tpu.models.bundle import ModelBundle
        from mmlspark_tpu.models.zoo import MLP
        module = MLP(features=(8,), num_outputs=4)
        mp = module.init(jax.random.PRNGKey(0),
                         np.zeros((1, 6), np.float32))["params"]
        server.add_model("mlp", ModelBundle(
            module=module, params=mp, input_spec=(6,),
            output_names=("features", "logits")))
        with pytest.raises(ModelLoadError, match="one name, one servable"):
            server.add_generator("mlp", model, params,
                                 config=small_cfg())

    def test_http_generate_blocking_matches_oneshot(self, generate_server):
        server, base = generate_server
        p = prompts(1, seed=7)[0]
        ref = server.generate_oneshot("lm", p, max_new_tokens=4)
        with _post(f"{base}/v1/models/lm:generate",
                   {"prompt": p, "max_new_tokens": 4,
                    "stream": False}) as resp:
            body = json.loads(resp.read())
        assert resp.status == 200
        assert body == {"model": "lm", "tokens": ref, "cancelled": False}

    def test_http_generate_streams_ndjson_per_token(self, generate_server):
        server, base = generate_server
        p = prompts(1, seed=8)[0]
        ref = server.generate_oneshot("lm", p, max_new_tokens=5)
        with _post(f"{base}/v1/models/lm:generate",
                   {"prompt": p, "max_new_tokens": 5}) as resp:
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            lines = [json.loads(ln) for ln in resp.read().splitlines()]
        *toks, done = lines
        assert [t["token"] for t in toks] == ref
        assert [t["index"] for t in toks] == list(range(len(ref)))
        assert done == {"done": True, "model": "lm", "tokens": ref,
                        "cancelled": False}

    def test_http_generate_rejects_malformed_bodies(self, generate_server):
        _server, base = generate_server
        for bad in ({}, {"prompt": []}, {"prompt": [1, True]},
                    {"prompt": "hi"}, {"prompt": [1], "max_new_tokens": "x"}):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(f"{base}/v1/models/lm:generate", bad)
            assert exc.value.code == 400, bad
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(f"{base}/v1/models/ghost:generate", {"prompt": [1]})
        assert exc.value.code == 404

"""ArrowBatchBridge — host-side batching in front of a compiled function.

The reference's hot inference loop ships partition rows one JNI FloatVector
element at a time into CNTK minibatches inside each executor JVM
(reference: cntk-model/src/main/scala/CNTKModel.scala:51-88 minibatch
iterator, :67-74 element-wise copies). The TPU-native bridge inverts the
topology: executors stay JVM-only and stream Arrow record batches to the
TPU host process, which

1. prefetches incoming batches on a reader thread (a bounded queue keeps
   memory flat and overlaps Arrow decode with device compute),
2. re-batches rows into **fixed-shape** padded device batches — one XLA
   program total, no per-shape recompiles,
3. runs the jit-compiled model (JAX async dispatch overlaps the host
   marshalling of batch i+1 with device compute of batch i), and
4. merges outputs back row-wise in input order, appended as a new column.

``make_map_in_arrow_fn`` packages the bridge as the exact callable Spark's
``DataFrame.mapInArrow`` expects, so the Spark-side integration is one
line; without Spark the same callable runs over any iterator of pyarrow
RecordBatches (the wire protocol is the contract, not the engine).

When ``transformer`` is a multi-stage ``PipelineModel`` (or any planner-
routed model), each chunk's transform goes through the pipeline planner
(core/plan.py): adjacent device-capable stages execute as ONE compiled
program per chunk — a single H2D upload and one async-windowed fetch per
minibatch instead of a device round-trip per stage — and the compiled
segment + device-resident params are cached on the transformer across
chunks, so streaming pays compile/upload once.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.jax_model import JaxModel, minibatches

_log = get_logger(__name__)

_SENTINEL = object()


class _ReaderError:
    """Carries a source-iterator exception across the prefetch queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class ArrowBatchBridge:
    """Streams Arrow record batches through a table→table transformer.

    ``transformer`` is any fitted pipeline stage (JaxModel,
    TrainedClassifierModel, PipelineModel, …); per-batch latency is recorded
    in ``self.latencies_ms`` for the p50 bridge metric.
    """

    def __init__(self, transformer: Any, prefetch: int = 4,
                 workers: int = 2):
        self.transformer = transformer
        self.prefetch = prefetch
        # workers > 1 overlaps host marshalling/Arrow codec of batch i+1
        # with the device round-trip of batch i (the GIL releases during
        # transfers); output order is preserved by completing futures
        # FIFO. Default 2 (round-5 verdict: overlap ON by default — the
        # serial path cost a full device round-trip per batch with the
        # overlap machinery sitting idle)
        # overlap chicken-switch for deployments that hit native
        # instability: MMLSPARK_TPU_BRIDGE_WORKERS=1 forces serial. It can
        # only LOWER the worker count (a fleet-wide cap must not re-widen
        # the codec/tunnel hazard on call sites that chose serial), and
        # garbage values are ignored with a warning rather than failing
        # every Spark python worker
        import os
        env_workers = os.environ.get("MMLSPARK_TPU_BRIDGE_WORKERS")
        self.workers = workers
        if env_workers:
            try:
                self.workers = min(workers, max(1, int(env_workers)))
            except ValueError:
                _log.warning(
                    "ignoring non-integer MMLSPARK_TPU_BRIDGE_WORKERS=%r",
                    env_workers)
        # serialize the Arrow codec across workers. This removes
        # codec↔codec concurrency and NARROWS (not eliminates) the
        # historical codec↔tunnel hazard window (see stream_table's note):
        # a worker's codec can still run while another worker's transform
        # drives the device link — fully excluding that would serialize
        # transform too and forfeit the overlap that pays (round-trip of
        # batch i under the wait of batch i+1). Empirically the 2-worker
        # default is clean across the bench (16-min tunnel runs), the
        # multihost scoring e2e, and the bridge suites; the env switch
        # above is the fallback if a deployment disagrees
        self._codec_lock = threading.Lock()
        self.latencies_ms: list[float] = []
        # per-batch marshal (Arrow→table + table→Arrow codec) vs score
        # (transform: coerce + device round-trip) decomposition, so the
        # p50 self-attributes: through a remote-device tunnel, score_ms
        # ~= the fetch RTT floor and marshal_ms is the host-side cost
        self.marshal_ms: list[float] = []
        self.score_ms: list[float] = []

    def _reader(self, source: Iterable, q: "queue.Queue") -> None:
        # a mid-stream source failure must reach the consumer as the original
        # exception, not as a clean end-of-stream (silent truncation of
        # scored output in the Spark offload path)
        try:
            for item in source:
                q.put(item)
        except BaseException as exc:  # noqa: BLE001 — re-raised in process()
            q.put(_ReaderError(exc))
        finally:
            q.put(_SENTINEL)

    def _score_one(self, item: Any) -> Any:
        t0 = time.perf_counter()
        with self._codec_lock:
            table = DataTable.from_arrow(item)
        t1 = time.perf_counter()
        out = self.transformer.transform(table)
        t2 = time.perf_counter()
        with self._codec_lock:
            arrow_out = out.to_arrow()
        t3 = time.perf_counter()
        self.marshal_ms.append(((t1 - t0) + (t3 - t2)) * 1e3)
        self.score_ms.append((t2 - t1) * 1e3)
        self.latencies_ms.append((t3 - t0) * 1e3)
        return arrow_out

    def process(self, batches: Iterable) -> Iterator:
        """RecordBatch iterator → RecordBatch iterator (order-preserving)."""
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        t = threading.Thread(target=self._reader, args=(batches, q),
                             daemon=True)
        t.start()
        if self.workers <= 1:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, _ReaderError):
                    raise item.exc
                for rb in self._score_one(item).to_batches():
                    yield rb
            return
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        pending: "deque" = deque()
        err: BaseException | None = None
        done = False
        with ThreadPoolExecutor(max_workers=self.workers) as ex:
            while True:
                while not done and len(pending) <= self.workers:
                    item = q.get()
                    if item is _SENTINEL:
                        done = True
                    elif isinstance(item, _ReaderError):
                        done, err = True, item.exc
                    else:
                        pending.append(ex.submit(self._score_one, item))
                if not pending:
                    break
                for rb in pending.popleft().result().to_batches():
                    yield rb
        if err is not None:
            raise err

    def p50_latency_ms(self) -> float | None:
        if not self.latencies_ms:
            return None
        return float(np.percentile(self.latencies_ms, 50))

    def p50_decomposition(self) -> dict[str, float] | None:
        """p50 split of the per-batch latency: ``marshal_ms`` (Arrow codec
        both ways) vs ``score_ms`` (transform incl. the device
        round-trip). Read against the bench's ``fetch_rtt_ms``: when
        score_ms ≈ RTT the bridge floor is the link, not the code."""
        if not self.latencies_ms:
            return None
        return {
            "marshal_ms": float(np.percentile(self.marshal_ms, 50)),
            "score_ms": float(np.percentile(self.score_ms, 50)),
        }


def make_map_in_arrow_fn(transformer: Any, prefetch: int = 4,
                         workers: int = 2) -> Callable[[Iterator], Iterator]:
    """Build the callable for ``df.mapInArrow(fn, schema)``.

    Spark calls ``fn(iterator_of_record_batches)`` once per partition inside
    a Python worker on the TPU host; the model is constructed once per
    worker (the broadcast-once/clone-per-partition analog — jit caching
    plays the role of ``ParameterCloningMethod.Share``,
    reference: CNTKModel.scala:90-114).
    """

    def fn(batches: Iterator) -> Iterator:
        bridge = ArrowBatchBridge(transformer, prefetch=prefetch,
                                  workers=workers)
        yield from bridge.process(batches)

    return fn


def stream_table(table: DataTable, rows_per_batch: int) -> Iterator:
    """Slice a DataTable into Arrow record batches (test/bench source —
    stands in for Spark partitions).

    Batches are built eagerly on the caller's thread: the bridge's prefetch
    thread then only dequeues ready objects. (Building Arrow arrays on a
    secondary thread while the main thread drives a remote-device tunnel
    segfaulted intermittently; a real Spark worker feeds already-decoded
    record batches, so eager construction is also the faithful shape.)"""
    out = []
    for start in range(0, len(table), rows_per_batch):
        chunk = table.take(np.arange(start,
                                     min(start + rows_per_batch,
                                         len(table))))
        out.extend(chunk.to_arrow().to_batches())
    return iter(out)

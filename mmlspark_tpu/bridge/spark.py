"""One-call Spark integration for the Arrow offload bridge.

The reference scores inside executor JVMs via JNI (reference:
cntk-model/src/main/scala/CNTKModel.scala:248-256 ``mapPartitions``); the
TPU-native topology keeps executors JVM-only and offloads Arrow batches to
the TPU host through ``DataFrame.mapInArrow``. This module packages that as
one call::

    from mmlspark_tpu.bridge.spark import spark_transform
    scored = spark_transform(df, fitted_model)     # a Spark DataFrame

pyspark is an optional dependency (``pip install mmlspark-tpu[spark]``);
everything here degrades to a clear ImportError when it is absent, and the
wire-level contract (iterator of RecordBatches in/out, schema stability,
order preservation, mid-stream error propagation) is tested engine-free in
``tests/test_spark_bridge.py``.
"""

from __future__ import annotations

from typing import Any

from mmlspark_tpu.bridge.offload import make_map_in_arrow_fn
from mmlspark_tpu.data.table import DataTable


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "spark_transform needs pyspark (pip install "
            "'mmlspark-tpu[spark]')") from e


def output_spark_schema(df: Any, transformer: Any, sample_rows: int = 4):
    """Infer the scored DataFrame's Spark schema from a driver-side probe.

    ``mapInArrow`` requires the output schema up front; scoring a small
    sample through the transformer yields the exact Arrow schema, converted
    to the Spark type system.
    """
    _require_pyspark()
    from pyspark.sql.pandas.types import from_arrow_schema

    pdf = df.limit(sample_rows).toPandas()
    if len(pdf) == 0:
        raise ValueError(
            "cannot infer output schema from an empty DataFrame; pass an "
            "explicit schema to df.mapInArrow(make_map_in_arrow_fn(...))")
    probe = transformer.transform(DataTable.from_pandas(pdf))
    return from_arrow_schema(probe.to_arrow().schema)


def spark_transform(df: Any, transformer: Any, prefetch: int = 4,
                    sample_rows: int = 4, *, workers: int = 2) -> Any:
    """Score a Spark DataFrame through a fitted stage on the TPU host.

    Executors stream Arrow record batches into one bridge per partition;
    each bridge re-batches rows into fixed-shape padded device minibatches
    and merges scores back in row order (the CNTKModel.transform analog as
    one line of Spark API). ``workers=2`` (default) overlaps the host-side
    Arrow codec of batch i+1 with the device round-trip of batch i —
    order-preserving; see ``ArrowBatchBridge``.
    """
    _require_pyspark()
    schema = output_spark_schema(df, transformer, sample_rows=sample_rows)
    return df.mapInArrow(
        make_map_in_arrow_fn(transformer, prefetch=prefetch,
                             workers=workers), schema)

"""Spark → TPU offload bridge.

Analog of the reference's executor-side inference path: Spark broadcasts the
model and each executor partition is minibatched through JNI into CNTK
(reference: cntk-model/src/main/scala/CNTKModel.scala:51-114, 248-256).
Here Spark executors stream **Arrow record batches** (``mapInArrow``) to a
host-side bridge that pads them into fixed device shapes, runs the
jit-compiled function, and merges results back row-wise in order.
"""

from mmlspark_tpu.bridge.offload import (
    ArrowBatchBridge, make_map_in_arrow_fn,
)

__all__ = ["ArrowBatchBridge", "make_map_in_arrow_fn"]

"""Sharded serving meshes — DP-replica fan-out and model-parallel tiers.

One served model, many chips (docs/serving.md §sharded serving). Three
tiers, selected per model at load time via a :class:`ServeMeshSpec`:

* **DP-replica serving** (``dp=N``) — N independent replicas, each a
  sub-mesh of ``tp × pp`` chips (one chip in the common small-model
  case). Params upload once *per replica*, the batcher's scheduler
  load-balances packed bucket-batches onto the least-loaded replica, and
  each replica keeps its own bounded in-flight window — every added
  replica multiplies the per-chip Round-8 serve throughput instead of
  sharding a single batch thinner.
* **model-parallel segments** (``tp=M`` / ``pp=K``) — a model too big
  for one chip runs as ONE sharded jitted segment per replica:
  ``core.plan`` places params by the generic sharding rules (tp
  column-sharding via GSPMD — zero manual collectives in the composite,
  the same invariant ``audit_plan_spmd`` enforces for dp segments) or a
  stage's own ``device_param_rules`` hook (e.g. a pipelined stage whose
  ``device_fn`` wraps :func:`~mmlspark_tpu.parallel.pipeline
  .pipeline_apply` — a manual-collective segment, verified against its
  declared ``ENTRY_POINTS`` contract in :mod:`mmlspark_tpu.analysis
  .spmd`).
* **multi-host lockstep** — when the serving mesh spans processes, every
  process must issue the same sharded programs in the same order or the
  collectives deadlock. :class:`LockstepCoordinator` reuses the
  train-loop fence discipline (PR 3's ``drain_barrier``): the batcher
  drains every in-flight dispatch *before* the cross-process signature
  exchange, then all processes dispatch the agreed batch.

Per-model program accounting: each replica compiles the same logical
bucket ladder (≤ ``len(buckets)`` programs); the copies are
device-specialized, so the per-model recompile observable reported by
:meth:`ReplicaSet.compiled_programs` is the MAX over replicas, not the
sum — a regression past the ladder on any replica still trips the gate.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from typing import Any, Mapping, Sequence

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.serve.errors import ModelLoadError

_log = get_logger(__name__)

# mesh axes a served segment may communicate over: the model-parallel
# axes only — dp is the replica axis and must stay collective-free
MODEL_PARALLEL_AXES = ("tp", "pp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class ServeMeshSpec:
    """Per-model serving layout: ``dp`` replicas of ``tp × pp`` chips.

    ``lockstep=True`` opts a model into collective-lockstep dispatch —
    for deployments that feed every process the identical request
    stream. Replicas are carved from this host's local devices, so a
    served program never contains a cross-process collective today;
    lockstep therefore stays OFF unless requested (the dryrun harness
    and tests exercise the discipline single-process).
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    lockstep: bool | None = None

    def __post_init__(self):
        for axis in ("dp", "tp", "pp"):
            if int(getattr(self, axis)) < 1:
                raise ValueError(
                    f"serve mesh axis {axis} must be >= 1: {self}")

    @property
    def chips(self) -> int:
        return self.dp * self.tp * self.pp

    @property
    def model_parallel(self) -> bool:
        return self.tp > 1 or self.pp > 1

    def describe(self) -> str:
        parts = [f"dp={self.dp}"]
        if self.tp > 1:
            parts.append(f"tp={self.tp}")
        if self.pp > 1:
            parts.append(f"pp={self.pp}")
        if self.lockstep:
            parts.append("lockstep")
        return ",".join(parts)

    @classmethod
    def parse(cls, value: Any) -> "ServeMeshSpec":
        """``"dp=4,tp=2[,lockstep]"`` / mapping / spec → spec.

        The CLI flag format (``tools/serve.py --mesh``): comma-separated
        ``axis=N`` terms over ``dp``/``tp``/``pp`` plus the bare
        ``lockstep`` toggle.
        """
        if isinstance(value, cls):
            return value
        if isinstance(value, Mapping):
            return cls(**dict(value))
        fields: dict[str, Any] = {}
        for term in str(value).split(","):
            term = term.strip()
            if not term:
                continue
            if term == "lockstep":
                fields["lockstep"] = True
                continue
            axis, sep, n = term.partition("=")
            if not sep or axis not in ("dp", "tp", "pp"):
                raise ValueError(
                    f"bad serve mesh term {term!r} (want dp=N[,tp=M]"
                    f"[,pp=K][,lockstep]): {value!r}")
            try:
                fields[axis] = int(n)
            except ValueError as e:
                raise ValueError(
                    f"bad serve mesh extent {term!r}: {value!r}") from e
        return cls(**fields)


class _ReplicaHost:
    """Cache host of one replica: carries the replica's compiled-segment
    cache (``core.plan._cached_segment``) and device-resident params —
    per replica, so params upload once per replica and the jit cache
    stays one logical bucket ladder per replica."""


class Replica:
    """One dispatch target: a sub-mesh plus its own compiled-segment
    cache (the batcher's lane carries the live load/in-flight
    accounting). ``shard_params`` optionally overrides the segment's
    param placement on this replica's mesh — ``(mesh, params_tuple) →
    shardings pytree`` — instead of the generic
    ``parallel.mesh.param_shardings`` rules."""

    def __init__(self, index: int, mesh: Any, shard_params: Any = None):
        self.index = index
        self.mesh = mesh
        self.shard_params = shard_params
        self.cache_host = _ReplicaHost()
        self.dispatched = 0    # total batches this replica served

    def describe(self) -> str:
        devs = [getattr(d, "id", "?") for d in self.mesh.devices.flat]
        return f"replica{self.index}[devices={devs}]"


class ReplicaSet:
    """The per-model replica fan-out the batcher schedules over."""

    def __init__(self, model: str, spec: ServeMeshSpec,
                 replicas: list[Replica]):
        self.model = model
        self.spec = spec
        self.replicas = replicas

    def __len__(self) -> int:
        return len(self.replicas)

    def compiled_programs(self) -> int | None:
        """Per-model logical XLA program count: the MAX over replicas
        (each replica holds a device-specialized copy of the same bucket
        ladder — the ladder bound is per model, not replicas × buckets).
        ``None`` when any replica's jit doesn't expose its cache size."""
        sizes = [_obs_rt.compiled_programs(r.cache_host)
                 for r in self.replicas]
        if any(s is None for s in sizes):
            return None
        return max(sizes) if sizes else 0


def build_replicas(model: str, spec: ServeMeshSpec,
                   devices: Sequence[Any] | None = None,
                   shard_params: Any = None) -> ReplicaSet:
    """Carve ``dp`` replica sub-meshes of ``tp × pp`` chips out of the
    local devices. A mesh that does not divide the device count is a
    typed load error (:class:`~mmlspark_tpu.serve.errors.ModelLoadError`)
    — before any compile or transfer. ``shard_params`` (an explicit
    param-placement override, see :class:`Replica`) applies to every
    replica."""
    import jax

    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh

    devices = list(devices if devices is not None
                   else jax.local_devices())
    chips = spec.chips
    if chips > len(devices) or len(devices) % chips:
        raise ModelLoadError(model, message=(
            f"model {model!r}: serving mesh {spec.describe()} needs "
            f"{chips} chip(s) ({spec.dp} replica(s) x {spec.tp * spec.pp} "
            f"chip(s) each) which does not divide this host's "
            f"{len(devices)} device(s)"))
    per = spec.tp * spec.pp
    sub = MeshSpec(dp=1, tp=spec.tp, pp=spec.pp)
    replicas = [Replica(r, make_mesh(sub, devices[r * per:(r + 1) * per]),
                        shard_params=shard_params)
                for r in range(spec.dp)]
    _log.info("serve[%s]: mesh %s -> %s", model, spec.describe(),
              "; ".join(r.describe() for r in replicas))
    return ReplicaSet(model, spec, replicas)


def _signature_digest(signature: tuple) -> int:
    """Stable 32-bit digest of a dispatch signature (bucket, entry
    layout) — what lockstep processes compare before issuing the
    collective-bearing program."""
    return zlib.crc32(repr(signature).encode("utf-8"))


class LockstepCoordinator:
    """Multi-host serve lockstep: agree on every dispatch, in order.

    The discipline mirrors ``train/input.py``'s multi-host rule: the
    batcher calls its ``drain_barrier()`` (all in-flight dispatches
    drained) *before* :meth:`agree`, so no process interleaves the
    signature exchange with outstanding device work; then every process
    verifies it is about to dispatch the identical (bucket, layout)
    program. Single-process (the dryrun harness) the exchange is local
    but the fence-then-agree ordering still runs — the discipline the
    SPMD203 static check pins.
    """

    def __init__(self, model: str):
        self.model = model
        self._lock = threading.Lock()
        self.steps = 0
        self.fingerprint = 0   # running digest over the dispatch order

    def agree(self, signature: tuple) -> None:
        """Exchange + verify one dispatch signature across processes.

        Raises ``RuntimeError`` on divergence — dispatching anyway would
        deadlock the collectives, and a typed host-side failure beats a
        hung mesh."""
        import jax

        digest = _signature_digest(signature)
        if jax.process_count() > 1:  # pragma: no cover - needs multi-host
            import numpy as np
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(
                np.asarray([digest], np.uint32))
            if len(set(int(g) for g in gathered.reshape(-1))) != 1:
                raise RuntimeError(
                    f"serve lockstep divergence on model "
                    f"{self.model!r}: processes disagree on dispatch "
                    f"{self.steps} signature ({signature!r}) — feed "
                    "every process the identical request sequence")
        with self._lock:
            self.steps += 1
            self.fingerprint = zlib.crc32(
                digest.to_bytes(4, "little"),
                self.fingerprint)

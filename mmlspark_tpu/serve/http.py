"""Stdlib-only HTTP front end for :class:`~mmlspark_tpu.serve.ModelServer`.

Protocol (details + examples in docs/serving.md):

* ``POST /v1/models/<name>:predict`` — body is either

  - JSON: ``{"rows": [{col: value, ...}, ...], "deadline_ms": 250,
    "columns": ["scores"]}``; response ``{"model": ..., "rows": [...]}``;
  - an Arrow IPC stream (``Content-Type:
    application/vnd.apache.arrow.stream``), marshalled through the same
    ``DataTable.from_arrow``/``to_arrow`` codec as the Spark offload
    bridge; the response is an Arrow stream when the ``Accept`` header
    asks for one, JSON otherwise. Deadline via ``X-Deadline-Ms``.

* ``POST /v1/models/<name>:generate`` — autoregressive token serving on
  a registered generator: ``{"prompt": [int, ...], "max_new_tokens": n,
  "stream": true}``. Streaming (default) answers chunked
  ``application/x-ndjson`` — one ``{"token", "index"}`` line per token
  as it decodes, then a terminal ``{"done": true, "tokens": [...]}``
  summary; ``stream: false`` blocks for one JSON object.

* ``GET /healthz`` — drain-aware **readiness**: the
  ok/degraded/unhealthy state machine over the SLO burn rates
  (``obs/health.py``), 200 while ready, 503 when draining or unhealthy
  (the body always carries the full verdict);
  ``GET /livez`` — **liveness**: always 200 while the process answers
  HTTP. Point restart-the-container probes here, never at ``/healthz``
  — an alive-but-burning (or gracefully draining) server must fail
  readiness without being killed;
  ``GET /slo`` — every model's SLO status:
  burn rates, error-budget remaining, latency verdict, derived
  queue-depth/occupancy/replica-skew signals (``obs/slo.py``);
  ``GET /v1/models`` — the model list;
  ``GET /v1/stats`` — every model's :class:`ServerStats` snapshot;
  ``GET /metrics`` — the obs metrics view. Content-negotiated: the
  default is the JSON snapshot (process-wide registry merged with every
  model's stats snapshot — docs/observability.md); an ``Accept`` header
  asking for ``text/plain`` (or OpenMetrics) gets the Prometheus text
  exposition of the same registries, so standard scrapers work
  unchanged;
  ``GET /trace`` — the captured span buffer as Chrome-trace
  ``trace_event`` JSON, request flows included (empty unless
  ``obs.enable()`` was called, e.g. ``tools/serve.py --obs`` or
  ``MMLSPARK_TPU_OBS=1``);
  ``GET /fleet`` — the FLEET-merged metrics view (``obs/fleet.py``:
  every process exporting under ``MMLSPARK_TPU_FLEET``, counters
  summed / gauges per host), JSON by default and the Prometheus text
  exposition of the merged registry under the same ``Accept``
  negotiation as ``/metrics``; 404 without a configured fleet dir.

Typed serving errors map to status codes: ``Overloaded`` → 429,
``DeadlineExceeded`` → 504, ``ModelNotFound`` → 404, ``BadRequest`` (and
malformed bodies) → 400, ``ServerClosed`` → 503. The backpressure
responses — 429, ``ServerClosed`` 503s, and the drain/unhealthy 503
from ``/healthz`` — carry a ``Retry-After`` header
(``ServeConfig.retry_after_s``, whole seconds) so generic clients can
act on the "retry with backoff" contract without parsing bodies.

Each HTTP request blocks its handler thread in ``ModelServer.predict`` —
the ``ThreadingHTTPServer`` below is exactly the concurrency source the
dynamic batcher coalesces across.
"""

from __future__ import annotations

import io
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.serve.errors import (
    BadRequest, DeadlineExceeded, ModelNotFound, Overloaded, ServeError,
    ServerClosed,
)
from mmlspark_tpu.serve.server import ModelServer

_log = get_logger(__name__)

ARROW_CONTENT_TYPE = "application/vnd.apache.arrow.stream"

_STATUS = {
    Overloaded: 429,
    DeadlineExceeded: 504,
    ModelNotFound: 404,
    BadRequest: 400,
    ServerClosed: 503,
}


def _json_safe(v: Any) -> Any:
    """Cell → JSON-representable value (numpy unwrapped, arrays listed)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, float) and not np.isfinite(v):
        return None
    return v


def table_to_json_rows(table: DataTable,
                       columns: list[str] | None = None) -> list[dict]:
    names = list(columns) if columns else table.columns
    return [{k: _json_safe(row[k]) for k in names}
            for row in table.iter_rows()]


def _client_deadline(value: Any, where: str) -> float | None:
    """Coerce a client-supplied deadline; malformed input is the client's
    fault (400), never a 500."""
    if value is None:
        return None
    try:
        return float(value)
    except (TypeError, ValueError) as e:
        raise BadRequest(
            f"malformed deadline in {where}: {value!r} (want a number "
            "of milliseconds)") from e


def _require_pyarrow():
    try:
        import pyarrow  # noqa: F401
        return pyarrow
    except ImportError as e:
        raise BadRequest(
            "Arrow bodies need pyarrow installed on the serving host "
            "(pip install mmlspark-tpu[arrow])") from e


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mmlspark-tpu-serve"

    # the ThreadingHTTPServer subclass below carries .model_server
    @property
    def _ms(self) -> ModelServer:
        return self.server.model_server  # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args: Any) -> None:
        _log.debug("http %s — %s", self.address_string(), fmt % args)

    # -- responses --

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              headers: dict | None = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        identity = getattr(self.server, "identity", None)
        if identity:
            # which backend answered — the fleet router surfaces this so
            # affinity/failover behavior is assertable end to end
            self.send_header("X-Serve-Identity", identity)
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: Any,
                   headers: dict | None = None) -> None:
        self._send(status, json.dumps(payload).encode("utf-8"),
                   headers=headers)

    def _retry_after(self, exc: BaseException | None = None) -> dict:
        """The backpressure hint: ``errors.py`` tells clients to "retry
        with backoff", so the 429/503 responses must carry something a
        generic HTTP client can act on. Whole seconds (the header's
        unit), rounded up. The error's own stamped ``retry_after_s``
        wins when present (it came from the rejecting model's config);
        the server-wide ``ServeConfig.retry_after_s`` is the fallback."""
        import math
        hint = getattr(exc, "retry_after_s", None)
        if hint is None:
            hint = self._ms.config.retry_after_s
        return {"Retry-After": str(max(1, math.ceil(hint)))}

    def _send_error_typed(self, exc: BaseException) -> None:
        status = 500
        for etype, code in _STATUS.items():
            if isinstance(exc, etype):
                status = code
                break
        headers = None
        if isinstance(exc, (Overloaded, ServerClosed)):
            # both are "come back later", not "give up": a full queue
            # drains, and a draining/swapping server is replaced by a
            # ready one behind the same balancer
            headers = self._retry_after(exc)
        self._send_json(status, {"error": type(exc).__name__,
                                 "message": str(exc)}, headers=headers)

    # -- routes --

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        try:
            if self.path == "/healthz":
                # drain-aware readiness: 503 tells the load balancer to
                # stop routing here (draining or unhealthy), while the
                # body keeps answering with the full verdict; the
                # Retry-After hint tells a probing client when to look
                # again
                payload = self._ms.health()
                ready = payload["ready"]
                self._send_json(
                    200 if ready else 503, payload,
                    headers=None if ready else self._retry_after())
            elif self.path == "/livez":
                # liveness is only "the process answers HTTP": always
                # 200 — a 503 here would make the orchestrator restart
                # an alive server mid-drain or mid-incident, discarding
                # warm compile caches and in-flight requests
                self._send_json(200, {"alive": True})
            elif self.path == "/slo":
                self._send_json(200, self._ms.slo_snapshot())
            elif self.path == "/v1/models":
                self._send_json(200, {"models": self._ms.models()})
            elif self.path == "/v1/stats":
                self._send_json(200, self._ms.snapshot())
            elif self.path == "/metrics":
                self._send_metrics()
            elif self.path == "/fleet":
                self._send_fleet()
            elif self.path == "/trace":
                from mmlspark_tpu.obs import export as obs_export
                self._send_json(200, obs_export.chrome_trace())
            else:
                self._send_json(404, {"error": "NotFound",
                                      "message": self.path})
        except BaseException as e:  # noqa: BLE001 — typed mapping
            self._send_error_typed(e)

    def _wants_prometheus(self) -> bool:
        """The /metrics-family content negotiation, in ONE place:
        ``Accept: text/plain`` (what Prometheus sends,
        ``text/plain;version=0.0.4``) or OpenMetrics asks for the text
        exposition; everything else gets JSON."""
        accept = (self.headers.get("Accept") or "").lower()
        return "text/plain" in accept or "openmetrics" in accept

    def _send_prometheus(self, registries: list) -> None:
        from mmlspark_tpu.obs import export as obs_export
        body = obs_export.prometheus_text(registries)
        self._send(200, body.encode("utf-8"),
                   "text/plain; version=0.0.4; charset=utf-8")

    def _send_metrics(self) -> None:
        """The /metrics body under content negotiation: JSON snapshot by
        default (unchanged), Prometheus text exposition when the Accept
        header asks for it."""
        from mmlspark_tpu.obs import export as obs_export
        from mmlspark_tpu.obs.metrics import registry
        if self._wants_prometheus():
            self._send_prometheus(
                [registry()] + self._ms.metric_registries())
            return
        self._send_json(200, {
            **obs_export.metrics_snapshot(),
            "models": self._ms.snapshot(),
        })

    def _send_fleet(self) -> None:
        """The fleet-merged metrics view (obs/fleet.py): every process
        exporting under the configured ``MMLSPARK_TPU_FLEET`` directory,
        counters summed / gauges per host. Content-negotiated like
        ``/metrics``: JSON snapshot by default, the Prometheus text
        exposition of the MERGED registry for ``text/plain`` — one
        scrape endpoint for the whole fleet. 404 when no fleet dir is
        configured; 503 when the directory holds no readable snapshots
        yet (come back after the first export interval)."""
        from mmlspark_tpu.obs import fleet as obs_fleet
        fleet_dir = obs_fleet.fleet_dir()
        if fleet_dir is None:
            self._send_json(404, {
                "error": "FleetNotConfigured",
                "message": "no fleet directory: set MMLSPARK_TPU_FLEET "
                           "or call obs.fleet.enable(dir)"})
            return
        try:
            # registry-only merge: the metrics bodies never read the
            # span rings, and a scraper polls this on a tight cadence
            view = obs_fleet.FleetCollector(fleet_dir).collect(
                include_ring=False)
        except obs_fleet.FleetReadError as e:
            self._send_json(503, {"error": "FleetUnreadable",
                                  "message": str(e)},
                            headers=self._retry_after())
            return
        if self._wants_prometheus():
            self._send_prometheus([view.registry])
            return
        self._send_json(200, view.snapshot())

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        try:
            # always consume the body first: responding with unread bytes
            # on a keep-alive (HTTP/1.1) connection desyncs the stream —
            # the leftover body would parse as the next request line
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            rid = self.headers.get("X-Fleet-Request-Id")
            if rid:
                # span link across the process hop: the fleet router
                # stamps each proxied request with its id; the matching
                # router-side span carries the same id, so a trace
                # reader can join the two processes' timelines
                from mmlspark_tpu.obs.spans import event as _obs_event
                _obs_event("serve/fleet_rx", "serve",
                           {"request_id": rid, "path": self.path})
            if self.path.startswith("/v1/models/") \
                    and self.path.endswith(":generate"):
                name = self.path[len("/v1/models/"):-len(":generate")]
                self._generate(name, body)
                return
            if not (self.path.startswith("/v1/models/")
                    and self.path.endswith(":predict")):
                self._send_json(404, {"error": "NotFound",
                                      "message": self.path})
                return
            name = self.path[len("/v1/models/"):-len(":predict")]
            ctype = (self.headers.get("Content-Type") or "").split(";")[0]
            if ctype == ARROW_CONTENT_TYPE:
                self._predict_arrow(name, body)
            else:
                self._predict_json(name, body)
        except BaseException as e:  # noqa: BLE001 — typed mapping
            self._send_error_typed(e)

    # -- predict bodies --

    def _predict_json(self, name: str, body: bytes) -> None:
        try:
            payload = json.loads(body or b"{}")
            rows = payload["rows"]
        except (ValueError, KeyError, TypeError) as e:
            raise BadRequest(f"malformed JSON predict body: {e}") from e
        if not isinstance(rows, list) or not rows:
            raise BadRequest("predict body needs a non-empty 'rows' list")
        # list cells become vectors; "dtype" (default float32) picks the
        # wire dtype so e.g. uint8-warmed image models can be hit without
        # compiling a second per-bucket program family (entry dtype is
        # part of the program identity — docs/serving.md)
        dtype_name = payload.get("dtype") or "float32"
        try:
            dtype = np.dtype(dtype_name)
        except TypeError as e:
            raise BadRequest(f"unknown dtype {dtype_name!r}") from e
        try:
            table = DataTable.from_rows([
                {k: (np.asarray(v, dtype) if isinstance(v, list) else v)
                 for k, v in r.items()} for r in rows])
        except Exception as e:  # client data, not a server fault → 400
            raise BadRequest(f"uncoercible predict rows: {e}") from e
        out = self._ms.predict(
            name, table,
            deadline_ms=_client_deadline(payload.get("deadline_ms"),
                                         "'deadline_ms'"))
        columns = payload.get("columns")
        if columns:
            missing = [c for c in columns if c not in out]
            if missing:
                raise BadRequest(
                    f"unknown response columns {missing}; available: "
                    f"{out.columns}")
        self._send_json(200, {
            "model": name,
            "rows": table_to_json_rows(out, columns),
        })

    def _generate(self, name: str, body: bytes) -> None:
        """``POST /v1/models/<name>:generate`` — autoregressive token
        serving. Body ``{"prompt": [int, ...], "max_new_tokens": n,
        "stream": true}``. ``stream: true`` (the default) answers with a
        chunked ``application/x-ndjson`` body: one ``{"token": t,
        "index": i}`` line per token AS IT DECODES (the TTFT a client
        observes is the engine's TTFT, not the whole generation), then a
        final ``{"done": true, ...}`` summary line. ``stream: false``
        blocks and answers with one JSON object. Admission errors map to
        the usual typed status codes; a mid-stream failure is reported
        as a terminal ``{"error": ...}`` line (the status line already
        went out)."""
        try:
            payload = json.loads(body or b"{}")
            prompt = payload["prompt"]
        except (ValueError, KeyError, TypeError) as e:
            raise BadRequest(f"malformed generate body: {e}") from e
        if not isinstance(prompt, list) or not prompt \
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in prompt):
            raise BadRequest(
                "generate body needs a non-empty integer 'prompt' list")
        max_new = payload.get("max_new_tokens")
        if max_new is not None and not isinstance(max_new, int):
            raise BadRequest(
                f"malformed max_new_tokens: {max_new!r} (want an int)")
        # admission happens BEFORE any response bytes: Overloaded /
        # BadRequest / ModelNotFound still map to clean status codes
        stream = self._ms.generate(name, prompt, max_new_tokens=max_new)
        if not payload.get("stream", True):
            tokens = stream.result()
            self._send_json(200, {"model": name, "tokens": tokens,
                                  "cancelled": stream.cancelled})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        identity = getattr(self.server, "identity", None)
        if identity:
            self.send_header("X-Serve-Identity", identity)
        self.end_headers()

        def chunk(obj: dict) -> None:
            data = json.dumps(obj).encode("utf-8") + b"\n"
            self.wfile.write(f"{len(data):X}\r\n".encode("ascii")
                             + data + b"\r\n")
            self.wfile.flush()

        try:
            for i, tok in enumerate(stream):
                chunk({"token": int(tok), "index": i})
            chunk({"done": True, "model": name,
                   "tokens": [int(t) for t in stream.tokens],
                   "cancelled": stream.cancelled})
        except ServeError as e:
            chunk({"error": type(e).__name__, "message": str(e)})
        self.wfile.write(b"0\r\n\r\n")

    def _predict_arrow(self, name: str, body: bytes) -> None:
        pa = _require_pyarrow()
        try:
            reader = pa.ipc.open_stream(io.BytesIO(body))
            batches = list(reader)
        except Exception as e:
            raise BadRequest(f"malformed Arrow stream: {e}") from e
        if not batches:
            raise BadRequest("empty Arrow stream")
        table = DataTable.from_arrow(batches[0])
        for rb in batches[1:]:
            table = table.concat(DataTable.from_arrow(rb))
        out = self._ms.predict(
            name, table,
            deadline_ms=_client_deadline(
                self.headers.get("X-Deadline-Ms"), "X-Deadline-Ms"))
        if ARROW_CONTENT_TYPE in (self.headers.get("Accept") or ""):
            sink = io.BytesIO()
            arrow_out = out.to_arrow()
            with pa.ipc.new_stream(sink, arrow_out.schema) as writer:
                writer.write_table(arrow_out)
            self._send(200, sink.getvalue(), ARROW_CONTENT_TYPE)
        else:
            self._send_json(200, {"model": name,
                                  "rows": table_to_json_rows(out)})


class ServeHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer bound to a :class:`ModelServer`.

    ``identity``, when set, is echoed on every response as
    ``X-Serve-Identity`` — the fleet tier names each backend so which
    process answered is observable through the router hop.
    """

    daemon_threads = True
    # socketserver's default backlog of 5 resets connections when the
    # fleet router fans a burst in; admission control belongs to the
    # ModelServer's queue (429), not the kernel's SYN queue
    request_queue_size = 128

    def __init__(self, model_server: ModelServer, address: tuple,
                 identity: str | None = None):
        self.model_server = model_server
        self.identity = identity
        super().__init__(address, _Handler)


def start_http_server(model_server: ModelServer, host: str = "0.0.0.0",
                      port: int = 8000, background: bool = True,
                      identity: str | None = None) -> ServeHTTPServer:
    """Bind and start serving. ``background=True`` runs ``serve_forever``
    on a daemon thread and returns the bound server (``.server_address``
    has the ephemeral port when 0 was requested); shut down with
    ``httpd.shutdown(); httpd.server_close()``."""
    httpd = ServeHTTPServer(model_server, (host, port), identity=identity)
    if background:
        t = threading.Thread(target=httpd.serve_forever,
                             name="ServeHTTP", daemon=True)
        t.start()
    return httpd

"""The serve fleet tier — router, backend pool, supervisor, autoscaler.

One process's chips bound the single-``ModelServer`` serving plane;
this package is the front tier that spreads traffic across N backend
serve *processes* and grows/shrinks the fleet from its own SLO burn
signals (docs/serving.md §fleet tier):

* :class:`BackendPool` (``pool.py``) — the routing table: least-loaded
  deadline-aware selection, ``Retry-After`` holds, zero-drop drains.
* :class:`FleetRouter` (``router.py``) — the HTTP fan-in proxying
  ``/v1/models/...`` (``:generate`` streams with per-stream backend
  affinity) with typed re-route-never-drop failover.
* :class:`ServeSupervisor` (``supervisor.py``) — launches/watches the
  backend processes on the shared supervision core
  (``mmlspark_tpu/service/``), restart-with-backoff via
  ``RecoveryPolicy``, every decision journaled.
* :class:`ScalePolicy` (``scale.py``) — the pure autoscaling decision
  function over ``MetricHistory`` burn/occupancy signals.

CLI: ``python tools/serve_fleet.py``.
"""

from mmlspark_tpu.serve.fleet.pool import (
    Backend, BackendPool, NoBackendAvailable,
)
from mmlspark_tpu.serve.fleet.router import FleetRouter
from mmlspark_tpu.serve.fleet.scale import (
    FleetLedger, Hold, ScaleDown, ScalePolicy, ScaleSignal, ScaleUp,
    signal_from_history, sustained_s,
)
from mmlspark_tpu.serve.fleet.supervisor import FleetConfig, ServeSupervisor

__all__ = [
    "Backend",
    "BackendPool",
    "FleetConfig",
    "FleetLedger",
    "FleetRouter",
    "Hold",
    "NoBackendAvailable",
    "ScaleDown",
    "ScalePolicy",
    "ScaleSignal",
    "ScaleUp",
    "ServeSupervisor",
    "signal_from_history",
    "sustained_s",
]

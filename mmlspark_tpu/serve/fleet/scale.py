"""Autoscaling as pure policy over burn-rate signals.

The train service split supervision into sensors → policy → actuator
(``train/service.py``); the fleet autoscaler keeps the same shape. The
sensors are the PR 14 SLO series every backend already publishes
(``serve.slo_burn_short`` fast-window burn, ``serve.occupancy_*``) —
the supervisor aggregates them off the beacons into its
:class:`~mmlspark_tpu.obs.timeseries.MetricHistory` and condenses one
poll into a :class:`ScaleSignal`. :class:`ScalePolicy` is the PURE
decision function from that signal + the :class:`FleetLedger` to a
typed action — unit-testable without a single process spawned:

==============================================  =====================
signal                                          action
==============================================  =====================
fast burn ≥ ``fast_burn`` for ``burn_sustain_s``  :class:`ScaleUp`
  (and below ``max_backends``)                    (spawn a backend)
mean occupancy ≤ ``idle_occupancy`` for           :class:`ScaleDown`
  ``idle_sustain_s`` (and above ``min_backends``) (zero-drop drain one)
within ``cooldown_s`` of the last scale action    :class:`Hold`
anything else                                     :class:`Hold`
==============================================  =====================

Sustain windows are the flap damper: one burning poll (a single
deadline storm sample) must not buy a process spawn, and one idle poll
must not tear a warm backend down. The cooldown guards against
relay-oscillation — a fresh backend needs at least one beacon interval
before its effect shows in the signals it was spawned to fix.
"""

from __future__ import annotations

import dataclasses
import math


def sustained_s(samples: list[tuple[float, float]], now: float,
                pred) -> float:
    """Length (seconds, up to ``now``) of the trailing run of samples
    satisfying ``pred`` — 0.0 when the newest sample fails it or there
    are no samples. The standard multiwindow-burn trick reduced to what
    a sustain threshold needs: how long has this been CONTINUOUSLY
    true."""
    run_start = None
    for t, v in samples:  # oldest → newest (MetricHistory.range order)
        if pred(v):
            if run_start is None:
                run_start = t
        else:
            run_start = None
    return (now - run_start) if run_start is not None else 0.0


@dataclasses.dataclass(frozen=True)
class ScaleSignal:
    """One poll's condensed sensor read."""

    backends: int           # live (up, non-draining) backends
    burn: float = 0.0       # newest max fast-window burn across backends
    burn_high_s: float = 0.0   # seconds burn has been >= the threshold
    occupancy: float = 0.0  # newest mean occupancy across backends
    idle_s: float = 0.0     # seconds occupancy has been <= idle line


@dataclasses.dataclass
class FleetLedger:
    """The scaling history the policy conditions on."""

    scale_ups: int = 0
    scale_downs: int = 0
    since_scale_s: float = math.inf  # seconds since the last scale
    #                                  action (inf = never scaled)


@dataclasses.dataclass(frozen=True)
class ScaleUp:
    reason: str


@dataclasses.dataclass(frozen=True)
class ScaleDown:
    reason: str


@dataclasses.dataclass(frozen=True)
class Hold:
    reason: str = ""


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """Signal → action, pure (table in the module docstring)."""

    fast_burn: float = 14.0      # the SLOSpec fast-burn line: burning
    #                              the monthly budget 14x too fast
    burn_sustain_s: float = 1.0
    idle_occupancy: float = 0.02
    idle_sustain_s: float = 30.0
    min_backends: int = 1
    max_backends: int = 4
    cooldown_s: float = 5.0

    def __post_init__(self) -> None:
        if self.min_backends < 1:
            raise ValueError(
                f"min_backends must be >= 1: {self.min_backends}")
        if self.max_backends < self.min_backends:
            raise ValueError(
                f"max_backends ({self.max_backends}) < min_backends "
                f"({self.min_backends})")

    def decide(self, sig: ScaleSignal, ledger: FleetLedger):
        if ledger.since_scale_s < self.cooldown_s:
            return Hold(f"cooldown ({ledger.since_scale_s:.1f}s < "
                        f"{self.cooldown_s:g}s since last scale)")
        if sig.burn_high_s >= self.burn_sustain_s and sig.burn > 0:
            if sig.backends >= self.max_backends:
                return Hold(f"fast burn {sig.burn:.1f}x sustained but "
                            f"already at max_backends "
                            f"({self.max_backends})")
            return ScaleUp(f"fast burn {sig.burn:.1f}x sustained "
                           f"{sig.burn_high_s:.1f}s "
                           f">= {self.burn_sustain_s:g}s")
        if sig.idle_s >= self.idle_sustain_s:
            if sig.backends <= self.min_backends:
                return Hold("idle but at min_backends "
                            f"({self.min_backends})")
            return ScaleDown(f"occupancy {sig.occupancy:.3f} <= "
                             f"{self.idle_occupancy:g} for "
                             f"{sig.idle_s:.1f}s")
        return Hold()


#: the aggregated series names the supervisor appends each poll and
#: :func:`signal_from_history` reads back — ONE derivation, shared by
#: policy and telemetry (the timeseries sampler persists them too via
#: the ``serve.fleet.`` default prefix)
BURN_SERIES = "serve.fleet.burn_max"
OCCUPANCY_SERIES = "serve.fleet.occupancy_mean"


def signal_from_history(history, *, now: float, backends: int,
                        policy: ScalePolicy,
                        window_s: float = 60.0) -> ScaleSignal:
    """Condense the supervisor's :class:`MetricHistory` into one
    :class:`ScaleSignal`: the newest burn/occupancy values plus the
    trailing sustain runs against ``policy``'s thresholds."""
    burn_samples = [s for series in
                    history.range(BURN_SERIES, now - window_s,
                                  now).values()
                    for s in series]
    occ_samples = [s for series in
                   history.range(OCCUPANCY_SERIES, now - window_s,
                                 now).values()
                   for s in series]
    burn_samples.sort()
    occ_samples.sort()
    return ScaleSignal(
        backends=backends,
        burn=burn_samples[-1][1] if burn_samples else 0.0,
        burn_high_s=sustained_s(burn_samples, now,
                                lambda v: v >= policy.fast_burn),
        occupancy=occ_samples[-1][1] if occ_samples else 0.0,
        idle_s=sustained_s(occ_samples, now,
                           lambda v: v <= policy.idle_occupancy),
    )

"""The routing table: backend registry, selection, holds, drains.

:class:`BackendPool` generalizes the PR 13 ``Client(retry=)`` semantics
across processes: where the in-process client retried one server with a
backoff, the router retries *another backend* — the pool is the shared
state that makes that choice (who is up, who is held by a
``Retry-After``, who is draining, who is least loaded).

Selection is least-loaded and deadline-aware: among backends that are
``up`` and not under an active hold, pick the one with the fewest
leased requests + active streams (ties → lowest id, for determinism).
When every candidate is held, :meth:`pick` raises
:class:`NoBackendAvailable` stamped with the EARLIEST hold expiry as
``retry_after_s`` — the router compares that against the request's
remaining deadline to decide wait-and-retry vs. surface the 503
(``core/retry.call_with_retry`` honors the same stamp as its sleep
floor, so in-process callers get the identical contract).

All pool state lives under ONE ``named_lock`` witness
(``serve.fleet.pool``); every method is a short critical section — no
network I/O, sleeps, or callbacks ever run under it (CC102/CC105), the
router does all blocking work between pool calls.
"""

from __future__ import annotations

import dataclasses
import time

from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.serve.errors import ServeError


class NoBackendAvailable(ServeError):
    """No backend is currently eligible to take this request (none
    registered, all down/draining, or every live one is under a
    ``Retry-After`` hold). ``retry_after_s`` carries the earliest hold
    expiry when holds are the reason — the router's deadline-aware
    wait-vs-503 pivot, and the client retry sleep floor."""

    def __init__(self, detail: str, retry_after_s: float | None = None):
        super().__init__(f"no backend available: {detail}")
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class Backend:
    """One registered backend serve process (mutable pool record)."""

    bid: int
    host: str
    port: int
    generation: int = 0
    state: str = "up"        # up | draining | down
    inflight: int = 0        # router-leased predict requests
    streams: int = 0         # active :generate streams (affinity holds)
    hold_until: float = 0.0  # monotonic Retry-After hold expiry
    versions: dict = dataclasses.field(default_factory=dict)
    #   served {model: repo version} from the beacon — the rollout-
    #   convergence surface the lifecycle deployer blocks promotion on

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    @property
    def load(self) -> int:
        return self.inflight + self.streams


class _Lease:
    """Context manager pairing the increment/decrement of one load
    field; decrement survives the backend being re-registered (same
    record object) and no-ops if it was removed meanwhile."""

    def __init__(self, pool: "BackendPool", bid: int, field: str):
        self._pool = pool
        self._bid = bid
        self._field = field

    def __enter__(self) -> int:
        self._pool._bump(self._bid, self._field, +1)
        return self._bid

    def __exit__(self, *exc) -> None:
        self._pool._bump(self._bid, self._field, -1)


class BackendPool:
    """Thread-safe registry + selector over the live backends."""

    def __init__(self):
        self._lock = named_lock("serve.fleet.pool")
        self._backends: dict[int, Backend] = {}

    # -- membership (the supervisor's side) --

    def add(self, bid: int, host: str, port: int,
            generation: int = 0,
            versions: dict | None = None) -> None:
        """Register or refresh a backend. A re-add after a restart (new
        port/generation) clears the down state and any stale hold; a
        re-add of a DRAINING backend keeps it draining (a beacon
        arriving mid-drain must not resurrect it into the candidate
        set)."""
        with self._lock:
            b = self._backends.get(bid)
            if b is None:
                self._backends[bid] = Backend(
                    bid, host, port, generation,
                    versions=dict(versions or {}))
                return
            restarted = (b.port != port or b.generation != generation
                         or b.host != host)
            b.host, b.port, b.generation = host, port, generation
            if versions is not None:
                b.versions = dict(versions)
            if b.state == "down" or restarted:
                b.state = "up" if b.state != "draining" else b.state
                b.hold_until = 0.0

    def remove(self, bid: int) -> None:
        with self._lock:
            self._backends.pop(bid, None)

    def mark_down(self, bid: int) -> bool:
        """Transport failure evidence from the router. Returns whether
        the backend was previously routable (so the caller reports each
        death once, not once per in-flight request)."""
        with self._lock:
            b = self._backends.get(bid)
            if b is None:
                return False
            was = b.state == "up"
            b.state = "down"
            return was

    def drain(self, bid: int) -> None:
        """Begin a zero-drop drain: the backend leaves the candidate
        set for NEW work but keeps its active leases/streams until they
        finish (:meth:`idle` reports when it is safe to stop the
        process)."""
        with self._lock:
            b = self._backends.get(bid)
            if b is not None and b.state == "up":
                b.state = "draining"

    def hold(self, bid: int, retry_after_s: float) -> None:
        """A backend answered 429/503 with Retry-After: keep it out of
        selection until the hold expires (monotonic clock)."""
        with self._lock:
            b = self._backends.get(bid)
            if b is not None:
                b.hold_until = max(b.hold_until,
                                   time.monotonic()
                                   + max(0.0, retry_after_s))

    # -- selection + leases (the router's side) --

    def pick(self, exclude: tuple[int, ...] = ()) -> int:
        """Least-loaded eligible backend id. Raises
        :class:`NoBackendAvailable` (stamped with the earliest hold
        expiry when holds are what is blocking) otherwise."""
        now = time.monotonic()
        with self._lock:
            up = [b for b in self._backends.values()
                  if b.state == "up" and b.bid not in exclude]
            free = [b for b in up if b.hold_until <= now]
            if free:
                best = min(free, key=lambda b: (b.load, b.bid))
                return best.bid
            if up:  # all live candidates are held: tell the caller
                #     when the earliest hold lifts
                soonest = min(b.hold_until for b in up) - now
                raise NoBackendAvailable(
                    f"all {len(up)} live backend(s) held by "
                    "Retry-After", retry_after_s=max(0.0, soonest))
        raise NoBackendAvailable("no live backends"
                                 + (f" (excluded {sorted(exclude)})"
                                    if exclude else ""))

    def _bump(self, bid: int, field: str, delta: int) -> None:
        with self._lock:
            b = self._backends.get(bid)
            if b is not None:
                setattr(b, field, max(0, getattr(b, field) + delta))

    def lease(self, bid: int) -> _Lease:
        """Account one in-flight predict on ``bid`` for its scope."""
        return _Lease(self, bid, "inflight")

    def stream_lease(self, bid: int) -> _Lease:
        """Account one active :generate stream on ``bid`` — the
        affinity hold that keeps a draining backend alive until its
        streams finish."""
        return _Lease(self, bid, "streams")

    # -- queries --

    def get(self, bid: int) -> Backend | None:
        with self._lock:
            b = self._backends.get(bid)
            return dataclasses.replace(b) if b is not None else None

    def address(self, bid: int) -> tuple[str, int]:
        with self._lock:
            b = self._backends.get(bid)
            if b is None:
                raise NoBackendAvailable(f"backend {bid} not registered")
            return (b.host, b.port)

    def idle(self, bid: int) -> bool:
        """True when ``bid`` is draining AND its last lease/stream is
        gone — the zero-drop stop point."""
        with self._lock:
            b = self._backends.get(bid)
            return (b is not None and b.state == "draining"
                    and b.load == 0)

    def up_count(self) -> int:
        with self._lock:
            return sum(1 for b in self._backends.values()
                       if b.state == "up")

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._backends)

    def snapshot(self) -> list[dict]:
        """The routing table as plain dicts (journal / ``/backends``)."""
        now = time.monotonic()
        with self._lock:
            return [{
                "bid": b.bid, "host": b.host, "port": b.port,
                "generation": b.generation, "state": b.state,
                "inflight": b.inflight, "streams": b.streams,
                "held_s": round(max(0.0, b.hold_until - now), 3),
                "versions": dict(b.versions),
            } for b in sorted(self._backends.values(),
                              key=lambda b: b.bid)]

"""The fleet router: HTTP fan-in with re-route-never-drop failover.

A front-end ``ThreadingHTTPServer`` that proxies the serve HTTP
protocol (``serve/http.py``) over the N backend serve processes in a
:class:`~mmlspark_tpu.serve.fleet.pool.BackendPool`. The contracts:

**Predict failover — resend, because inference is pure.** A
``:predict`` is a deterministic pure function of its rows (the whole
bit-compat discipline of the serving plane), so a transport failure at
ANY point — connect refused, reset mid-body, a torn response — is
answered by resending the same request to another backend: the client
can never observe a dropped answer, and "doubled" has no meaning for a
side-effect-free computation. A backend that answers 429/503 gets a
``Retry-After`` hold in the pool (selection skips it until expiry) and
the request re-routes to a free backend; when EVERY live backend is
held, the router compares the earliest hold expiry against its wait
budget — sleep-and-retry if it fits, else surface the typed 503 with
``Retry-After`` so the client's own retry loop (whose sleep floor
honors the same stamp) takes over.

**Generate failover — replay minus the delivered prefix.** A
``:generate`` stream is pinned to one backend (per-stream affinity via
``pool.stream_lease``: a draining backend finishes its active streams;
new streams route elsewhere). If the backend dies mid-stream, the
router replays the SAME request on another backend and discards the
first ``delivered`` token lines before resuming the client's stream —
decode is deterministic, so the replayed prefix is bit-identical to
what the client already holds and the continuation seams exactly:
strict-prefix preserved, no token dropped, none doubled. A terminal
``{"error": ...}`` line FROM the engine is relayed as-is (that is the
backend's typed answer, not a transport fault).

Fault seams (``serve/faults.py``): ``backend_down`` (before connect),
``backend_slow`` (a ``delay_s`` sleep at the same seam), and
``backend_torn_response`` (per response/token-line read) make the
kill/failover chaos replayable.

The router's own telemetry rides the process registry
(``serve.fleet.router.*`` counters — exported by the fleet telemetry
plane like any other registry), and each proxied request carries an
``X-Fleet-Request-Id`` the backend echoes into its trace as a
``serve/fleet_rx`` event — the span link across the process hop.
"""

from __future__ import annotations

import http.client
import itertools
import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span
from mmlspark_tpu.serve import faults as _faults
from mmlspark_tpu.serve.fleet.pool import BackendPool, NoBackendAvailable

_log = get_logger(__name__)

ROUTER_THREAD = "ServeFleetRouter"

#: what counts as "the backend hop failed" (vs. the backend answering):
#: socket-level faults, HTTP protocol tears, and the injected faults
#: that model them — all safe to re-route
_TRANSPORT_ERRORS = (OSError, http.client.HTTPException,
                     _faults.InjectedFault)


def _parse_retry_after(headers: dict) -> float | None:
    v = headers.get("Retry-After")
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mmlspark-tpu-fleet-router"

    @property
    def _router(self) -> "FleetRouter":
        return self.server.router  # type: ignore[attr-defined]

    def log_message(self, fmt, *args) -> None:
        _log.debug("router %s — %s", self.address_string(), fmt % args)

    # -- responses --

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              headers: dict | None = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict,
                   headers: dict | None = None) -> None:
        self._send(status, json.dumps(payload).encode("utf-8"),
                   headers=headers)

    def _send_no_backend(self, e: NoBackendAvailable) -> None:
        self._router._count("no_backend")
        ra = e.retry_after_s
        if ra is None:
            ra = self._router.default_retry_after_s
        self._send_json(503, {"error": "NoBackendAvailable",
                              "message": str(e)},
                        headers={"Retry-After":
                                 str(max(1, math.ceil(ra)))})

    # -- routes --

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        router = self._router
        if self.path == "/healthz":
            up = router.pool.up_count()
            self._send_json(200 if up else 503,
                            {"ready": up > 0, "backends_up": up},
                            headers=None if up else
                            {"Retry-After": str(max(1, math.ceil(
                                router.default_retry_after_s)))})
        elif self.path == "/livez":
            self._send_json(200, {"alive": True})
        elif self.path == "/backends":
            self._send_json(200, {"backends": router.pool.snapshot(),
                                  "counters": router.counters()})
        else:
            self._send_json(404, {"error": "NotFound",
                                  "message": self.path})

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not (self.path.startswith("/v1/models/")
                and (self.path.endswith(":predict")
                     or self.path.endswith(":generate"))):
            self._send_json(404, {"error": "NotFound",
                                  "message": self.path})
            return
        router = self._router
        router._count("requests")
        name = self.path[len("/v1/models/"):].rsplit(":", 1)[0]
        rid = router._next_request_id()
        with _obs_span("serve.fleet/route", "serve",
                       {"model": name, "request_id": rid,
                        "path": self.path}):
            if self.path.endswith(":generate"):
                self._proxy_generate(name, body, rid)
            else:
                self._proxy_predict(name, body, rid)

    # -- predict proxy --

    def _backend_headers(self, rid: str) -> dict:
        hdrs = {"Content-Type":
                self.headers.get("Content-Type") or "application/json",
                "X-Fleet-Request-Id": rid}
        for h in ("Accept", "X-Deadline-Ms"):
            v = self.headers.get(h)
            if v:
                hdrs[h] = v
        return hdrs

    def _proxy_predict(self, name: str, body: bytes, rid: str) -> None:
        router = self._router
        tried: set[int] = set()
        waited = 0.0
        attempts = 0
        while True:
            try:
                bid = router.pool.pick(exclude=tuple(tried))
            except NoBackendAvailable as e:
                # deadline-aware wait: when every live backend is held
                # and the earliest hold lifts within the wait budget,
                # waiting beats bouncing a 503 to a client that asked
                # for an answer, not an errand
                ra = e.retry_after_s
                if (ra is not None
                        and waited + ra <= router.wait_budget_s):
                    time.sleep(ra)
                    waited += ra
                    continue
                if ra is None and waited < router.wait_budget_s:
                    # every backend marked down, none merely held: a
                    # transient death window. The supervisor's next
                    # beacon revives a survivor (or lands a respawn)
                    # within a beat — wait it out and re-admit
                    # previously tried backends (predict is pure, a
                    # revived backend may be retried)
                    step = min(0.05, router.wait_budget_s - waited)
                    time.sleep(step)
                    waited += step
                    tried.clear()
                    continue
                self._send_no_backend(e)
                return
            attempts += 1
            with router.pool.lease(bid):
                try:
                    status, hdrs, resp = router._forward(
                        bid, name, self.path, body,
                        self._backend_headers(rid))
                except _TRANSPORT_ERRORS as e:
                    # backend death mid-request: a retriable re-route,
                    # never a dropped answer (predict is pure — the
                    # resend recomputes the identical result)
                    if router.pool.mark_down(bid):
                        _log.warning("router: backend %d down (%s)",
                                     bid, e)
                    router._count("reroutes")
                    tried.add(bid)
                    continue
            if status in (429, 503):
                ra = _parse_retry_after(hdrs)
                router.pool.hold(
                    bid, ra if ra is not None
                    else router.default_retry_after_s)
                router._count("held")
                if attempts < router.max_attempts:
                    continue  # pick() now skips the held backend
            router._count("relayed")
            out = {"X-Fleet-Backend": str(bid)}
            for h in ("X-Serve-Identity", "Retry-After"):
                if h in hdrs:
                    out[h] = hdrs[h]
            self._send(status, resp,
                       content_type=hdrs.get("Content-Type",
                                             "application/json"),
                       headers=out)
            return

    # -- generate proxy (streaming, affinity, prefix-skip replay) --

    def _chunk(self, obj: dict) -> None:
        data = json.dumps(obj).encode("utf-8") + b"\n"
        self.wfile.write(f"{len(data):X}\r\n".encode("ascii")
                         + data + b"\r\n")
        self.wfile.flush()

    def _proxy_generate(self, name: str, body: bytes,
                        rid: str) -> None:
        router = self._router
        tried: set[int] = set()
        waited = 0.0
        # replay state OUTLIVES a torn leg: _stream_from mutates these
        # in place so a tear after the status line / after k delivered
        # tokens replays with the truth, not a stale snapshot
        self._g_sent = False       # client status line + headers out?
        self._g_delivered = 0      # token lines the client holds
        while True:
            try:
                bid = router.pool.pick(exclude=tuple(tried))
            except NoBackendAvailable as e:
                # same wait discipline as predict: holds lift, death
                # windows close at the next supervisor beacon — and a
                # stream mid-replay would rather stall a beat than die
                ra = e.retry_after_s
                if (ra is not None
                        and waited + ra <= router.wait_budget_s):
                    time.sleep(ra)
                    waited += ra
                    continue
                if ra is None and waited < router.wait_budget_s:
                    step = min(0.05, router.wait_budget_s - waited)
                    time.sleep(step)
                    waited += step
                    tried.clear()
                    continue
                if not self._g_sent:
                    self._send_no_backend(e)
                else:
                    # mid-stream exhaustion: the status line is gone,
                    # so the failure arrives as the typed terminal
                    # line the protocol already defines
                    router._count("no_backend")
                    self._chunk({"error": "NoBackendAvailable",
                                 "message": str(e)})
                    self.wfile.write(b"0\r\n\r\n")
                return
            with router.pool.stream_lease(bid):
                leg = self._stream_from(router, bid, name, body, rid,
                                        tried)
            if leg is None:
                # torn: replay the SAME request on another backend,
                # skipping the prefix the client already holds
                # (deterministic decode → the skipped lines are
                # bit-identical to what was delivered)
                tried.add(bid)
                router._count("stream_replays")
                continue
            if leg:
                return

    def _stream_from(self, router: "FleetRouter", bid: int, name: str,
                     body: bytes, rid: str,
                     tried: set) -> bool | None:
        """One backend's leg of a :generate stream. Returns None on a
        transport tear (caller replays elsewhere), True when the
        response is complete, False to re-pick (backpressure reroute).
        Mutates ``self._g_sent`` / ``self._g_delivered``."""
        path = f"/v1/models/{name}:generate"
        try:
            host, port = router.pool.address(bid)
            _faults.hit("backend_down", name, bid)
            _faults.hit("backend_slow", name, bid)
            conn = http.client.HTTPConnection(
                host, port, timeout=router.backend_timeout_s)
        except _TRANSPORT_ERRORS:
            router.pool.mark_down(bid)
            return None
        try:
            try:
                conn.request("POST", path, body=body,
                             headers=self._backend_headers(rid))
                resp = conn.getresponse()
            except _TRANSPORT_ERRORS:
                router.pool.mark_down(bid)
                return None
            if resp.status != 200:
                # typed admission answer (Overloaded/BadRequest/...):
                # relay it cleanly — unless it is backpressure and
                # another backend can still take the stream
                data = resp.read()
                hdrs = dict(resp.getheaders())
                if resp.status in (429, 503):
                    ra = _parse_retry_after(hdrs)
                    router.pool.hold(
                        bid, ra if ra is not None
                        else router.default_retry_after_s)
                    router._count("held")
                    if not self._g_sent \
                            and len(tried) + 1 < router.max_attempts:
                        tried.add(bid)
                        return False
                if self._g_sent:  # stream open: typed terminal line
                    self._chunk({"error": "BackendRejected",
                                 "status": resp.status,
                                 "message": data.decode("utf-8",
                                                        "replace")})
                    self.wfile.write(b"0\r\n\r\n")
                else:
                    out = {"X-Fleet-Backend": str(bid)}
                    if "Retry-After" in hdrs:
                        out["Retry-After"] = hdrs["Retry-After"]
                    self._send(resp.status, data,
                               content_type=hdrs.get(
                                   "Content-Type", "application/json"),
                               headers=out)
                return True
            if not self._g_sent:
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.send_header("X-Fleet-Backend", str(bid))
                self.end_headers()
                self._g_sent = True
            skip = self._g_delivered
            try:
                while True:
                    _faults.hit("backend_torn_response", name, bid)
                    line = resp.readline()
                    if not line:
                        break  # EOF before the terminal line: torn
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        break  # half a line: torn mid-write
                    if "token" in obj:
                        if skip > 0:
                            skip -= 1  # replayed prefix: the client
                            continue   # already holds these tokens
                        self._chunk({"token": obj["token"],
                                     "index": self._g_delivered})
                        self._g_delivered += 1
                    elif "error" in obj:
                        # the ENGINE's typed mid-stream failure: relay
                        # as-is — it is the backend's answer, replaying
                        # it elsewhere could double-deliver work the
                        # engine already refused
                        self._chunk(obj)
                        self.wfile.write(b"0\r\n\r\n")
                        return True
                    else:  # the terminal done/summary line
                        self._chunk(obj)
                        self.wfile.write(b"0\r\n\r\n")
                        router._count("relayed")
                        return True
            except _TRANSPORT_ERRORS:
                pass
            router.pool.mark_down(bid)
            return None
        finally:
            conn.close()


class _RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default backlog of 5 resets connections under a
    # fan-in burst; the router is the fleet's front door — queue them
    request_queue_size = 128

    def __init__(self, router: "FleetRouter", address: tuple):
        self.router = router
        super().__init__(address, _RouterHandler)


class FleetRouter:
    """The fan-in front end over a :class:`BackendPool` (module
    docstring has the routing/failover contracts)."""

    def __init__(self, pool: BackendPool, host: str = "127.0.0.1",
                 port: int = 0, max_attempts: int = 3,
                 backend_timeout_s: float = 30.0,
                 wait_budget_s: float = 2.0,
                 default_retry_after_s: float = 1.0):
        self.pool = pool
        self.max_attempts = int(max_attempts)
        self.backend_timeout_s = float(backend_timeout_s)
        self.wait_budget_s = float(wait_budget_s)
        self.default_retry_after_s = float(default_retry_after_s)
        self._rid = itertools.count()
        self._httpd = _RouterHTTPServer(self, (host, port))
        self._thread: threading.Thread | None = None

    # -- lifecycle --

    @property
    def address(self) -> tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return (str(host), int(port))

    def start(self) -> "FleetRouter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=ROUTER_THREAD,
            daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- internals shared with the handler --

    def _next_request_id(self) -> str:
        return f"{os.getpid():x}-{next(self._rid)}"

    def _count(self, name: str) -> None:
        _obs_registry().counter(f"serve.fleet.router.{name}").add()

    def counters(self) -> dict:
        return {m.name: m.value
                for m in _obs_registry().iter_metrics()
                if m.name.startswith("serve.fleet.router.")}

    def _forward(self, bid: int, name: str, path: str, body: bytes,
                 headers: dict) -> tuple[int, dict, bytes]:
        """One predict hop: connect, send, read the whole answer.
        Raises a ``_TRANSPORT_ERRORS`` member on any failure — the
        caller's cue to re-route. Fault seams fire here so chaos
        schedules can model a dead backend (``backend_down``), a slow
        one (``backend_slow``), and a response torn mid-read
        (``backend_torn_response``)."""
        host, port = self.pool.address(bid)
        _faults.hit("backend_down", name, bid)
        _faults.hit("backend_slow", name, bid)
        conn = http.client.HTTPConnection(
            host, port, timeout=self.backend_timeout_s)
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            _faults.hit("backend_torn_response", name, bid)
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

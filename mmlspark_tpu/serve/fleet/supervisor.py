"""The fleet actuator: launch, watch, heal, and scale serve backends.

:class:`ServeSupervisor` is the serve-side sibling of
``train/service.py``'s :class:`TrainSupervisor`, built on the SAME
shared supervision core (``mmlspark_tpu/service/``): beacons are the
sensor transport (``atomic_write_json``/``read_beacon``,
generation-checked), :class:`SupervisedProcess` wraps each child with
its output pump, recovery runs through the train service's PURE
:class:`RecoveryPolicy` (restart-with-backoff, budgeted), and every
decision lands in ``decisions.jsonl`` via :class:`SupervisorJournal`
(mirrored as obs ``fleet/*`` events + ``serve.fleet.*`` counters when
the tracer is on).

What is serve-specific:

* the beacon carries a PORT — backends bind ephemerally and the beacon
  is how the supervisor learns the address it feeds the shared
  :class:`~mmlspark_tpu.serve.fleet.pool.BackendPool` (the router's
  routing table). A backend is routable the moment its first
  ``running`` beacon lands and unroutable the moment its process dies
  (``mark_down``) — the router's transport-failure evidence and the
  supervisor's exit-code evidence converge on the same table.
* restarts point the fresh process at the SAME compile cache
  (``MMLSPARK_TPU_COMPILE_CACHE``), so a respawned or scaled-up
  backend warms its whole bucket ladder from PR 15 AOT artifacts —
  zero fresh XLA compiles on the serving path (the fleet gate pins
  this off the beacon's cache stats).
* the autoscaling loop: each watch tick aggregates the beacons'
  SLO reads (PR 14 ``serve.slo_burn_*`` fast-window burn, occupancy)
  into a :class:`~mmlspark_tpu.obs.timeseries.MetricHistory`
  (``serve.fleet.burn_max`` / ``serve.fleet.occupancy_mean``), and
  :class:`~mmlspark_tpu.serve.fleet.scale.ScalePolicy` — pure, like
  every policy here — decides ScaleUp/ScaleDown/Hold. Scale-down is
  ZERO-DROP by construction: the victim is drained in the pool first
  (no new work routes to it, active :generate streams keep their
  affinity), and SIGTERM is sent only once its last lease/stream is
  gone; the worker then drains its own queue and exits 0.

Threading: ONE watch thread (``ServeFleetWatch``) owns all supervisor
state. The public surface (``scale_up``/``scale_down``/``close``)
enqueues typed commands under a ``named_lock`` witness — nothing
blocks under the lock (CC102), the watch thread is joined on close
(CC104).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Sequence

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import fleet as _obs_fleet
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.timeseries import MetricHistory
from mmlspark_tpu.serve.fleet.pool import BackendPool
from mmlspark_tpu.serve.fleet.scale import (
    BURN_SERIES, OCCUPANCY_SERIES, FleetLedger, ScaleDown, ScalePolicy,
    ScaleUp, signal_from_history,
)
from mmlspark_tpu.service.core import (
    SupervisedProcess, SupervisorJournal, read_beacon,
    terminate_processes, join_pumps,
)
from mmlspark_tpu.train.service import (
    ENV_DIR, ENV_GENERATION, ENV_RANK, ENV_WORLD, Fail, Ledger, Proceed,
    RecoveryPolicy, Restart, WorkerExit, WorkerHang,
)

_log = get_logger(__name__)

WATCH_THREAD = "ServeFleetWatch"

# worker-side ServeConfig knobs the supervisor passes through the env
# (defined here, NOT in worker.py, so launching `-m ...fleet.worker`
# does not find the worker module pre-imported by the package __init__)
ENV_SLO = "MMLSPARK_TPU_SERVE_FLEET_SLO"
ENV_MAX_QUEUE = "MMLSPARK_TPU_SERVE_FLEET_MAX_QUEUE"
ENV_REPO = "MMLSPARK_TPU_SERVE_FLEET_REPO"  # model repo root: workers
#   serve every repo model's CURRENT version at boot and accept
#   versioned hot-swap commands from the lifecycle deployer's
#   deploy.json (serve/fleet/worker.py watches it each beacon tick)


def _default_worker_cmd() -> list[str]:
    return [sys.executable, "-m", "mmlspark_tpu.serve.fleet.worker"]


def _ensure_importable(env: dict) -> None:
    """Prepend the directory holding ``mmlspark_tpu`` to the child's
    ``PYTHONPATH`` so the default ``-m ...fleet.worker`` spawn resolves
    regardless of the caller's cwd (a CLI launched from a scratch dir
    imports the package off ``sys.path``, which children don't inherit)."""
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    prior = env.get("PYTHONPATH")
    if prior:
        if pkg_parent in prior.split(os.pathsep):
            return
        env["PYTHONPATH"] = pkg_parent + os.pathsep + prior
    else:
        env["PYTHONPATH"] = pkg_parent


@dataclasses.dataclass
class FleetConfig:
    """Supervisor configuration. ``cmd`` is one backend's argv (default:
    the built-in self-test worker the gate and bench use), launched once
    per backend with the shared ``MMLSPARK_TPU_SERVICE_*`` env contract
    — rank is the backend id, generation counts that backend's
    restarts."""

    service_dir: str
    cmd: Sequence[str] | None = None
    initial_backends: int = 2
    # preempt_exit_codes=(): a serve backend has no topology ladder to
    # re-scale down, so EVERY death takes the budgeted restart path
    policy: RecoveryPolicy = RecoveryPolicy(
        rescale_on_exhausted=False, preempt_exit_codes=())
    scale: ScalePolicy = dataclasses.field(default_factory=ScalePolicy)
    scale_window_s: float = 60.0  # history window the signal condenses
    poll_s: float = 0.1
    grace_s: float = 10.0
    beacon_timeout_s: float | None = 15.0  # alive-but-silent deadline
    start_grace_s: float | None = 120.0  # FIRST-beacon deadline: a cold
    #   backend pays jax import + (cache-miss) XLA compiles before it
    #   can beacon at all, so startup gets its own allowance — the
    #   beacon_timeout_s stall deadline applies once it has beaconed
    compile_cache: str | None = None       # → MMLSPARK_TPU_COMPILE_CACHE
    repo: str | None = None                # → ENV_REPO (lifecycle repo)
    slo: dict | None = None                # → worker ServeConfig.slo
    max_queue: int | None = None           # → worker ServeConfig.max_queue
    worker_obs: bool = True
    worker_fleet: bool = True  # propagate this process's fleet dir so
    #                            backends export serve.* telemetry into
    #                            the same plane (obs/fleet.py)
    extra_env: dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.initial_backends < 1:
            raise ValueError("initial_backends must be >= 1: "
                             f"{self.initial_backends}")


class _Backend(SupervisedProcess):
    """One supervised backend process + its fleet-side bookkeeping."""

    def __init__(self, bid: int, proc: subprocess.Popen):
        super().__init__(bid, proc, log_prefix="fleet backend",
                         thread_name=f"{WATCH_THREAD}[pump{bid}]")
        self.generation = 0
        self.ledger = Ledger()   # per-backend restart budget
        self.draining = False    # scale-down in progress
        self.term_sent = False   # SIGTERM already delivered (drain)
        self.last_beacon_ts: float | None = None


@dataclasses.dataclass
class _Respawn:
    """A restart the policy granted, waiting out its backoff."""
    bid: int
    generation: int
    due: float  # monotonic
    ledger: Ledger


class ServeSupervisor:
    """Launch/watch/heal/scale the backend fleet (module docstring).

    ``start()`` spawns the initial backends and the watch thread;
    ``pool`` (shared with the :class:`FleetRouter`) is the live routing
    table this supervisor maintains. ``close()`` stops everything
    thread-clean."""

    def __init__(self, cfg: FleetConfig, pool: BackendPool | None = None):
        self.cfg = cfg
        self.pool = pool if pool is not None else BackendPool()
        os.makedirs(cfg.service_dir, exist_ok=True)
        self._journal = SupervisorJournal(
            os.path.join(cfg.service_dir, "decisions.jsonl"),
            event_prefix="fleet", cat="fleet",
            counter_prefix="serve.fleet.",
            counter_kinds=("spawn", "restart", "scale_up", "scale_down",
                           "backend_exit", "hang", "fail", "drained"),
            log_label="serve fleet")
        self.history = MetricHistory(maxlen=4096)
        self._backends: dict[int, _Backend] = {}  # watch-thread-owned
        self._respawns: list[_Respawn] = []
        self._next_bid = 0
        self._fleet_ledger = FleetLedger()
        self._last_scale: float | None = None  # monotonic
        self._cmd_lock = named_lock("serve.fleet.supervisor")
        self._commands: deque[str] = deque()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch,
                                        name=WATCH_THREAD, daemon=True)
        self._started = False
        self._closed = False

    # -- public surface (any thread): enqueue, never touch state --

    def start(self) -> "ServeSupervisor":
        if self._started:
            return self
        self._started = True
        for _ in range(self.cfg.initial_backends):
            self._spawn(self._alloc_bid(), generation=0, ledger=Ledger())
        self._thread.start()
        return self

    def scale_up(self) -> None:
        """Request one more backend (journaled as a manual scale-up)."""
        with self._cmd_lock:
            self._commands.append("scale_up")

    def scale_down(self) -> None:
        """Request a zero-drop drain of one backend."""
        with self._cmd_lock:
            self._commands.append("scale_down")

    def close(self) -> None:
        """Stop the watch thread, terminate every backend (SIGTERM →
        grace → kill), join the pumps. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)
        workers = list(self._backends.values())
        terminate_processes(workers, self.cfg.grace_s)
        join_pumps(workers)
        for b in workers:
            self.pool.remove(b.rank)
        self._backends.clear()
        self._journal.record("stop", {
            "backends": len(workers),
            "scale_ups": self._fleet_ledger.scale_ups,
            "scale_downs": self._fleet_ledger.scale_downs})

    def __enter__(self) -> "ServeSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def status(self) -> dict:
        """Point-in-time fleet view (CLI/debugging; the pool snapshot is
        the authoritative routing table). ``rollout`` condenses the
        beacon-reported served versions into the convergence view the
        lifecycle deployer blocks fleet-wide promotion on: a model is
        converged when every up backend serves the same repo version."""
        backends = self.pool.snapshot()
        by_model: dict[str, set] = {}
        for row in backends:
            if row["state"] != "up":
                continue
            for model, version in row["versions"].items():
                by_model.setdefault(model, set()).add(version)
        return {
            "backends": backends,
            "rollout": {
                model: {"converged": len(vs) == 1,
                        "versions": sorted(vs)}
                for model, vs in sorted(by_model.items())},
            "respawns_pending": len(self._respawns),
            "scale_ups": self._fleet_ledger.scale_ups,
            "scale_downs": self._fleet_ledger.scale_downs,
        }

    # -- spawn/respawn (watch thread, or start() before it runs) --

    def _alloc_bid(self) -> int:
        bid = self._next_bid
        self._next_bid += 1
        return bid

    def _spawn(self, bid: int, generation: int, ledger: Ledger) -> None:
        env = dict(os.environ)
        env.update(self.cfg.extra_env)
        env[ENV_DIR] = self.cfg.service_dir
        env[ENV_RANK] = str(bid)
        env[ENV_WORLD] = "1"  # backends are independent replicas, not
        #                       a mesh — no cross-process collectives
        env[ENV_GENERATION] = str(generation)
        if self.cfg.compile_cache:
            env["MMLSPARK_TPU_COMPILE_CACHE"] = self.cfg.compile_cache
        if self.cfg.repo:
            env[ENV_REPO] = self.cfg.repo
        if self.cfg.slo is not None:
            env[ENV_SLO] = json.dumps(self.cfg.slo)
        if self.cfg.max_queue is not None:
            env[ENV_MAX_QUEUE] = str(self.cfg.max_queue)
        if self.cfg.worker_obs:
            env.setdefault("MMLSPARK_TPU_OBS", "1")
        if self.cfg.worker_fleet:
            fdir = _obs_fleet.fleet_dir()
            if fdir:
                env.setdefault("MMLSPARK_TPU_FLEET", fdir)
        if self.cfg.cmd:
            cmd = list(self.cfg.cmd)
        else:
            cmd = _default_worker_cmd()
            _ensure_importable(env)
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                errors="replace")
        b = _Backend(bid, proc)
        b.generation = generation
        b.ledger = ledger
        self._backends[bid] = b
        self._journal.record("spawn", {
            "bid": bid, "generation": generation, "pid": proc.pid,
            "compile_cache": self.cfg.compile_cache})

    # -- the watch loop (single owner of all supervisor state) --

    def _watch(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self._drain_commands()
                self._reap_exits()
                self._run_respawns()
                self._read_beacons()
                self._step_drains()
                self._scale_tick()
            except Exception:  # pragma: no cover - the watch must
                _log.exception("serve fleet watch tick failed")  # survive

    def _drain_commands(self) -> None:
        while True:
            with self._cmd_lock:
                cmd = self._commands.popleft() if self._commands \
                    else None
            if cmd is None:
                return
            if cmd == "scale_up":
                self._execute_scale_up("manual scale_up request")
            elif cmd == "scale_down":
                self._execute_scale_down("manual scale_down request")

    def _reap_exits(self) -> None:
        for bid, b in list(self._backends.items()):
            code = b.proc.poll()
            if code is None or b.exit_recorded:
                continue
            b.exit_recorded = True
            was_routable = self.pool.mark_down(bid)
            self._journal.record("backend_exit", {
                "bid": bid, "generation": b.generation, "code": code,
                "draining": b.draining, "was_routable": was_routable})
            if b.draining:
                # the zero-drop drain completing: expected, clean
                self.pool.remove(bid)
                join_pumps([b])
                del self._backends[bid]
                self._journal.record("drained", {"bid": bid,
                                                 "code": code})
                continue
            action = self.cfg.policy.decide(WorkerExit(bid, code),
                                            b.ledger)
            if isinstance(action, Proceed):
                # exit 0 without a drain request is still capacity loss
                # — recover it, but keep it bounded by the same restart
                # budget so a clean-exit loop cannot spin forever
                if b.ledger.restarts_used < self.cfg.policy.max_restarts:
                    action = Restart("backend exited cleanly without a "
                                     "drain request", delay_s=0.5)
                else:
                    action = Fail("clean-exit loop; restart budget "
                                  f"({self.cfg.policy.max_restarts}) "
                                  "exhausted")
            self._apply_recovery(b, action)

    def _apply_recovery(self, b: _Backend, action) -> None:
        bid = b.rank
        join_pumps([b])
        del self._backends[bid]
        if isinstance(action, Restart):
            b.ledger.restarts_used += 1
            self._journal.record("restart", {
                "bid": bid, "reason": action.reason,
                "delay_s": round(action.delay_s, 3),
                "restarts_used": b.ledger.restarts_used,
                "generation": b.generation + 1})
            self._respawns.append(_Respawn(
                bid, b.generation + 1,
                time.monotonic() + action.delay_s, b.ledger))
            return
        # Fail (or any non-restart action a custom policy returns):
        # this backend stays down; the pool forgets it
        self.pool.remove(bid)
        self._journal.record("fail", {
            "bid": bid,
            "reason": getattr(action, "reason", repr(action))})

    def _run_respawns(self) -> None:
        now = time.monotonic()
        due = [r for r in self._respawns if r.due <= now]
        self._respawns = [r for r in self._respawns if r.due > now]
        for r in due:
            self._spawn(r.bid, r.generation, r.ledger)

    def _read_beacons(self) -> None:
        burns, occs = [], []
        now_mono = time.monotonic()
        # snapshot: a hang verdict mutates _backends via _apply_recovery
        for bid, b in list(self._backends.items()):
            if b.proc.poll() is not None:
                continue
            beacon = read_beacon(self.cfg.service_dir, bid, b.generation)
            if beacon is None or beacon.get("status") not in (
                    "running", "draining"):
                # alive but silent past the deadline → hang signal (the
                # baseline is spawn time via SupervisedProcess); a
                # backend that has NEVER beaconed is still booting and
                # gets the start grace instead of the stall deadline
                deadline = (self.cfg.start_grace_s
                            if b.last_beacon_ts is None
                            and self.cfg.start_grace_s is not None
                            else self.cfg.beacon_timeout_s)
                if (deadline is not None and not b.draining
                        and now_mono - b.progress_ts > deadline):
                    self._hang(b, now_mono - b.progress_ts)
                continue
            ts = beacon.get("ts")
            if ts != b.last_beacon_ts:
                b.last_beacon_ts = ts
                b.progress_ts = now_mono
            if beacon.get("status") == "running":
                # the beacon is the address channel: first beacon makes
                # the backend routable; a draining pool entry is never
                # resurrected by a late beacon (pool.add preserves it)
                self.pool.add(bid, str(beacon.get("host", "127.0.0.1")),
                              int(beacon.get("port", 0)),
                              generation=b.generation,
                              versions=beacon.get("versions"))
            if not b.draining:
                burns.append(float(beacon.get("burn_short", 0.0)))
                occs.append(float(beacon.get("occupancy", 0.0)))
        now = time.time()
        if burns:
            self.history.append(now, BURN_SERIES, max(burns))
        if occs:
            self.history.append(now, OCCUPANCY_SERIES,
                                sum(occs) / len(occs))
        if _obs_rt._enabled:
            reg = _obs_registry()
            reg.gauge("serve.fleet.backends").set(self.pool.up_count())
            if burns:
                reg.gauge(BURN_SERIES).set(max(burns))
            if occs:
                reg.gauge(OCCUPANCY_SERIES).set(sum(occs) / len(occs))

    def _hang(self, b: _Backend, stalled_s: float) -> None:
        bid = b.rank
        self.pool.mark_down(bid)
        self._journal.record("hang", {
            "bid": bid, "generation": b.generation,
            "stalled_s": round(stalled_s, 3)})
        action = self.cfg.policy.decide(WorkerHang(bid, stalled_s),
                                        b.ledger)
        terminate_processes([b], self.cfg.grace_s)
        b.exit_recorded = True
        self._apply_recovery(b, action)

    def _step_drains(self) -> None:
        """Advance zero-drop drains: SIGTERM a draining backend only
        once the pool shows its last lease/stream gone — the worker
        then drains its own queue and exits 0 (reaped as ``drained``)."""
        for b in self._backends.values():
            if (b.draining and not b.term_sent
                    and b.proc.poll() is None
                    and self.pool.idle(b.rank)):
                try:
                    b.proc.terminate()
                except OSError:  # pragma: no cover - exited just now
                    pass
                b.term_sent = True

    # -- autoscaling --

    def _live_count(self) -> int:
        """Backends the fleet counts as capacity: spawned and not
        draining (a pending respawn still owns its slot — a restart
        must not read as a capacity drop and trigger a scale-up)."""
        managed = sum(1 for b in self._backends.values()
                      if not b.draining)
        return managed + len(self._respawns)

    def _scale_tick(self) -> None:
        now_mono = time.monotonic()
        self._fleet_ledger.since_scale_s = (
            float("inf") if self._last_scale is None
            else now_mono - self._last_scale)
        sig = signal_from_history(
            self.history, now=time.time(), backends=self._live_count(),
            policy=self.cfg.scale, window_s=self.cfg.scale_window_s)
        action = self.cfg.scale.decide(sig, self._fleet_ledger)
        if isinstance(action, ScaleUp):
            self._execute_scale_up(action.reason)
        elif isinstance(action, ScaleDown):
            self._execute_scale_down(action.reason)

    def _execute_scale_up(self, reason: str) -> None:
        bid = self._alloc_bid()
        self._journal.record("scale_up", {
            "bid": bid, "reason": reason,
            "backends": self._live_count()})
        self._spawn(bid, generation=0, ledger=Ledger())
        self._fleet_ledger.scale_ups += 1
        self._last_scale = time.monotonic()

    def _execute_scale_down(self, reason: str) -> None:
        # victim: the least-loaded up backend (the cheapest zero-drop
        # drain); ties break toward the NEWEST bid so the original
        # fleet core is the last to go
        candidates = [s for s in self.pool.snapshot()
                      if s["state"] == "up"
                      and s["bid"] in self._backends
                      and not self._backends[s["bid"]].draining]
        if not candidates:
            self._journal.record("scale_down_skipped",
                                 {"reason": reason,
                                  "detail": "no drainable backend"})
            return
        victim = min(candidates,
                     key=lambda s: (s["inflight"] + s["streams"],
                                    -s["bid"]))["bid"]
        self.pool.drain(victim)
        self._backends[victim].draining = True
        self._journal.record("scale_down", {
            "bid": victim, "reason": reason,
            "backends": self._live_count()})
        self._fleet_ledger.scale_downs += 1
        self._last_scale = time.monotonic()

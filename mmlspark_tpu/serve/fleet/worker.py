"""The runnable backend serve worker the fleet supervisor launches.

``python -m mmlspark_tpu.serve.fleet.worker`` under the supervisor's
env contract (the SAME ``MMLSPARK_TPU_SERVICE_*`` contract as train
workers — the shared ``mmlspark_tpu/service`` core reads the beacons
either way):

* builds the deterministic self-test CNN (the ``check_compile_cache``
  model: seeded ``get_model`` → bit-identical params in every process,
  so every backend computes bit-identical answers — the property the
  fleet gate pins through the router),
* serves it over HTTP on an EPHEMERAL port (the beacon, not the env,
  carries the port back to the supervisor — no port-allocation race),
* publishes a liveness beacon each interval with the bound port, the
  SLO burn/occupancy excerpt (the autoscaler's sensors), a ``serve.*``
  counter excerpt (the fleet-merge pin's per-backend truth), and the
  compile-cache stats (how the gate proves a scaled-up backend warmed
  from the PR 15 cache with zero fresh XLA compiles),
* on SIGTERM: beacon ``draining``, zero-drop drain
  (``ModelServer.close(drain=True)`` — queued work finishes), beacon
  ``exited``, exit 0.

The compile cache arrives via ``MMLSPARK_TPU_COMPILE_CACHE`` (honored
by ``ServeConfig(compile_cache=None)``); the SLO spec via
``MMLSPARK_TPU_SERVE_FLEET_SLO`` (a JSON dict of ``SLOSpec`` fields —
the gate tightens the windows so induced burn shows within a beacon
interval or two).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.serve.fleet.supervisor import ENV_MAX_QUEUE, \
    ENV_REPO, ENV_SLO

_log = get_logger(__name__)

DEPLOY_FILE = "deploy.json"  # the lifecycle deployer's command channel

MODEL_NAME = "cnn"
SELFTEST_BUCKETS = (1, 8)
ROW_DIM = 32 * 32 * 3

GEN_NAME = "lm"
GEN_VOCAB = 48
GEN_T_MAX = 64


def selftest_bundle():
    """The fleet's deterministic serve workload: the seeded ConvNet the
    ``check_compile_cache`` gate already proves bit-identical and
    cache-warmable across processes."""
    from mmlspark_tpu.models.zoo import get_model
    return get_model("ConvNet_CIFAR10", widths=(8, 16), dense_width=32)


def selftest_rows(n: int, seed: int = 7) -> np.ndarray:
    """Deterministic uint8 image rows (the dtype the model is warmed
    with — same program family on every backend)."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, (n, ROW_DIM)).astype(np.uint8)


def selftest_generator():
    """A seeded causal toy LM for the ``:generate`` surface: PRNGKey(0)
    init → bit-identical params (and greedy decodes) in every backend,
    the same determinism contract as the CNN."""
    import jax
    from mmlspark_tpu.models.sequence import TransformerTagger

    model = TransformerTagger(vocab_size=GEN_VOCAB, embed_dim=16,
                              num_heads=2, num_layers=2, mlp_dim=32,
                              num_tags=GEN_VOCAB, max_len=GEN_T_MAX,
                              causal=True)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return model, params


def build_server():
    """The worker's ModelServer: self-test CNN + toy causal LM, (1, 8)
    ladder, SLO from the env. Shared with the bench/gate reference
    instance so "router answer == single-process answer" compares
    equals against equals."""
    from mmlspark_tpu.data.table import DataTable
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve import GenerateConfig, ModelServer, \
        ServeConfig

    slo = None
    raw = os.environ.get(ENV_SLO)
    if raw:
        slo = json.loads(raw)
    cfg = ServeConfig(
        buckets=SELFTEST_BUCKETS, deadline_ms=None, slo=slo,
        max_queue=int(os.environ.get(ENV_MAX_QUEUE, "128")))
    server = ModelServer(cfg)
    jm = JaxModel(model=selftest_bundle(), input_col="image",
                  output_col="scores")
    server.add_model(MODEL_NAME, jm,
                     example=DataTable({"image": [selftest_rows(1)[0]]}))
    gen_model, gen_params = selftest_generator()
    server.add_generator(GEN_NAME, gen_model, gen_params,
                         config=GenerateConfig(
                             slots=4, t_max=GEN_T_MAX,
                             prefill_buckets=(4, 8), prefill_rows=2,
                             max_new_tokens=16, max_queue=64))
    repo_root = os.environ.get(ENV_REPO)
    if repo_root:
        _serve_repo_models(server, repo_root)
    return server


def _serve_repo_models(server, repo_root: str) -> None:
    """Serve every repo model's CURRENT version (digest-verified by
    ``add_model_from_repo``; a ModelBundle auto-wraps to a JaxModel with
    the bundle's own input/output columns). A model that fails to load
    is skipped with a warning — one corrupt publish must not keep the
    whole backend from coming up; the beacon's ``versions`` map simply
    won't list it, which the deployer reads as non-convergence."""
    from mmlspark_tpu.models.repo import ModelRepo

    repo = ModelRepo(repo_root)
    for name in repo.models():
        try:
            server.add_model_from_repo(repo, name)
        except Exception as e:
            _log.warning("fleet backend: repo model %r skipped: %s",
                         name, e)


class _DeployWatcher:
    """Apply versioned hot-swap commands from the lifecycle deployer.

    The deployer (``lifecycle/deployer.py`` :class:`FleetTarget`) writes
    ``<service_dir>/deploy.json`` — ``{"seq", "model", "version",
    "repo", "backends"}`` — atomically; each backend polls it every
    beacon interval and applies each NEW seq addressed to it (scope
    ``"all"`` or an explicit bid list) via ``add_model_from_repo``:
    digests verify before anything deserializes, the flip is the
    server's own zero-drop swap. A failed apply is reported in the
    beacon (``deploy_error``) and NOT retried for the same seq — the
    beacon's ``versions`` map stays on the old version, the deployer
    reads that as non-convergence and its policy decides (hold until
    ``max_stage_ticks``, then abort → rollback)."""

    def __init__(self, info, server):
        self.info = info
        self.server = server
        self.path = os.path.join(info.service_dir, DEPLOY_FILE)
        self.seq = 0
        self.error: str | None = None

    def poll(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                cmd = json.load(f)
            seq = int(cmd.get("seq", 0))
        except (OSError, ValueError, TypeError):
            return
        if seq <= self.seq:
            return
        self.seq = seq
        scope = cmd.get("backends")
        if scope != "all" and self.info.rank not in (scope or ()):
            return
        try:
            self.server.add_model_from_repo(
                str(cmd["repo"]), str(cmd["model"]),
                version=int(cmd["version"]))
            self.error = None
            _log.info("fleet backend %d: deploy seq %d → %s v%d",
                      self.info.rank, seq, cmd["model"],
                      int(cmd["version"]))
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            _log.warning("fleet backend %d: deploy seq %d failed: %s",
                         self.info.rank, seq, self.error)

    def describe(self) -> dict:
        out: dict = {"deploy_seq": self.seq}
        if self.error:
            out["deploy_error"] = self.error
        return out


def _beacon_sample(info, server, port: int, status: str,
                   deploy: _DeployWatcher | None = None) -> dict:
    """One beacon payload: identity + port + the autoscaler's sensors
    + the fleet-merge counter excerpt + compile-cache stats + the
    served ``{model: repo version}`` map (the deployer's rollout-
    convergence sensor)."""
    from mmlspark_tpu.core import compile_cache as _cc
    from mmlspark_tpu.obs.metrics import Counter as _ObsCounter
    from mmlspark_tpu.obs.metrics import registry as _obs_registry

    sample: dict = {
        "rank": info.rank, "pid": os.getpid(),
        "generation": info.generation,
        "ts": time.time(), "status": status,
        "host": "127.0.0.1", "port": port,
        "model": MODEL_NAME,
        "burn_short": 0.0, "occupancy": 0.0,
        "counters": [], "compile_cache": None,
        "versions": {},
    }
    if deploy is not None:
        sample.update(deploy.describe())
    try:
        sample["versions"] = {
            name: snap["version"]
            for name, snap in server.snapshot().items()
            if isinstance(snap, dict) and "version" in snap}
    except Exception:  # pragma: no cover - beacon never kills the worker
        pass
    try:
        # each beacon is one SLO sample per model (registry reads only)
        # — the sampling cadence that feeds the supervisor's
        # MetricHistory, mirroring how /slo polls drive it in-process
        slo = server.slo_snapshot()
        burns = [m.get("burn_rate_short") for m in slo.values()
                 if isinstance(m, dict)]
        occs = [m.get("occupancy_mean") for m in slo.values()
                if isinstance(m, dict)]
        sample["burn_short"] = max(
            (b for b in burns if b is not None), default=0.0)
        sample["occupancy"] = max(
            (o for o in occs if o is not None), default=0.0)
    except Exception:  # pragma: no cover - beacon never kills the worker
        pass
    try:
        for reg in [_obs_registry()] + server.metric_registries():
            for m in reg.iter_metrics():
                if isinstance(m, _ObsCounter) \
                        and m.name.startswith("serve."):
                    sample["counters"].append(
                        [m.name, dict(m.labels), m.value])
        cache = _cc.active()
        if cache is not None:
            sample["compile_cache"] = dict(cache.stats)
    except Exception:  # pragma: no cover
        pass
    return sample


def run_backend_worker(beacon_interval_s: float = 0.25) -> int:
    """The worker main: serve until SIGTERM, beaconing all the while."""
    from mmlspark_tpu.service.core import atomic_write_json
    from mmlspark_tpu.serve.http import start_http_server
    from mmlspark_tpu.train.service import ServiceWorkerInfo

    info = ServiceWorkerInfo.from_env()
    if info is None:
        raise SystemExit("not under a fleet supervisor "
                         "(MMLSPARK_TPU_SERVICE_DIR unset)")
    os.makedirs(info.service_dir, exist_ok=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    server = build_server()
    deploy = _DeployWatcher(info, server)
    httpd = start_http_server(server, host="127.0.0.1", port=0,
                              identity=f"backend-{info.rank}")
    port = int(httpd.server_address[1])
    _log.info("fleet backend %d (gen %d) serving on 127.0.0.1:%d",
              info.rank, info.generation, port)
    try:
        while not stop.wait(beacon_interval_s):
            deploy.poll()
            try:
                atomic_write_json(
                    info.beacon_path(),
                    _beacon_sample(info, server, port, "running",
                                   deploy=deploy))
            except Exception:  # pragma: no cover - beacon never kills
                pass           # the worker it reports on
        # zero-drop drain: announce, stop admitting, finish what's
        # queued/in flight, then the terminal beacon
        atomic_write_json(info.beacon_path(),
                          _beacon_sample(info, server, port, "draining",
                                         deploy=deploy))
        server.close(drain=True)
    finally:
        httpd.shutdown()
        httpd.server_close()
        try:
            atomic_write_json(info.beacon_path(),
                              _beacon_sample(info, server, port,
                                             "exited", deploy=deploy))
        except Exception:  # pragma: no cover - best-effort terminal
            pass
    return 0


if __name__ == "__main__":
    raise SystemExit(run_backend_worker())

"""Serving configuration — the bucket ladder and admission bounds."""

from __future__ import annotations

import dataclasses

from mmlspark_tpu.serve.errors import BadRequest

DEFAULT_BUCKETS = (1, 8, 32, 128)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Knobs of one :class:`~mmlspark_tpu.serve.ModelServer`.

    ``buckets`` is the fixed ladder request batches are padded onto. On
    TPU every distinct input shape is a fresh XLA compilation, so the
    batcher never dispatches a raw coalesced size: it packs whole requests
    up to the largest bucket and pads to the smallest bucket that fits —
    at most ``len(buckets)`` compiled programs per (model, entry layout).
    A denser ladder wastes less padding compute per dispatch; a sparser
    one compiles (and warms) fewer programs. The entry *layout* (per-row
    shape AND dtype) is part of the program identity: clients that send
    e.g. uint8 image bytes where warmup used float32 pay one extra
    compile per bucket on first contact — warm with an ``example`` (or a
    ``--schema``) matching the production dtype. See docs/serving.md.
    """

    buckets: tuple = DEFAULT_BUCKETS
    max_queue: int = 128        # queued requests per model; admission bound
    deadline_ms: float | None = None  # default per-request deadline
    max_inflight: int = 2       # dispatched-but-undrained batches PER
    #                             REPLICA LANE (HBM and latency bound on
    #                             each lane's async window)
    warmup: bool = True         # compile every bucket at load time
    stats_window: int = 4096    # per-model latency reservoir bound
    drain_timeout_s: float = 30.0  # close(drain=True) join bound
    mesh: object = None         # server-wide default serving mesh — a
    #                             ServeMeshSpec / "dp=N[,tp=M][,pp=K]"
    #                             string / dict (serve.mesh); None keeps
    #                             the single whole-mesh dispatch lane.
    #                             add_model(mesh=...) overrides per model
    slo: object = None          # per-model SLO — an obs.slo.SLOSpec /
    #                             dict of its fields / None (the default
    #                             spec). Drives the /slo burn-rate
    #                             surface and the /healthz state machine
    #                             (docs/observability.md)
    retry_after_s: float = 1.0  # the Retry-After hint on 429/503 HTTP
    #                             responses: how long a backpressured or
    #                             drain-bounced client should wait before
    #                             retrying (rounded UP to whole seconds
    #                             on the wire — the header's unit)
    lane_restart: object = None  # lane self-healing pacing — a
    #                             core.retry.RetryPolicy (None = the
    #                             default: 3 restarts, 50 ms..2 s
    #                             deterministic exponential backoff).
    #                             A dead/wedged dispatch lane has its
    #                             undispatched batches requeued onto
    #                             surviving lanes and is restarted under
    #                             this schedule; past the budget the
    #                             lane stays down and health degrades
    lifecycle_dir: str | None = None  # model-lifecycle decision journal:
    #                             swap/canary/promote/rollback (and lane
    #                             death/restart) decisions append to
    #                             <dir>/decisions.jsonl — the serve
    #                             analog of the training service's
    #                             supervision forensics. None = journal
    #                             kept in memory only
    precision: object = None    # server-wide default serving precision —
    #                             a core.precision.PrecisionPolicy /
    #                             "f32"|"bf16"|"int8w" string / dict of
    #                             policy fields / None (= f32, the
    #                             historical byte-identical programs).
    #                             add_model(precision=...) overrides per
    #                             model; parity vs the f32 offline
    #                             transform is calibrated at load
    #                             (docs/quantization.md)
    compile_cache: str | None = None  # persistent AOT compile-cache dir
    #                             (core/compile_cache.py): compiled
    #                             bucket programs serialize to disk and
    #                             cold processes warm-start by
    #                             DESERIALIZING the ladder instead of
    #                             re-compiling it. None honors
    #                             MMLSPARK_TPU_COMPILE_CACHE; an
    #                             unwritable dir degrades to a warning +
    #                             in-memory compiles (docs/serving.md
    #                             §compile cache)

    def __post_init__(self):
        # a misordered/duplicated ladder used to be silently repaired
        # here; it now refuses at load with the typed error the serve
        # plane uses for every bad-model-config refusal (a mis-sorted
        # ladder in a config file is a deploy bug, not an intent)
        from mmlspark_tpu.serve.errors import ModelLoadError
        from mmlspark_tpu.serve.ladder import validate_ladder
        try:
            buckets = validate_ladder(self.buckets)
        except ValueError as e:
            raise ModelLoadError("<config>", message=str(e))
        object.__setattr__(self, "buckets", buckets)
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1: {self.max_queue}")
        if self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1: {self.max_inflight}")
        if self.retry_after_s <= 0:
            raise ValueError(
                f"retry_after_s must be > 0: {self.retry_after_s}")

    def lane_restart_policy(self):
        """The lane supervisor's restart pacing (``lane_restart`` or the
        default). Deterministic (jitter=0) by default: lane restarts are
        a single server's recovery, not a thundering herd, and a
        reproducible schedule is what the chaos gate pins."""
        from mmlspark_tpu.core.retry import RetryPolicy
        if self.lane_restart is not None:
            return self.lane_restart
        return RetryPolicy(max_attempts=4, base_delay_s=0.05,
                           max_delay_s=2.0, jitter=0.0)

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int, model: str = "?") -> int:
        """Smallest bucket admitting ``rows`` rows."""
        for b in self.buckets:
            if rows <= b:
                return b
        raise BadRequest(
            f"model {model!r}: request of {rows} rows exceeds the largest "
            f"bucket {self.max_bucket} (requests are never split)")


@dataclasses.dataclass(frozen=True)
class GenerateConfig:
    """Knobs of one autoregressive token-serving engine
    (:class:`~mmlspark_tpu.serve.generate.GenerateBatcher`).

    The compiled-program budget is the whole point of the shape
    discipline here: prompt *lengths* quantize onto ``prefill_buckets``
    (the PR 15 ladder rules — validated, warmable, compile-cache
    eligible) while the prefill *row* dimension is always padded to
    ``prefill_rows``, so prefill compiles at most
    ``len(prefill_buckets)`` programs; decode is ONE fixed-shape program
    ``[slots]`` forever — requests join/leave per token step via the
    active-slot mask, never via a recompile. Total programs ≤
    ``len(prefill_buckets) + 1``.
    """

    slots: int = 8              # decode batch width = KV-cache slot count
    t_max: int = 128            # cache horizon [.., T_max, ..]: prompt +
    #                             generated tokens per request must fit
    prefill_buckets: tuple = (8, 32)  # prompt-length ladder (tokens)
    prefill_rows: int = 4       # fixed row dim of the prefill program:
    #                             up to this many waiting prompts pack
    #                             into one prefill dispatch (pad rows
    #                             scatter to the out-of-bounds slot id
    #                             and are dropped by XLA)
    max_new_tokens: int = 16    # default generation budget per request
    max_queue: int = 128        # waiting-for-a-slot bound; admission
    #                             backpressure past it (Overloaded)
    retry_after_s: float = 1.0  # backpressure hint stamped on Overloaded/
    #                             ServerClosed (HTTP Retry-After + the
    #                             client retry sleep floor)
    eos_token: int | None = None  # stop token (None = run to budget)
    stats_window: int = 4096
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        from mmlspark_tpu.serve.errors import ModelLoadError
        from mmlspark_tpu.serve.ladder import validate_ladder
        try:
            buckets = validate_ladder(self.prefill_buckets)
        except ValueError as e:
            raise ModelLoadError("<generate-config>", message=str(e))
        object.__setattr__(self, "prefill_buckets", buckets)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1: {self.slots}")
        if self.prefill_rows < 1:
            raise ValueError(
                f"prefill_rows must be >= 1: {self.prefill_rows}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1: {self.max_queue}")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1: {self.max_new_tokens}")
        if self.t_max < buckets[-1] + 1:
            raise ValueError(
                f"t_max={self.t_max} cannot hold the largest prefill "
                f"bucket {buckets[-1]} plus one generated token")

    def prefill_bucket_for(self, tokens: int, model: str = "?") -> int:
        """Smallest prompt-length bucket admitting ``tokens`` tokens."""
        for b in self.prefill_buckets:
            if tokens <= b:
                return b
        raise BadRequest(
            f"model {model!r}: prompt of {tokens} tokens exceeds the "
            f"largest prefill bucket {self.prefill_buckets[-1]}")

"""DynamicBatcher — per-model coalescing dispatch loop.

Clipper-style adaptive batching over a compiled batch engine, with the TPU
constraint driving the design: every distinct input shape is a recompile,
so coalesced requests are packed into padded batches drawn from a fixed
bucket ladder (``ServeConfig.buckets``) and the server compiles exactly one
program per (model, bucket).

The loop's discipline mirrors ``train/input.py``'s overlapped pipeline,
inverted to the serving direction:

* **admission** is a bounded FIFO — a full queue rejects with the typed
  :class:`~mmlspark_tpu.serve.errors.Overloaded` (backpressure, not an
  unbounded latency cliff), and requests whose deadline expires while
  queued are cancelled *before dispatch*;
* **packing** takes whole requests in FIFO order up to the largest bucket
  and pads to the smallest bucket that fits (a request is never split, so
  a timeout can never observe a partial result);
* **dispatch** fans out over one or more :class:`_Lane` workers — one per
  DP replica when the model serves sharded
  (:mod:`mmlspark_tpu.serve.mesh`), else a single lane over the model's
  own mesh. The batcher packs on its own thread and hands the padded
  batch to the least-loaded lane, so host packing of batch *i+1* overlaps
  device compute of batch *i*; each lane drives
  ``core.plan.transform_async`` — one H2D upload, one fused program call,
  one async D2H fetch round per bucket batch — against its own sub-mesh
  and compiled-segment cache (params uploaded once per replica), with its
  own bounded in-flight window (``max_inflight`` per replica);
* **lockstep** (multi-host serving) — before a collective-bearing
  dispatch every process must quiesce and agree: the batcher calls
  :meth:`DynamicBatcher.drain_barrier` (the PR 3 train-input fence
  discipline — all in-flight dispatches drained) and then the
  :class:`~mmlspark_tpu.serve.mesh.LockstepCoordinator` signature
  exchange, so cross-process collective issue order stays identical;
* **shutdown** (``close(drain=True)``) stops admission, answers every
  already-admitted request, then joins the scheduler and every lane
  worker — no leaked thread.

With the obs tracer enabled every request also carries a **trace id**
minted at admission (``obs/context.py``): the admit/complete spans
record under it, and the pack/dispatch/drain bucket-batch spans link
every coalesced member, so one request's journey across the scheduler
and lane threads reads as a single flow in the exported timeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent import futures
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.obs import context as _obs_ctx
from mmlspark_tpu.obs.lockwitness import named_condition
from mmlspark_tpu.obs import flight as _obs_flight
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.spans import event as _obs_event
from mmlspark_tpu.obs.spans import span as _obs_span
from mmlspark_tpu.serve import faults as _faults
from mmlspark_tpu.serve.config import ServeConfig
from mmlspark_tpu.serve.errors import (
    BadRequest, DeadlineExceeded, LaneFailed, Overloaded, ServerClosed,
)
from mmlspark_tpu.serve.stats import ServerStats

_log = get_logger(__name__)

THREAD_PREFIX = "ServeBatcher"

# request states — transitions are guarded by the request's own lock
_QUEUED, _DISPATCHED, _DONE, _TIMED_OUT = range(4)


def _cell_sig(cell: Any) -> tuple:
    if isinstance(cell, dict) and "data" in cell:
        d = np.asarray(cell["data"])
        return ("image", d.shape, str(d.dtype))
    if isinstance(cell, np.ndarray):
        return ("array", cell.shape, str(cell.dtype))
    if isinstance(cell, (list, tuple)):
        return ("seq", len(cell))
    return ("cell", type(cell).__name__)


def _compat_key(table: DataTable) -> tuple:
    """Batch-compatibility fingerprint: column names plus the (uniform)
    per-cell layout of EVERY row. Requests only coalesce when keys match,
    so a wrong-shape request (same column names, different per-row
    layout) is dispatched alone and fails alone — it can never take a
    batch of well-formed neighbors down with it. A request whose own rows
    are ragged gets a "nonuniform" key carrying its full cell-by-cell
    layout: it can only ever coalesce with an identically-ragged request
    (both doomed to the same per-batch failure), never with a well-formed
    one. The key is a pure function of the table's layout — the lockstep
    dispatch signature hashes it, so identical request streams must
    digest identically across processes and runs.
    O(rows × cols) on cheap signatures; requests are bucket-sized."""
    parts = []
    for name in sorted(table.columns):
        col = table[name]
        if col.dtype != object:
            parts.append((name, ("np", str(col.dtype))))
            continue
        sig = _cell_sig(col[0]) if len(col) else ("empty",)
        for cell in col[1:]:
            if _cell_sig(cell) != sig:
                # internally ragged: keyed by the whole per-cell layout —
                # every OTHER column still contributes its part, so two
                # requests coalesce only when ALL columns line up
                sig = ("nonuniform", tuple(_cell_sig(c) for c in col))
                break
        parts.append((name, sig))
    return tuple(parts)


def _batch_links(batch: list) -> tuple | None:
    """The fan-in edge set of one packed batch: every member request's
    trace id (obs/context.py). The pack/dispatch/drain spans carry it so
    a request's flow steps through the shared bucket-batch work. Only
    called on the enabled path."""
    links = tuple(r._trace for r in batch if r._trace is not None)
    return links or None


class ServeRequest:
    """Handle for one admitted request; wait with :meth:`result`.

    Resolution is atomic per request: a request either gets its complete
    output table, or exactly one typed error — a deadline expiry can never
    observe a partial result, and a result arriving after the caller gave
    up is discarded.
    """

    __slots__ = ("model", "table", "n_rows", "deadline_ms", "_deadline",
                 "_submitted", "_dispatched_at", "_resolved_at", "_state",
                 "_lock", "_event", "_result", "_error", "_stats",
                 "_compat", "_trace")

    def __init__(self, model: str, table: DataTable,
                 deadline_ms: float | None, stats: ServerStats):
        self.model = model
        self.table = table
        self.n_rows = len(table)
        self._compat = _compat_key(table)
        # request-scoped trace id (obs/context.py): minted here at
        # admission, carried for the request's whole life so the
        # pack/dispatch/drain batch spans can link back to it. None
        # (one flag check) when the tracer is off
        self._trace = _obs_ctx.mint()
        self.deadline_ms = deadline_ms
        now = time.monotonic()
        self._submitted = now
        self._deadline = (None if deadline_ms is None
                          else now + deadline_ms / 1e3)
        self._dispatched_at: float | None = None
        self._resolved_at: float | None = None
        self._state = _QUEUED
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._result: DataTable | None = None
        self._error: BaseException | None = None
        self._stats = stats

    # -- batcher side --

    def _mark_dispatched(self, now: float) -> None:
        with self._lock:
            if self._state == _QUEUED:
                self._state = _DISPATCHED
                self._dispatched_at = now

    def _resolve(self, table: DataTable) -> bool:
        """Deliver the result; False when the caller already gave up (the
        late result is discarded — never a partial/stale delivery)."""
        with self._lock:
            if self._state == _TIMED_OUT:
                return False
            self._state = _DONE
            self._result = table
            self._resolved_at = time.monotonic()
        self._event.set()
        return True

    def _fail(self, error: BaseException) -> bool:
        with self._lock:
            if self._state == _TIMED_OUT:
                return False
            self._state = _DONE
            self._error = error
            self._resolved_at = time.monotonic()
        self._event.set()
        return True

    # -- caller side --

    @property
    def trace_id(self) -> int | None:
        """The request's obs trace id (None when tracing is disabled):
        the key into :func:`mmlspark_tpu.obs.context.request_traces`."""
        return self._trace

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> DataTable:
        """Block until resolution; raises the request's typed error.

        The effective wait is the sooner of ``timeout`` and the request's
        own deadline. On expiry the request is atomically marked timed out
        (any later resolution is discarded) and
        :class:`DeadlineExceeded` is raised — never a partial result.
        Giving up is terminal: a repeat call re-raises the same error
        immediately (it can never block or hand back a discarded result).
        """
        with self._lock:
            if self._state == _TIMED_OUT:
                raise self._error
        waits = [t for t in (timeout, None if self._deadline is None
                             else self._deadline - time.monotonic())
                 if t is not None]
        ok = self._event.wait(min(waits) if waits else None)
        with self._lock:
            if self._state == _DONE:
                if self._error is not None:
                    raise self._error
                return self._result
            # not resolved in time: give up atomically and terminally
            self._state = _TIMED_OUT
            if not ok and timeout is not None and (
                    self._deadline is None
                    or time.monotonic() < self._deadline):
                self._error = TimeoutError(
                    f"model {self.model!r}: no result within {timeout}s "
                    "(request deadline not yet reached)")
            else:
                self._error = DeadlineExceeded(
                    self.model, self.deadline_ms or 0.0,
                    "queued" if self._dispatched_at is None
                    else "in-flight")
            err = self._error
        self._stats.record_timeout()  # once: the transition, not retries
        raise err


class _Lane:
    """One dispatch lane: a DP replica's sub-mesh (or the model's default
    whole-mesh path) with its own worker thread, compiled-segment cache,
    and bounded in-flight window.

    The worker pulls packed bucket-batches the scheduler assigned, issues
    the async dispatch against the lane's mesh, and drains its window —
    at most ``max_inflight`` dispatched-but-undrained batches per lane.
    On shutdown the worker finishes everything already assigned to it
    (the device work is in flight; answering it costs only the drain).
    """

    __slots__ = ("batcher", "index", "cache_host", "mesh", "shard_params",
                 "replica", "_cv", "_queue", "_window", "_closing",
                 "_thread", "load", "_hb", "alive", "_inhand", "_indrain")

    def __init__(self, batcher: "DynamicBatcher", index: int,
                 cache_host: Any, mesh: Any = None,
                 shard_params: Any = None, replica: Any = None):
        self.batcher = batcher
        self.index = index
        self.cache_host = cache_host
        self.mesh = mesh
        self.shard_params = shard_params
        self.replica = replica       # serve.mesh.Replica | None
        self._cv = named_condition("serve.batcher._Lane._cv")
        self._queue: deque = deque()   # (packed, batch, rows, bucket)
        self._window: deque = deque()  # (pending, batch, rows, bucket, t0)
        self._closing = False
        # lane self-healing state: `alive` flips False exactly once, in
        # _lane_failed, under this lane's _cv — the scheduler only
        # assigns to alive lanes, and `assign` itself re-checks so the
        # acquire→assign race can never strand a batch on a corpse.
        # _inhand/_indrain track the one work item the worker is
        # touching outside the queue/window structures, so the healer
        # can account for EVERY admitted batch when the thread dies
        self.alive = True
        self._inhand: tuple | None = None
        self._indrain: tuple | None = None
        self.load = 0  # queued + in-flight batches; guarded by the
        #                batcher's scheduler condition, not this lane's
        # flight-recorder heartbeat: busy while work is assigned, idle
        # (disarmed) while parked on the condition — an idle lane is
        # never a hang, a lane stuck inside a dispatch or drain is
        self._hb = f"serve/{batcher.name}#{index}"
        self._thread = threading.Thread(
            target=self._run,
            name=f"{THREAD_PREFIX}[{batcher.name}]#{index}", daemon=True)
        self._thread.start()

    @property
    def replica_index(self) -> int | None:
        return None if self.replica is None else self.replica.index

    # -- scheduler side --

    def assign(self, packed: DataTable, batch: list, rows: int,
               bucket: int) -> bool:
        """Queue one packed batch for this lane's worker. False when the
        lane is dead (the healer already swept its queue — appending
        would strand the batch forever); the caller re-acquires."""
        with self._cv:
            if not self.alive:
                return False
            self._queue.append((packed, batch, rows, bucket))
            self._cv.notify()
        return True

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()

    def join(self, timeout: float) -> bool:
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    # -- worker --

    def _release(self) -> None:
        """One batch fully resolved: free the load slot and wake the
        scheduler (and any ``drain_barrier`` waiter)."""
        cv = self.batcher._sched_cv
        with cv:
            self.load -= 1
            cv.notify_all()

    def _labels(self) -> dict | None:
        if not _obs_rt._enabled:
            return None
        labels = {"model": self.batcher.name}
        if self.replica is not None:
            labels["replica"] = self.replica.index
        return labels

    def _run(self) -> None:
        try:
            self._work_loop()
        except BaseException as e:  # noqa: BLE001 — lane self-healing
            # a NON-REQUEST exception reached the worker loop (request
            # failures are relayed inside _dispatch/_drain_one): this
            # thread is done for, but its queue must not be — hand
            # everything to the batcher's healer, which requeues the
            # undispatched work, fails the in-flight window typed, and
            # restarts the lane under the configured backoff
            try:
                self.batcher._lane_failed(self, e)
            except BaseException:  # pragma: no cover - defensive
                _log.exception("%s lane %d: self-healing itself failed",
                               self.batcher.name, self.index)

    def _work_loop(self) -> None:
        while True:
            with self._cv:
                while (not self._queue and not self._window
                       and not self._closing):
                    if _obs_flight._rec is not None:
                        _obs_flight._rec.disarm(self._hb)
                    self._cv.wait()
                item = self._queue.popleft() if self._queue else None
                self._inhand = item
                closing = self._closing
            if _obs_flight._rec is not None:
                _obs_flight._rec.beat(self._hb)
            if item is None:
                if self._window:
                    # idle: finish outstanding batches promptly
                    self._drain_one()
                    continue
                if closing:
                    if _obs_flight._rec is not None:
                        _obs_flight._rec.disarm(self._hb)
                    return
                continue
            # the lane-death injection point: a fault here models the
            # motivating bug — a non-request exception killing the
            # worker with a batch in hand and more queued behind it
            _faults.hit("lane_death", model=self.batcher.name,
                        lane=self.index)
            self._dispatch(*item)
            self._inhand = None
            if len(self._window) >= self.batcher.config.max_inflight:
                self._drain_one()

    def _dispatch(self, packed: DataTable, batch: list, rows: int,
                  bucket: int) -> None:
        from mmlspark_tpu.core import plan
        now = time.monotonic()
        if all(r._deadline is not None and now >= r._deadline
               for r in batch):
            # the whole batch expired while queued for this lane: cancel
            # BEFORE dispatch (the same pre-dispatch cancellation the
            # admission queue applies) instead of burning device time on
            # answers nobody is waiting for
            for r in batch:
                if r._fail(DeadlineExceeded(self.batcher.name,
                                            r.deadline_ms or 0.0,
                                            "queued")):
                    self.batcher.stats.record_expired()
            self._release()
            return
        for r in batch:
            r._mark_dispatched(now)
        labels = self._labels()
        try:
            with _obs_span("serve/dispatch", "serve",
                           {**labels, "bucket": bucket}
                           if labels is not None else None,
                           _batch_links(batch)
                           if labels is not None else None):
                # injection points at the dispatch seam: a slow
                # dispatch (sleep) and a dispatch-time raise — the
                # latter is relayed per request below, exactly like a
                # real packing/upload failure
                _faults.hit("dispatch_slow", model=self.batcher.name,
                            lane=self.index)
                _faults.hit("dispatch_raise", model=self.batcher.name,
                            lane=self.index)
                pending = plan.transform_async(
                    self.batcher.stages, packed, self.cache_host,
                    mesh=self.mesh, shard_params=self.shard_params,
                    precision=self.batcher.precision)
        except BaseException as e:  # noqa: BLE001 — relayed per request
            for r in batch:
                if r._fail(e):
                    self.batcher.stats.record_failed()
            self._release()
            return
        if self.replica is not None:
            self.replica.dispatched += 1
        self._window.append((pending, batch, rows, bucket, now))

    def _drain_one(self) -> None:
        entry = self._window.popleft()
        # out of the window but not yet resolved: visible to the healer
        # (a death inside the drain must still fail this batch typed) —
        # cleared only on a non-raising drain, so an escaping exception
        # reaches _lane_failed with the entry still attributable
        self._indrain = entry
        self._drain_entry(entry)
        self._indrain = None

    def _drain_entry(self, entry: tuple) -> None:
        pending, batch, rows, bucket, t0 = entry
        if _obs_flight._rec is not None:
            _obs_flight._rec.beat(self._hb)
        labels = self._labels()
        try:
            with _obs_span("serve/drain", "serve",
                           {**labels, "bucket": bucket}
                           if labels is not None else None,
                           _batch_links(batch)
                           if labels is not None else None):
                out = pending.result()
        except BaseException as e:  # noqa: BLE001 — relayed per request
            _log.warning("%s lane %d: batch of %d failed: %s",
                         self.batcher.name, self.index, rows, e)
            for r in batch:
                if r._fail(e):
                    self.batcher.stats.record_failed()
            self._release()
            return
        done = time.monotonic()
        # pending.shapes is what the device actually saw (one entry per
        # uploaded chunk) — if bucket quantization ever regresses, the
        # distinct-shape count grows past the ladder and the perf gate
        # trips; a host-path dispatch contributes no shapes
        self.batcher.stats.record_batch(bucket, rows, (done - t0) * 1e3,
                                        pending.shapes,
                                        replica=self.replica_index)
        if len(out) != bucket:
            # a row-count-changing stage breaks the per-request split:
            # offsets would shift and neighbors would silently receive
            # each other's rows. Fail the WHOLE batch — wrong-but-
            # plausible results are worse than a typed error
            err = BadRequest(
                f"model {self.batcher.name!r}: transform changed the row "
                f"count ({bucket} in, {len(out)} out) — row-preserving "
                "models only; per-request results cannot be attributed")
            for r in batch:
                if r._fail(err):
                    self.batcher.stats.record_failed()
            self._release()
            return
        offset = 0
        for r in batch:
            idx = np.arange(offset, offset + r.n_rows)
            offset += r.n_rows
            if labels is None:  # tracer off: resolve with zero obs work
                delivered = r._resolve(out.take(idx))
            else:
                # fan-out: each request's slice resolves under its OWN
                # trace context, so the per-request serve/complete span
                # closes the admission → pack → dispatch → drain flow
                with _obs_ctx.bind(r._trace), \
                        _obs_span("serve/complete", "serve",
                                  {**labels, "rows": r.n_rows}):
                    delivered = r._resolve(out.take(idx))
            if delivered:
                self.batcher.stats.record_done(
                    (done - r._submitted) * 1e3,
                    ((r._dispatched_at or done) - r._submitted) * 1e3)
        self._release()


class DynamicBatcher:
    """Bounded request queue + coalescing dispatch loop for ONE model."""

    def __init__(self, name: str, stages: list, cache_host: Any,
                 config: ServeConfig, stats: ServerStats | None = None,
                 replicas: Any = None, lockstep: Any = None,
                 precision: Any = None):
        self.name = name
        self.stages = list(stages)
        self.cache_host = cache_host
        self.config = config
        self.stats = stats or ServerStats(config.stats_window, model=name)
        self.replicas = replicas     # serve.mesh.ReplicaSet | None
        self._lockstep = lockstep    # serve.mesh.LockstepCoordinator | None
        self.precision = precision   # core.precision.PrecisionPolicy |
        #                              None — every lane dispatch (and
        #                              warm compile) pins it, so the
        #                              served program IS the policy's
        self._cv = named_condition("serve.batcher.DynamicBatcher._cv")
        self._queue: deque[ServeRequest] = deque()
        self._closed = False     # admission stopped (drain in progress)
        self._abort = False      # fail queued work instead of draining
        # lane scheduling state: lane.load counters live under this
        # condition; lanes notify it as batches resolve
        self._sched_cv = named_condition("serve.batcher.DynamicBatcher._sched_cv")
        # lane self-healing: restart budget shared across lanes (bounds
        # total churn — a model whose lanes keep dying is a model
        # problem, not a restart problem) and an optional server-side
        # hook so deaths/restarts land in the lifecycle journal
        self._lane_restarts_used = 0
        self.on_lane_event: Any = None
        if replicas is not None:
            self._lanes = [
                _Lane(self, i, rep.cache_host, mesh=rep.mesh,
                      shard_params=rep.shard_params, replica=rep)
                for i, rep in enumerate(replicas.replicas)]
        else:
            # default: ONE lane over the model's own mesh and cache, so
            # online serving and offline transform share one compiled
            # segment + param upload
            self._lanes = [_Lane(self, 0, cache_host)]
        self._thread = threading.Thread(
            target=self._run, name=f"{THREAD_PREFIX}[{name}]", daemon=True)
        self._thread.start()

    # -- admission --

    def submit(self, table: DataTable,
               deadline_ms: float | None = None) -> ServeRequest:
        """Admit one request (whole table = one atomic unit of work)."""
        n = len(table)
        if n == 0:
            raise BadRequest(f"model {self.name!r}: empty request")
        if n > self.config.max_bucket:
            self.config.bucket_for(n, self.name)  # raises BadRequest
        req = ServeRequest(self.name, table, deadline_ms, self.stats)
        if _obs_rt._enabled:
            # the request's trace begins here: the admit span records
            # under the freshly minted trace id (obs/context.py), and
            # every later span of this request's journey links back
            with _obs_ctx.bind(req._trace), \
                    _obs_span("serve/admit", "serve",
                              {"model": self.name, "rows": n}):
                self._admit(req)
        else:
            self._admit(req)
        return req

    def _admit(self, req: ServeRequest) -> None:
        with self._cv:
            if self._closed:
                raise ServerClosed(
                    f"model {self.name!r} is shutting down",
                    retry_after_s=self.config.retry_after_s)
            if len(self._queue) >= self.config.max_queue:
                self.stats.record_rejected()
                _obs_event("serve/overloaded", "serve",
                           {"model": self.name})
                raise Overloaded(self.name, len(self._queue),
                                 self.config.max_queue,
                                 retry_after_s=self.config.retry_after_s)
            self._queue.append(req)
            self.stats.record_admitted(req.n_rows)
            self._cv.notify()

    @property
    def queued(self) -> int:
        with self._cv:
            return len(self._queue)

    @property
    def closed(self) -> bool:
        """True once admission stopped (drain in progress or done) —
        the health surfaces' per-model drain-awareness read."""
        with self._cv:
            return self._closed

    # -- the dispatch loop --

    def _collect(self, now: float) -> tuple[list, list, int]:
        """Pop expired requests plus the next packable FIFO run (whole
        requests, total rows ≤ the largest bucket)."""
        batch: list[ServeRequest] = []
        expired: list[ServeRequest] = []
        rows = 0
        with self._cv:
            while self._queue:
                r = self._queue[0]
                if r._deadline is not None and now >= r._deadline:
                    self._queue.popleft()
                    expired.append(r)
                    continue
                if batch and rows + r.n_rows > self.config.max_bucket:
                    break
                # only layout-compatible requests share a batch (same
                # columns AND same per-row cell layout): a mis-shaped
                # request must fail alone, not take the whole coalesced
                # batch down with it
                if batch and r._compat != batch[0]._compat:
                    break
                self._queue.popleft()
                batch.append(r)
                rows += r.n_rows
        return batch, expired, rows

    def _pack(self, batch: list, rows: int) -> tuple[DataTable, int]:
        """Concatenate the requests' rows (one multi-way pass — pairwise
        ``concat`` would re-copy the accumulated columns per request,
        O(k²) on the hot packing path that is supposed to overlap device
        compute) and pad to the bucket size by repeating the last row
        (always coercible; trimmed on emit)."""
        bucket = self.config.bucket_for(rows, self.name)
        first = batch[0].table
        if len(batch) == 1 and bucket == rows:
            return first, bucket
        pad = bucket - rows
        cols: dict[str, np.ndarray] = {}
        for name in first.columns:
            parts = [r.table[name] for r in batch]
            if any(p.dtype == object for p in parts):
                merged = np.empty(bucket, dtype=object)
                offset = 0
                for p in parts:
                    merged[offset:offset + len(p)] = p
                    offset += len(p)
                # repeat the last cell by reference: padding is read-only
                # and trimmed before emit (per-slot assignment — a slice
                # assign would broadcast an ndarray cell elementwise)
                last_cell = parts[-1][-1]
                for k in range(offset, bucket):
                    merged[k] = last_cell
                cols[name] = merged
            else:
                if pad:
                    parts.append(np.repeat(parts[-1][-1:], pad, axis=0))
                cols[name] = np.concatenate(parts)
        return DataTable(cols, dict(first.meta)), bucket

    def _acquire_lane(self) -> _Lane | None:
        """Least-loaded ALIVE replica pick (ties → lowest index), bounded
        at ``max_inflight`` outstanding batches per lane — the
        scheduler's backpressure. Blocks until a slot frees (or, with
        every lane down, until the supervisor restarts one); None when
        aborted."""
        with self._sched_cv:
            while not self._abort:
                alive = [L for L in self._lanes if L.alive]
                if alive:
                    lane = min(alive, key=lambda L: (L.load, L.index))
                    if lane.load < self.config.max_inflight:
                        lane.load += 1
                        return lane
                # waiting for a lane slot (or a lane restart) is the
                # LANES' business, not a scheduler hang: keep its
                # flight heartbeat fresh (a stuck lane raises its own)
                if _obs_flight._rec is not None:
                    _obs_flight._rec.beat(f"serve/{self.name}/scheduler")
                self._sched_cv.wait(timeout=0.1)
        return None

    def drain_barrier(self, poll_s: float = 0.05) -> None:
        """Block until every assigned batch has been dispatched AND
        drained across all lanes — the serve analog of
        ``DeviceLoader.drain_barrier`` (PR 3): multi-host lockstep calls
        this before the cross-process signature exchange so no process
        interleaves the exchange with in-flight device work."""
        # beat the scheduler's flight heartbeat only when running ON the
        # scheduler thread (the in-repo lockstep path): its work-unit
        # bracket disarms afterwards. A foreign caller beating it would
        # mark the scheduler busy with nothing to ever disarm it — an
        # idle server would ripen into a spurious watchdog "hang" dump
        on_sched = threading.current_thread() is self._thread
        with self._sched_cv:
            # dead lanes are excluded: the healer zeroes a corpse's
            # load, but an acquire that raced the death can leave a
            # ghost increment on it — the fence must never spin on a
            # lane that can no longer drain anything
            while (not self._abort
                   and any(lane.load for lane in self._lanes
                           if lane.alive)):
                if on_sched and _obs_flight._rec is not None:
                    _obs_flight._rec.beat(f"serve/{self.name}/scheduler")
                self._sched_cv.wait(timeout=poll_s)

    # -- lane self-healing --

    def _notify_lane_event(self, kind: str, payload: dict) -> None:
        cb = self.on_lane_event
        if cb is not None:
            try:
                cb(kind, payload)
            except Exception:  # pragma: no cover - journal must not kill
                _log.exception("%s: lane-event hook failed", self.name)

    def _lane_failed(self, lane: _Lane, exc: BaseException) -> None:
        """A lane worker died on a non-request exception (runs ON the
        dying thread, as its last act). The contract the motivating bug
        violated: no admitted request may be silently stranded, and
        capacity loss must be visible, not quiet.

        * **undispatched** batches (the lane's queue + the in-hand item)
          are requeued onto surviving lanes — they were never
          dispatched, so re-dispatching can never double-respond;
        * **in-flight** batches (the async window + a mid-drain entry)
          lose their results with the worker: each request fails with
          the typed, retryable :class:`LaneFailed` — never resolved
          speculatively;
        * the lane is **restarted** under ``ServeConfig.lane_restart``
          backoff (reusing the dead lane's compiled-segment cache, so a
          restart costs no recompile); past the budget the lane stays
          down and ``lane_health`` keeps reporting the hole — degraded
          health instead of silently shrunk capacity.
        """
        with lane._cv:
            lane.alive = False
            stranded: list[tuple] = []
            if lane._inhand is not None:
                stranded.append(lane._inhand)
                lane._inhand = None
            stranded.extend(lane._queue)
            lane._queue.clear()
            inflight = list(lane._window)
            lane._window.clear()
            if lane._indrain is not None:
                inflight.append(lane._indrain)
                lane._indrain = None
            lane._closing = True
        if _obs_flight._rec is not None:
            _obs_flight._rec.disarm(lane._hb)
        self.stats.record_lane_death()
        _log.warning(
            "%s lane %d died (%s: %s) — requeueing %d undispatched "
            "batch(es), failing %d in-flight", self.name, lane.index,
            type(exc).__name__, exc, len(stranded), len(inflight))
        if _obs_rt._enabled:
            _obs_event("serve/lane_death", "serve",
                       {"model": self.name, "lane": lane.index,
                        "error": f"{type(exc).__name__}: {exc}"})
        self._notify_lane_event("lane_death", {
            "model": self.name, "lane": lane.index,
            "error": f"{type(exc).__name__}: {exc}",
            "undispatched": len(stranded), "inflight": len(inflight)})
        err = LaneFailed(self.name, lane.index,
                         f"{type(exc).__name__}: {exc}")
        err.__cause__ = exc
        for entry in inflight:
            for r in entry[1]:
                if r._fail(err):
                    self.stats.record_failed()
        # free the corpse's load accounting so the scheduler and the
        # drain fence see real capacity
        with self._sched_cv:
            lane.load = 0
            self._sched_cv.notify_all()
        with self._cv:
            closing = self._closed or self._abort
        # survivors first: requeued work should not wait out the
        # restart backoff when other lanes can take it now
        if stranded and not closing:
            survivors = [L for L in self._lanes
                         if L.alive and L is not lane]
            if survivors:
                self.stats.record_requeued(len(stranded))
                for item in stranded:
                    self._requeue(item)
                stranded = []
        replacement = None if closing else self._restart_lane(lane)
        if stranded and replacement is not None:
            self.stats.record_requeued(len(stranded))
            for item in stranded:
                self._requeue(item)
            stranded = []
        for packed, batch, rows, bucket in stranded:
            # no survivor and no restart (budget spent, or shutting
            # down): the queue must still be answered, typed
            for r in batch:
                if r._fail(err if not closing
                           else ServerClosed(f"model {self.name!r} "
                                             "closed")):
                    self.stats.record_failed()

    def _requeue(self, item: tuple) -> None:
        """Re-assign one undispatched batch to the least-loaded alive
        lane (expired deadlines are cancelled at the lane's own
        pre-dispatch check, exactly like first-time assignment)."""
        while True:
            with self._sched_cv:
                if self._abort:
                    break
                alive = [L for L in self._lanes if L.alive]
                if not alive:
                    break
                lane = min(alive, key=lambda L: (L.load, L.index))
                lane.load += 1
            if lane.assign(*item):
                return
        for r in item[1]:
            if r._fail(LaneFailed(self.name, -1,
                                  "no surviving lane to requeue onto")):
                self.stats.record_failed()

    def _restart_lane(self, lane: _Lane) -> _Lane | None:
        """Spawn a replacement worker for the dead lane's slot under the
        configured backoff (the dying thread pays the sleep); None when
        the restart budget is exhausted."""
        policy = self.config.lane_restart_policy()
        with self._sched_cv:
            used = self._lane_restarts_used
            exhausted = used >= policy.max_attempts - 1
            if not exhausted:
                self._lane_restarts_used = used + 1
        if exhausted:
            _log.error(
                "%s lane %d: restart budget (%d) exhausted — lane "
                "stays down, capacity degraded", self.name,
                lane.index, policy.max_attempts - 1)
            # hook fires with no lock held (CC105): a listener that
            # re-enters the batcher (depth(), drain_barrier()) must not
            # deadlock against the scheduler cv
            self._notify_lane_event("lane_down", {
                "model": self.name, "lane": lane.index,
                "restarts_used": used})
            return None
        delay = 0.0
        for i, d in enumerate(policy.delays()):
            if i == used:
                delay = d
                break
        if delay:
            time.sleep(delay)
        replacement = _Lane(self, lane.index, lane.cache_host,
                            mesh=lane.mesh,
                            shard_params=lane.shard_params,
                            replica=lane.replica)
        with self._sched_cv:
            self._lanes[lane.index] = replacement
            self._sched_cv.notify_all()
        self.stats.record_lane_restart()
        _log.info("%s lane %d restarted (attempt %d, %.0f ms backoff)",
                  self.name, lane.index, used + 1, delay * 1e3)
        if _obs_rt._enabled:
            _obs_event("serve/lane_restart", "serve",
                       {"model": self.name, "lane": lane.index,
                        "attempt": used + 1})
        self._notify_lane_event("lane_restart", {
            "model": self.name, "lane": lane.index, "attempt": used + 1,
            "backoff_s": round(delay, 3)})
        return replacement

    def lane_health(self) -> dict:
        """The capacity surface health checks merge in: a model whose
        lanes are down is degraded even while its latency percentiles
        still look clean (fewer lanes = less headroom, invisible until
        overload)."""
        with self._sched_cv:
            lanes = list(self._lanes)
        return {
            "lanes": len(lanes),
            "alive": sum(1 for L in lanes if L.alive),
            "deaths": self.stats.lane_deaths,
            "restarts": self.stats.lane_restarts,
            "requeued_batches": self.stats.requeued_batches,
        }

    def _dispatch(self, batch: list, rows: int) -> None:
        # pack on the scheduler thread: the packing work is what overlaps
        # device compute of the previous batch on the lane workers, so
        # the timeline shows the overlap (or its absence) directly
        on = _obs_rt._enabled
        with _obs_span("serve/pack", "serve",
                       {"model": self.name, "requests": len(batch),
                        "rows": rows} if on else None,
                       _batch_links(batch) if on else None):
            packed, bucket = self._pack(batch, rows)
        if self._lockstep is not None:
            # collective lockstep: quiesce every lane (the fence), claim
            # the dispatch slot, and only THEN agree cross-process — once
            # agree() returns, this process dispatches unconditionally
            # (lanes complete assigned work even on abort), so no process
            # can advance the agreed schedule and then fail to issue the
            # collective-bearing program it agreed to
            self.drain_barrier()
            lane = self._acquire_lane()
            if lane is None:  # aborted at the fence: nothing was agreed
                raise ServerClosed(f"model {self.name!r} closed",
                                   retry_after_s=self.config.retry_after_s)
            try:
                # fenced cross-process seam: every lockstep process
                # exits agree() together — the fleet plane's serve-side
                # skew/stitch anchor (obs/fleet.FENCE_SPAN_NAMES)
                with _obs_span("serve/lockstep_agree", "serve",
                               {"model": self.name, "bucket": bucket}
                               if _obs_rt._enabled else None):
                    self._lockstep.agree((bucket, batch[0]._compat))
            except BaseException:
                # nothing dispatched: free the claimed slot or the next
                # drain_barrier spins on this lane's load forever
                with self._sched_cv:
                    lane.load -= 1
                    self._sched_cv.notify_all()
                raise
            if not lane.assign(packed, batch, rows, bucket):
                # the agreed lane died after the exchange — this process
                # cannot issue the program it agreed to; typed failure
                # (relayed per request by the caller), never a silent
                # re-route that would desync the agreed schedule
                raise LaneFailed(self.name, lane.index,
                                 "lane died after lockstep agreement")
            return
        while True:
            lane = self._acquire_lane()
            if lane is None:  # aborted while waiting for a slot
                raise ServerClosed(f"model {self.name!r} closed",
                                   retry_after_s=self.config.retry_after_s)
            if lane.assign(packed, batch, rows, bucket):
                return
            # raced a lane death between acquire and assign: the healer
            # sweeps the corpse's load accounting; pick another lane

    def _run(self) -> None:
        hb = f"serve/{self.name}/scheduler"
        while not self._abort:
            batch, expired, rows = self._collect(time.monotonic())
            for r in expired:
                if r._fail(DeadlineExceeded(self.name,
                                            r.deadline_ms or 0.0,
                                            "queued")):
                    self.stats.record_expired()
            if batch:
                # flight heartbeat: busy only while work is in hand — a
                # scheduler wedged in pack/lane-acquire is a hang, an
                # empty queue is not
                if _obs_flight._rec is not None:
                    _obs_flight._rec.beat(hb)
                try:
                    self._dispatch(batch, rows)
                except BaseException as e:  # noqa: BLE001 — per-request
                    for r in batch:
                        if r._fail(e):
                            self.stats.record_failed()
                finally:
                    if _obs_flight._rec is not None:
                        _obs_flight._rec.disarm(hb)
                continue
            with self._cv:
                if self._queue:
                    continue  # raced with a submit
                if self._closed or self._abort:
                    break
                # untimed: every path that adds work or shuts down
                # notifies under this condition (submit, close), and this
                # wait is only reached with the queue empty — queued-
                # deadline expiry never needs a timer here because a
                # non-empty queue never reaches the wait
                self._cv.wait()
        # batches already assigned to lanes complete even on abort (the
        # device work is in flight; answering it costs only the drain) —
        # the lane workers finish their queues and windows before joining
        for lane in self._lanes:
            lane.close()
        # abort path: fail whatever the scheduler never assigned
        leftovers: list[ServeRequest] = []
        with self._cv:
            leftovers.extend(self._queue)
            self._queue.clear()
        for r in leftovers:
            r._fail(ServerClosed(f"model {self.name!r} closed"))

    # -- warmup --

    def warm(self, padded: DataTable) -> None:
        """Compile (and cache) the program for this padded batch size on
        EVERY lane by executing it through the SAME dispatch path requests
        take — each replica owns its compiled ladder and param upload.
        Blocking; runs on the loader's thread, not the dispatch loop, and
        records nothing in the request stats. Replica compiles are
        independent (own cache host, own sub-mesh) and XLA compilation
        releases the GIL, so lanes warm concurrently — model-load
        latency stays ~one compile per bucket, not replicas × buckets."""
        from mmlspark_tpu.core import plan

        def _one(lane: _Lane) -> None:
            plan.transform_async(self.stages, padded, lane.cache_host,
                                 mesh=lane.mesh,
                                 shard_params=lane.shard_params,
                                 precision=self.precision).result()

        if len(self._lanes) == 1:
            _one(self._lanes[0])
            return
        with futures.ThreadPoolExecutor(
                max_workers=len(self._lanes),
                thread_name_prefix=f"{THREAD_PREFIX}-{self.name}-warm",
        ) as pool:
            for f in [pool.submit(_one, lane) for lane in self._lanes]:
                f.result()

    def probe(self, padded: DataTable) -> DataTable:
        """Synchronously run one padded (bucket-sized) batch through lane
        0's EXACT dispatch path — same compiled-segment cache, mesh,
        param placement, and precision policy as production requests —
        without touching the request stats. The load-time calibration
        entry: ``ModelServer.add_model`` measures the low-precision
        program's parity against the f32 offline transform here
        (docs/quantization.md)."""
        from mmlspark_tpu.core import plan
        lane = self._lanes[0]
        return plan.transform_async(self.stages, padded, lane.cache_host,
                                    mesh=lane.mesh,
                                    shard_params=lane.shard_params,
                                    precision=self.precision).result()

    # -- lifecycle --

    def close(self, drain: bool = True) -> None:
        """Stop admission; ``drain=True`` answers every admitted request
        before the workers exit, ``drain=False`` fails queued requests
        with :class:`ServerClosed`. Idempotent; joins the scheduler and
        every lane worker."""
        with self._cv:
            self._closed = True
            if not drain:
                self._abort = True
            self._cv.notify_all()
        with self._sched_cv:
            self._sched_cv.notify_all()  # unblock an _acquire_lane wait
        deadline = time.monotonic() + self.config.drain_timeout_s
        self._thread.join(timeout=self.config.drain_timeout_s)
        stuck = self._thread.is_alive()
        # join to a FIXED POINT over lane replacements: a lane dying
        # concurrently with close() may still spawn one replacement
        # (its healer checked the closed flag just before we set it);
        # joining a corpse only returns after its healer finished, so
        # any replacement it made is in self._lanes by the next scan
        joined: set[int] = set()
        while True:
            with self._sched_cv:
                todo = [L for L in self._lanes if id(L) not in joined]
            if not todo:
                break
            for lane in todo:
                joined.add(id(lane))
                lane.close()  # idempotent; _run also closes lanes
                if not lane.join(max(deadline - time.monotonic(), 0.1)):
                    stuck = True
        if stuck:  # pragma: no cover - defensive
            _log.warning("ServeBatcher[%s] did not stop within %.1fs",
                         self.name, self.config.drain_timeout_s)
        if _obs_flight._rec is not None and not stuck:
            # these seams are gone for good: drop their heartbeats so a
            # long-lived process with model churn doesn't accumulate
            # dead idle rows in every dump's heartbeat table
            _obs_flight._rec.forget(f"serve/{self.name}/scheduler")
            for lane in self._lanes:
                _obs_flight._rec.forget(lane._hb)

    def compiled_programs(self) -> int | None:
        """XLA executables compiled for this model's serving entry — the
        jit compile-cache hook owned by the obs subsystem
        (:func:`mmlspark_tpu.obs.runtime.compiled_programs`). For a
        replicated model this is the per-model LOGICAL count: the max
        over replicas' caches (each replica compiles the same bucket
        ladder, device-specialized), so the ladder bound stays
        ``<= len(buckets)`` per model, not replicas × buckets. ``None``
        when the jit object doesn't expose its cache size (older jax) —
        callers fall back to ``stats.dispatch_shapes``."""
        if self.replicas is not None:
            return self.replicas.compiled_programs()
        return _obs_rt.compiled_programs(self.cache_host)

"""ModelServer — load, validate, warm, and serve fitted models.

Load path: a served model is any fitted table→table transformer
(``PipelineModel``, ``JaxModel``, …) or a raw :class:`ModelBundle` (wrapped
in a ``JaxModel`` on the spot). Every load runs the PR 2 pre-flight
analyzer first — a model that cannot survive ``analysis.analyze`` fails
the load with :class:`ModelLoadError` *before any device work* (no
compile, no transfer), mirroring transformSchema-at-submit in the
reference. Loads with a concrete input schema (given, or derived from the
bundle's ``input_spec``) also warm the bucket ladder: one compiled program
per (model, bucket) exists before the first request arrives.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Iterable, Mapping

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.retry import RetryPolicy, call_with_retry
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.serve.batcher import DynamicBatcher, ServeRequest
from mmlspark_tpu.serve.config import ServeConfig
from mmlspark_tpu.serve.errors import (
    BadRequest, DeadlineExceeded, LaneFailed, ModelLoadError,
    ModelNotFound, Overloaded, ServeError, ServerClosed,
)
from mmlspark_tpu.serve.stats import ServerStats

_log = get_logger(__name__)


def _as_stages(model: Any) -> tuple[list, Any, Any]:
    """(stage list, cache_host, model) for any servable object.

    A ``ModelBundle`` is wrapped in a ``JaxModel`` reading column
    ``"input"`` and writing ``"scores"`` (the CLI's bundle-file path);
    a ``PipelineModel`` serves its fitted stages through its own
    compiled-segment cache, so online and offline execution share one
    compile + param upload.
    """
    from mmlspark_tpu.models.bundle import ModelBundle
    if isinstance(model, ModelBundle):
        from mmlspark_tpu.models.jax_model import JaxModel
        model = JaxModel(model=model, input_col="input",
                         output_col="scores")
    stages = getattr(model, "stages", None)
    if stages is not None and not callable(stages):
        return list(stages), model, model
    if not hasattr(model, "transform"):
        raise BadRequest(
            f"not a servable model: {type(model).__name__} (needs "
            ".transform or a ModelBundle)")
    return [model], model, model


def _derived_schema(stages: list) -> Any | None:
    """A concrete input schema derivable from the model itself: a leading
    ``JaxModel`` pins its input column to the bundle's ``input_spec``
    (as the flat vector ``coerce_input_matrix`` accepts)."""
    from mmlspark_tpu.analysis.info import ColumnInfo, TableSchema
    from mmlspark_tpu.models.jax_model import JaxModel
    if not stages or not isinstance(stages[0], JaxModel):
        return None
    bundle = stages[0].model
    if bundle is None:
        return None
    size = int(np.prod(tuple(bundle.input_spec)))
    return TableSchema({stages[0].input_col: ColumnInfo.vector(
        size, "float32")})


def _example_rows(schema: Any, n: int) -> DataTable | None:
    """Synthesize an ``n``-row table realizing ``schema`` — the warmup
    input. None when any column's layout is not concrete enough to build
    honest rows (warmup is then skipped; first request pays the compile)."""
    from mmlspark_tpu.analysis.info import (
        KIND_IMAGE, KIND_SCALAR, KIND_TEXT, KIND_VECTOR,
    )
    cols: dict[str, Any] = {}
    meta: dict[str, dict] = {}
    for name, info in schema.columns.items():
        if info.kind == KIND_IMAGE:
            shape = info.concrete_shape
            if shape is None or len(shape) != 3:
                return None
            from mmlspark_tpu.core.schema import make_image
            cols[name] = [make_image(f"warmup{i}",
                                     np.zeros(shape, np.uint8))
                          for i in range(n)]
            meta[name] = {"is_image": True}
        elif info.kind == KIND_VECTOR:
            size = info.row_size
            if size is None:
                return None
            dt = np.uint8 if info.dtype == "uint8" else np.float32
            cols[name] = [np.zeros(size, dt) for _ in range(n)]
        elif info.kind == KIND_SCALAR:
            dt = np.dtype(info.dtype or "float64")
            cols[name] = np.zeros(n, dt)
        elif info.kind == KIND_TEXT:
            cols[name] = [""] * n
        else:
            return None
    if not cols:
        return None
    table = DataTable(cols)
    for name, m in meta.items():
        table = table.with_meta(name, **m)
    return table


# the ONE parity read both the load-time low-precision calibration and
# the shadow-canary drift signal use (serve/lifecycle.py), so their
# tolerances mean the same thing
from mmlspark_tpu.serve.lifecycle import (  # noqa: E402
    max_abs_parity as _max_abs_parity,
)


class _ModelEntry:
    def __init__(self, name: str, model: Any, batcher: DynamicBatcher,
                 schema: Any | None, mesh_spec: Any | None = None,
                 slo: Any = None, health: Any = None,
                 precision: Any = None, parity: float | None = None,
                 version: Any = None):
        self.name = name
        self.model = model
        self.batcher = batcher
        self.schema = schema
        self.mesh_spec = mesh_spec
        self.slo = slo          # obs.slo.SLOTracker
        self.health = health    # obs.health.HealthMonitor
        self.precision = precision  # core.precision.PrecisionPolicy | None
        self.parity = parity    # measured max-abs vs f32 offline at load
        self.version = version  # model-repo version (or caller tag)
        self.canary: Any = None  # serve.lifecycle.CanaryState | None
        # the load call's kwargs, kept so a ladder rollout
        # (ModelServer.apply_ladder) can rebuild this entry identically
        # except for the bucket ladder
        self.load_kwargs: dict = {}
        # adaptive-ladder re-fit policy (lazy; ModelServer.ladder_tick)
        self.ladder_advisor: Any = None


class _GeneratorEntry:
    """One registered token-serving engine: the autoregressive analog of
    :class:`_ModelEntry`. No lanes, no canary — the engine owns its one
    decode loop; SLO sampling rides the same tracker machinery so
    ``/slo`` carries TTFT/ITL burn next to the batch models."""

    def __init__(self, name: str, engine: Any, slo: Any):
        self.name = name
        self.engine = engine    # serve.generate.GenerateBatcher
        self.slo = slo          # obs.slo.SLOTracker


class ModelServer:
    """Serves one or more fitted models through per-model dynamic batchers.

    Thread-safe: :meth:`submit`/:meth:`predict` may be called from any
    number of client threads (the HTTP front end is one such client).
    """

    def __init__(self, config: ServeConfig | None = None):
        from mmlspark_tpu.serve.lifecycle import DecisionJournal
        self.config = config or ServeConfig()
        if self.config.compile_cache:
            # persistent AOT compile cache (process-wide, like the obs
            # pillars): every model this server loads serializes its
            # compiled bucket programs to disk, and a later cold
            # process deserializes them instead of re-compiling. An
            # unwritable dir degrades to a warning inside configure()
            from mmlspark_tpu.core import compile_cache as _cc
            _cc.configure(self.config.compile_cache)
        self._models: dict[str, _ModelEntry] = {}
        self._generators: dict[str, _GeneratorEntry] = {}
        self._lock = named_lock("serve.server.ModelServer._lock")
        self._closed = False
        # lifecycle forensics: swap/canary/promote/rollback and lane
        # death/restart decisions — decisions.jsonl on disk when
        # ServeConfig.lifecycle_dir is set, always the in-memory tail
        self.journal = DecisionJournal(self.config.lifecycle_dir)
        # fleet plane: per-model stats registries ride the process's
        # telemetry snapshots (and the timeseries sampler) so the
        # serve.* series aggregate across the fleet; unregistered on
        # close — a dead server's registries must not keep exporting
        from mmlspark_tpu.obs import fleet as _obs_fleet
        _obs_fleet.add_registry_source(self.metric_registries)

    # -- loading --

    def _build_entry(self, name: str, model: Any,
                     schema: Any | None = None,
                     example: DataTable | None = None,
                     mesh: Any = None, shard_params: Any = None,
                     precision: Any = None, version: Any = None,
                     buckets: Any = None) -> _ModelEntry:
        """Validate, shard, warm, and calibrate one servable — the
        whole load path SHORT of registration, shared by
        :meth:`add_model` (stable loads and hot-swaps) and
        :meth:`deploy_canary` (candidate versions warming concurrently
        with live traffic). Returns a running, warmed entry that is not
        yet routed any requests; on any failure its batcher is closed
        before the raise (no leaked dispatch threads).

        1. **Validate** with the pre-flight analyzer over ``schema`` (or a
           schema derived from the model's own input contract, or an
           inexact empty schema) — error diagnostics raise
           :class:`ModelLoadError` before any device work.
        2. **Shard** (optional): ``mesh`` (or the server-wide
           ``ServeConfig.mesh``) selects the model's serving tier —
           ``dp=N`` replica fan-out and/or ``tp``/``pp`` model-parallel
           sub-meshes (:mod:`mmlspark_tpu.serve.mesh`); ``shard_params``
           optionally overrides every replica's param placement
           (``(mesh, params_tuple) → shardings``). A mesh that does
           not divide the host's device count, or a sharded segment that
           violates its SPMD contract (manual collectives on a dp
           replica; off-contract axes under tp/pp), is a typed
           :class:`ModelLoadError` — still before any device work.
        3. **Resolve precision** (optional): ``precision`` (or the
           server-wide ``ServeConfig.precision``) selects the serving
           :class:`~mmlspark_tpu.core.precision.PrecisionPolicy` —
           ``"bf16"`` activations or ``"int8w"`` weight-only int8, both
           folded into the compile-cache key so every (model, precision)
           owns its own program ladder and device param tree.
        4. **Warm** the bucket ladder when concrete example rows are
           available (``example``, or rows synthesized from the schema):
           one compiled program per bucket exists before the first
           request, on EVERY replica.
        5. **Calibrate** (low-precision loads): the quantized program's
           outputs on the sample batch are measured against the f32
           offline transform; drift past the policy's pinned tolerance
           is a typed :class:`ModelLoadError` (docs/quantization.md).
        6. **Start** the model's dispatch loop (one lane per replica).

        ``buckets`` overrides the server-wide ladder for THIS entry (a
        per-model learned ladder — :meth:`apply_ladder`); the entry's
        batcher, warmup, and calibration all run on the override.
        """
        from mmlspark_tpu.analysis import TableSchema, analyze
        from mmlspark_tpu.core.precision import PrecisionPolicy

        cfg = self.config
        if buckets is not None:
            from mmlspark_tpu.serve.ladder import validate_ladder
            try:
                ladder = validate_ladder(buckets)
            except ValueError as e:
                raise ModelLoadError(name, message=(
                    f"model {name!r}: {e}")) from e
            cfg = dataclasses.replace(self.config, buckets=ladder)
        stages, cache_host, model = _as_stages(model)
        try:
            policy = PrecisionPolicy.parse(
                precision if precision is not None
                else self.config.precision)
        except (TypeError, ValueError) as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: invalid precision policy: {e}")) from e
        if policy is not None and not policy.active:
            policy = None  # f32 = the unwrapped fast path
        if schema is None:
            schema = _derived_schema(stages)
        check_schema = schema if schema is not None \
            else TableSchema({}, exact=False)
        report = analyze(model, check_schema)
        if not report.ok:
            raise ModelLoadError(name, report)

        mesh = mesh if mesh is not None else self.config.mesh
        replicas = lockstep = mesh_spec = None
        if mesh is not None:
            from mmlspark_tpu.serve.mesh import (
                LockstepCoordinator, ServeMeshSpec, build_replicas,
            )
            mesh_spec = ServeMeshSpec.parse(mesh)
            if mesh_spec.lockstep and mesh_spec.dp > 1:
                # lockstep drains every lane before each agreed dispatch,
                # so extra DP replicas could never serve a batch — they'd
                # only cost dp× warm compiles and param HBM. Typed error
                # beats silently serializing a fan-out the caller paid for.
                raise ModelLoadError(name, message=(
                    f"model {name!r}: lockstep serving dispatches one "
                    f"agreed batch at a time, which is incompatible with "
                    f"dp={mesh_spec.dp} replica fan-out — use dp=1 for "
                    f"lockstep models, or drop lockstep for DP scaling"))
            replicas = build_replicas(name, mesh_spec,
                                      shard_params=shard_params)
            self._audit_sharded(name, stages, schema, mesh_spec, replicas,
                                policy)
            # lockstep only on request: build_replicas carves sub-meshes
            # of THIS host's devices, so no serve program today contains
            # a cross-process collective — auto-enabling on process
            # count would fence (and allgather-stall) multi-host
            # processes that serve independent local traffic. The flag
            # exists for callers that feed every process the identical
            # stream (the dryrun harness; a future cross-process mesh).
            if mesh_spec.lockstep:
                lockstep = LockstepCoordinator(name)

        # SLO tracker + health monitor: burn rates over the stats
        # registry (reads only — obs/slo.py), the hysteretic
        # ok/degraded/unhealthy machine over them (obs/health.py).
        # Sampling is on-demand (each /slo, /healthz, or slo_snapshot
        # poll), so an unpolled server pays nothing. The spec parses
        # BEFORE the batcher exists: a malformed ServeConfig.slo must
        # fail the load without leaking dispatch threads
        from mmlspark_tpu.obs.health import HealthMonitor
        from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
        try:
            spec = SLOSpec.parse(cfg.slo)
        except (TypeError, ValueError) as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: invalid SLO spec: {e}")) from e
        stats = ServerStats(
            cfg.stats_window, model=name,
            extra_labels=None if version is None
            else {"version": version})
        batcher = DynamicBatcher(name, stages, cache_host, cfg,
                                 stats, replicas=replicas,
                                 lockstep=lockstep, precision=policy)
        # lane supervision lands in the lifecycle journal: a death or
        # restart is a capacity decision, same forensics as a swap
        batcher.on_lane_event = self.journal.record
        tracker = SLOTracker(spec, stats,
                             queued_fn=lambda: batcher.queued)
        monitor = HealthMonitor.for_spec(spec)
        parity = None
        try:
            if cfg.warmup:
                warm = example
                if warm is None and schema is not None:
                    warm = _example_rows(schema, 1)
                if warm is not None and len(warm):
                    import time as _time
                    t0 = _time.perf_counter()
                    self._warm(batcher, warm)
                    # the warm-start observable: wall seconds to bring
                    # the whole ladder up (XLA compiles when cold,
                    # compile-cache deserializes when warm) — the
                    # serve.warm_wall_s gauge bench A/Bs
                    stats.record_warm_wall(_time.perf_counter() - t0)
                else:
                    _log.info("serve[%s]: no concrete input layout — "
                              "skipping warmup (first request per bucket "
                              "pays the compile)", name)
            parity = self._calibrate(name, model, batcher, policy,
                                     example, schema)
        except BaseException:
            batcher.close(drain=False)
            raise
        return _ModelEntry(name, model, batcher, schema, mesh_spec,
                           slo=tracker, health=monitor, precision=policy,
                           parity=parity, version=version)

    def add_model(self, name: str, model: Any,
                  schema: Any | None = None,
                  example: DataTable | None = None,
                  mesh: Any = None, shard_params: Any = None,
                  precision: Any = None, version: Any = None,
                  buckets: Any = None) -> None:
        """Register ``model`` under ``name`` (see :meth:`_build_entry`
        for the validate → shard → warm → calibrate load path).

        Re-registering a served name is the **hot-swap**: the new
        version loads and warms its whole bucket ladder while the live
        version keeps serving (compiles release the GIL — the PR 7 warm
        discipline), then the name flips to the new entry atomically
        and the old batcher drains — every request admitted before the
        flip is answered by the version that admitted it, and
        :meth:`submit` re-routes the flip race, so no request is ever
        dropped by a swap (``check_serve_lifecycle`` pins this).
        ``version`` tags the entry (the model-repo version, or any
        caller label): it labels the per-version stats registry and the
        journal's swap records. ``buckets`` pins a per-model ladder
        (:meth:`apply_ladder` rolls a learned one out through this same
        path)."""
        entry = self._build_entry(name, model, schema=schema,
                                  example=example, mesh=mesh,
                                  shard_params=shard_params,
                                  precision=precision, version=version,
                                  buckets=buckets)
        entry.load_kwargs = dict(schema=schema, example=example,
                                 mesh=mesh, shard_params=shard_params,
                                 precision=precision, version=version)
        old = canary = None
        with self._lock:
            closed = self._closed
            if not closed:
                old = self._models.get(name)
                if old is not None:
                    # the outgoing version's canary (if any) dies with
                    # it: a swap supersedes an in-flight rollout
                    canary, old.canary = old.canary, None
                self._models[name] = entry
        if closed:
            # teardown outside self._lock: close() joins lane threads,
            # and holding the server-wide lock across those joins would
            # stall every concurrent submit/snapshot (CC102)
            entry.batcher.close(drain=False)
            raise ServerClosed("server is closed",
                               retry_after_s=self.config.retry_after_s)
        if old is not None:
            if canary is not None:
                canary.batcher.close(drain=True)
            old.batcher.close(drain=True)
            self.journal.record("swap", {
                "model": name, "from_version": old.version,
                "to_version": version,
                "canary_superseded": canary is not None})
        _log.info("serve[%s]: loaded (buckets=%s, mesh=%s, "
                  "precision=%s, version=%s)", name,
                  entry.batcher.config.buckets,
                  entry.mesh_spec.describe() if entry.mesh_spec
                  else "default",
                  entry.precision.describe() if entry.precision
                  else "f32", version)

    def add_model_from_repo(self, repo: Any, name: str,
                            version: int | None = None,
                            schema: Any | None = None,
                            example: DataTable | None = None,
                            **kwargs: Any) -> Any:
        """Load ``name`` from a versioned
        :class:`~mmlspark_tpu.models.repo.ModelRepo` (a repo object or
        its root path) and serve it — the repo's digests verify before
        anything deserializes, so a torn or corrupt version raises the
        repo's typed error here and a currently-served version keeps
        serving untouched. Returns the verified ``ModelVersion``."""
        from mmlspark_tpu.models.repo import ModelRepo
        if isinstance(repo, str):
            repo = ModelRepo(repo)
        model, info = repo.load(name, version)
        self.add_model(name, model, schema=schema, example=example,
                       version=info.version, **kwargs)
        return info

    # -- adaptive bucket ladder (serve/ladder.py) --

    def apply_ladder(self, name: str, buckets: Any) -> None:
        """Roll a new bucket ladder out for ``name`` through the
        hot-swap path: the entry rebuilds with the new ladder (warming
        it — with the persistent compile cache live, the new rungs
        deserialize from disk instead of paying XLA compiles), then the
        name flips atomically and the old batcher drains. Zero requests
        dropped, by the same contract as a version swap; the top rung
        must equal the current max bucket so nothing admissible becomes
        inadmissible mid-flight. Journaled as a ``"ladder"`` decision."""
        from mmlspark_tpu.serve.ladder import validate_ladder
        entry = self._entry(name)
        old = entry.batcher.config.buckets
        new = validate_ladder(buckets)
        if new[-1] != old[-1]:
            raise ValueError(
                f"model {name!r}: ladder rollout must keep the top rung "
                f"{old[-1]} (got {new[-1]}) — shrinking it would refuse "
                f"requests the server admitted a moment ago")
        advisor = entry.ladder_advisor
        self.add_model(name, entry.model, buckets=new,
                       **entry.load_kwargs)
        cur = self._entry(name)
        cur.ladder_advisor = advisor  # policy state survives the flip
        self.journal.record("ladder", {
            "model": name, "from_buckets": list(old),
            "to_buckets": list(new)})

    def ladder_tick(self, name: str, budget: int | None = None,
                    advisor: Any = None) -> dict | None:
        """One adaptive-ladder evaluation for ``name``: fit a ladder to
        the observed request-size histogram (``serve.request_rows``)
        under the program budget (default: the current rung count — the
        ``programs <= len(buckets)`` discipline) and, when the window
        is SLO-clean and the fit beats the current ladder by the
        advisor's margin, roll it out via :meth:`apply_ladder`.
        On-demand like ``lifecycle_tick``: polling this is the re-fit
        cadence. Returns the decision dict, or None (no change)."""
        from mmlspark_tpu.obs.health import OK
        from mmlspark_tpu.serve.ladder import LadderAdvisor
        entry = self._entry(name)
        if advisor is not None:
            entry.ladder_advisor = advisor
        elif entry.ladder_advisor is None:
            entry.ladder_advisor = LadderAdvisor()
        _status, health = self._sample_model_health(entry)
        current = entry.batcher.config.buckets
        fitted = entry.ladder_advisor.propose(
            entry.batcher.stats.request_sizes(), current,
            slo_clean=(health["state"] == OK and not health["draining"]),
            budget=budget)
        if fitted is None:
            return None
        self.apply_ladder(name, fitted)
        return {"action": "ladder", "model": name,
                "from_buckets": list(current),
                "to_buckets": list(fitted)}

    def _audit_sharded(self, name: str, stages: list, schema: Any,
                       mesh_spec: Any, replicas: Any,
                       policy: Any = None) -> None:
        """Static SPMD gate for a sharded serve entry, at load time.

        The served segment runs on every replica's sub-mesh, so it must
        honor the sharded-serving contract *before* any compile: a
        DP-replica segment stays manual-collective-free (replicas are
        independent — a collective would deadlock the fan-out), and a
        tp/pp model-parallel segment may communicate only over its
        model-parallel axes, never ``dp``. A low-precision load audits
        the QUANTIZED composite (``policy`` threads into the plan
        replay), so the verified program is the dispatched one. Needs a
        concrete entry layout; a model with no derivable schema skips
        the audit (the analyzer already passed) and relies on the
        repo-wide ``check_spmd_clean`` gate."""
        if schema is None or not replicas.replicas:
            return
        from mmlspark_tpu.analysis.spmd import audit_plan_spmd
        from mmlspark_tpu.serve.mesh import MODEL_PARALLEL_AXES

        expect_axes = (tuple(a for a in MODEL_PARALLEL_AXES)
                       if mesh_spec.model_parallel else None)
        try:
            audit = audit_plan_spmd(stages, schema.entry_meta,
                                    mesh=replicas.replicas[0].mesh,
                                    expect_axes=expect_axes,
                                    precision=policy)
        except Exception as e:  # abstract trace failed: not a verdict
            _log.info("serve[%s]: sharded SPMD audit skipped (%s)",
                      name, e)
            return
        if not audit.ok:
            raise ModelLoadError(name, message=(
                f"model {name!r} failed the sharded-serving SPMD audit "
                f"on mesh {mesh_spec.describe()}:\n" + audit.format()))

    def _warm(self, batcher: DynamicBatcher, example: DataTable) -> None:
        """Compile every bucket by running one padded batch per rung
        through the SAME dispatch path requests take. The rungs come
        from the BATCHER's config — a per-model ladder override warms
        its own ladder, not the server-wide default."""
        row = example.take(np.arange(1))
        for bucket in batcher.config.buckets:
            padded = row if bucket == 1 else row.concat(
                row.take(np.zeros(bucket - 1, dtype=np.int64)))
            batcher.warm(padded)

    def _calibrate(self, name: str, model: Any, batcher: DynamicBatcher,
                   policy: Any, example: DataTable | None,
                   schema: Any) -> float | None:
        """Measured max-abs parity of a low-precision serve program vs
        the f32 offline transform, on the calibration batch (the caller's
        ``example`` sample, else one schema-synthesized row). Weight
        scales need no activation statistics (symmetric per-channel
        max-abs over the weights themselves); what IS calibrated from
        data is the *observed* output drift, checked against the
        policy's pinned tolerance — drift past it fails the load with a
        typed :class:`ModelLoadError` before the model ever serves.
        Returns the measured parity (None when no policy is active or no
        concrete rows exist to calibrate with)."""
        if policy is None:
            return None
        calib = example
        if calib is None and schema is not None:
            calib = _example_rows(schema, 1)
        if calib is None or not len(calib):
            _log.info("serve[%s]: no calibration rows — %s parity "
                      "unverified at load (first requests trust the "
                      "pinned tolerance)", name, policy.describe())
            return None
        n = min(len(calib), batcher.config.max_bucket)
        calib = calib.take(np.arange(n))
        bucket = batcher.config.bucket_for(n, name)
        padded = calib if bucket == n else calib.take(
            np.arange(bucket) % n)
        try:
            ref = model.transform(calib)          # the f32 offline path
            got = batcher.probe(padded)           # the served program
        except BaseException as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: {policy.describe()} calibration run "
                f"failed: {type(e).__name__}: {e}")) from e
        if len(got) != len(padded):  # row-changing transform: serving
            #                          rejects it per batch anyway
            _log.info("serve[%s]: calibration transform changed the row "
                      "count — parity unverified", name)
            return None
        parity = _max_abs_parity(ref, got.take(np.arange(n)),
                                 set(calib.columns))
        tol = policy.resolve_tolerance()
        if parity is not None and parity > tol:
            raise ModelLoadError(name, message=(
                f"model {name!r}: {policy.describe()} serving diverges "
                f"from the f32 offline transform by max-abs {parity:.4g} "
                f"on the {n}-row calibration batch (pinned tolerance "
                f"{tol:g}) — pin a wider per-model tolerance explicitly "
                "or serve at a wider precision"))
        return parity

    # -- request surface --

    def _entry(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise ModelNotFound(name, list(self._models))
            return entry

    def submit(self, name: str, table: DataTable,
               deadline_ms: float | None = None) -> ServeRequest:
        """Admit a request; returns the awaitable handle. ``deadline_ms``
        defaults to the server-wide ``ServeConfig.deadline_ms``.

        Swap-safe: a hot-swap that closes the old batcher between this
        call's entry lookup and its admission re-routes to the entry
        that now owns the name (the zero-dropped-requests contract) —
        ``ServerClosed`` only propagates when the SERVER is closing or
        the model is gone. With a rollout in flight, the canary's
        deterministic router takes its configured fraction: mirrored
        (shadow — the stable answer is returned either way) or split
        (canary — those requests get the candidate's answers)."""
        if deadline_ms is None:
            deadline_ms = self.config.deadline_ms
        while True:
            entry = self._entry(name)
            canary = entry.canary
            take = canary is not None and canary.route()
            if take and canary.mode == "canary":
                try:
                    return canary.batcher.submit(table, deadline_ms)
                except ServerClosed:
                    pass  # rolled back mid-flight: stable serves it
            try:
                req = entry.batcher.submit(table, deadline_ms)
            except ServerClosed:
                with self._lock:
                    closed = self._closed
                    cur = self._models.get(name)
                if closed or cur is None or cur is entry:
                    raise
                continue  # hot-swap raced us: retry on the new entry
            if take and canary.mode == "shadow":
                try:
                    mirror = canary.batcher.submit(table, deadline_ms)
                    canary.note_pair(req, mirror)
                except ServeError:
                    # a shadow must never affect the stable path: a
                    # mirror bounced by canary admission (overload,
                    # rollback race) is burn-visible in the canary
                    # stats, nothing more
                    pass
            return req

    def predict(self, name: str, table: DataTable,
                deadline_ms: float | None = None,
                timeout: float | None = None) -> DataTable:
        """Blocking submit+wait."""
        return self.submit(name, table, deadline_ms).result(timeout)

    # -- autoregressive token serving (serve/generate.py) --

    def add_generator(self, name: str, model: Any, params: Any,
                      config: Any = None,
                      decode_attention_fn: Any = None) -> None:
        """Register an autoregressive token-serving engine under
        ``name``: a causal :class:`~mmlspark_tpu.models.sequence.
        TransformerTagger` (+ its fitted params) served through
        continuous batching with the KV cache as plan-managed device
        state (:class:`~mmlspark_tpu.serve.generate.GenerateBatcher`).

        Generators share the server's SLO machinery — an
        :class:`~mmlspark_tpu.obs.slo.SLOTracker` over the engine's
        :class:`ServerStats` publishes the per-token gauges
        (``serve.ttft_p50_ms``/``serve.ttft_p99_ms``/
        ``serve.itl_p99_ms``) on every ``/slo`` poll, and the engine's
        registry rides ``/metrics`` and the fleet exporter. The name
        space is shared with batch models: one name, one servable."""
        from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
        from mmlspark_tpu.serve.config import GenerateConfig
        from mmlspark_tpu.serve.generate import GenerateBatcher
        cfg = config or GenerateConfig()
        try:
            spec = SLOSpec.parse(self.config.slo)
        except (TypeError, ValueError) as e:
            raise ModelLoadError(name, message=(
                f"generator {name!r}: invalid SLO spec: {e}")) from e
        stats = ServerStats(cfg.stats_window, model=name)
        engine = GenerateBatcher(name, model, params, config=cfg,
                                 stats=stats,
                                 decode_attention_fn=decode_attention_fn)
        tracker = SLOTracker(spec, stats,
                             queued_fn=lambda: engine.queued)
        entry = _GeneratorEntry(name, engine, tracker)
        reject: Exception | None = None
        old = None
        with self._lock:
            if self._closed:
                reject = ServerClosed("server is closed")
            elif name in self._models:
                reject = ModelLoadError(name, message=(
                    f"{name!r} already serves a batch model — one name, "
                    f"one servable"))
            else:
                old = self._generators.get(name)
                self._generators[name] = entry
        if reject is not None:
            engine.close(drain=False)
            raise reject
        if old is not None:
            # re-registration is the generator hot-swap: drained, so
            # every admitted stream is answered by the engine that
            # admitted it
            old.engine.close(drain=True)
            self.journal.record("swap", {"model": name, "generator": True})
        _log.info("serve[%s]: generator loaded (slots=%d, "
                  "prefill_buckets=%s, t_max=%d)", name, cfg.slots,
                  cfg.prefill_buckets, cfg.t_max)

    def _generator(self, name: str) -> _GeneratorEntry:
        with self._lock:
            entry = self._generators.get(name)
            if entry is None:
                raise ModelNotFound(name, list(self._generators))
            return entry

    def generate(self, name: str, prompt: Any,
                 max_new_tokens: int | None = None) -> Any:
        """Admit a generation request on generator ``name``; returns the
        :class:`~mmlspark_tpu.serve.generate.TokenStream` (iterate for
        tokens as they decode, or ``.result()`` for the full list)."""
        return self._generator(name).engine.submit(
            prompt, max_new_tokens=max_new_tokens)

    def generate_oneshot(self, name: str, prompt: Any,
                         max_new_tokens: int | None = None) -> list[int]:
        """Whole-sequence reference decode of one prompt through
        generator ``name``'s OWN compiled programs
        (:meth:`~mmlspark_tpu.serve.generate.GenerateBatcher.oneshot`,
        fresh buffers, engine state untouched) — the bit-identity anchor
        every continuously-batched stream is pinned against."""
        return self._generator(name).engine.oneshot(
            prompt, max_new_tokens=max_new_tokens)

    def generators(self) -> list[str]:
        with self._lock:
            return sorted(self._generators)

    # -- rollout: canary/shadow + SLO-driven promotion (lifecycle.py) --

    def deploy_canary(self, name: str, model: Any,
                      mode: str = "shadow", fraction: float = 0.25,
                      version: Any = None, schema: Any | None = None,
                      example: DataTable | None = None,
                      mesh: Any = None, shard_params: Any = None,
                      precision: Any = None, policy: Any = None,
                      parity_tolerance: float | None = None,
                      promote_after: int = 3) -> None:
        """Start a rollout of ``model`` as ``name``'s candidate version.

        The candidate goes through the full load path (validate, warm
        its own bucket ladder, calibrate) while the stable version keeps
        serving; from then on the configured ``fraction`` of admissions
        is mirrored (``mode="shadow"``: clients still get stable
        answers, outputs are diffed) or split (``mode="canary"``: those
        clients get candidate answers). Each :meth:`lifecycle_tick`
        samples the candidate's burn engine (+ shadow parity vs
        ``parity_tolerance``) and runs ``policy``
        (:class:`~mmlspark_tpu.serve.lifecycle.PromotionPolicy`,
        default derived from the server's SLO spec): fast-burn or
        parity drift auto-rolls back, ``promote_after`` consecutive
        clean windows promote the candidate to stable. Every decision
        is journaled."""
        from mmlspark_tpu.obs.slo import SLOSpec
        from mmlspark_tpu.serve.lifecycle import (
            CanaryState, PromotionPolicy,
        )
        stable = self._entry(name)  # ModelNotFound before any build
        # everything cheap validates BEFORE the expensive build: a bad
        # mode/fraction/policy must not leave a fully warmed candidate
        # batcher running with no owner
        if mode not in ("canary", "shadow"):
            raise ValueError(
                f"canary mode must be 'canary' or 'shadow': {mode!r}")
        if not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1]: {fraction}")
        if policy is None:
            policy = PromotionPolicy.for_spec(
                SLOSpec.parse(self.config.slo), promote_after)
        entry = self._build_entry(name, model, schema=schema,
                                  example=example, mesh=mesh,
                                  shard_params=shard_params,
                                  precision=precision, version=version)
        try:
            state = CanaryState(name, version, mode, fraction,
                                entry.batcher, entry.slo, policy,
                                parity_tolerance=parity_tolerance)
        except ValueError:
            entry.batcher.close(drain=False)
            raise
        state.entry = entry  # promotion flips this whole entry in
        reject: Exception | None = None
        replaced = None
        with self._lock:
            if self._closed:
                reject = ServerClosed("server is closed")
            else:
                cur = self._models.get(name)
                if cur is None:
                    reject = ModelNotFound(name, list(self._models))
                else:
                    replaced, cur.canary = cur.canary, state
        if reject is not None:
            # close the never-attached batcher outside self._lock —
            # close() joins lane threads (CC102 under the server lock)
            entry.batcher.close(drain=False)
            raise reject
        if replaced is not None:
            replaced.batcher.close(drain=True)
        self.journal.record("canary_deploy", {
            "model": name, "version": version, "mode": mode,
            "fraction": fraction,
            "stable_version": stable.version,
            "replaced": None if replaced is None else replaced.version})

    def lifecycle_tick(self, name: str) -> dict | None:
        """One promotion-policy evaluation for ``name``'s rollout (None
        when no canary is deployed): sample the canary's SLO burn + the
        shadow-parity ring into a typed signal, run the pure policy,
        execute the action. On-demand like every PR 8 sampler — polling
        this (or ``/slo``) IS the rollout's evaluation cadence."""
        entry = self._entry(name)
        canary = entry.canary
        if canary is None:
            return None
        with canary.tick_lock:
            result, drain = self._tick_locked(name, entry, canary)
        if drain is not None:
            # drain outside tick_lock: close(drain=True) joins lane
            # threads for the full drain, and holding tick_lock across
            # it would block every concurrent tick/rollback (CC102) —
            # the detach under self._lock already made the decision
            # exactly-once, so racers see a detached canary and bail
            drain.close(drain=True)
        return result

    def _tick_locked(self, name: str, entry: _ModelEntry,
                     canary: Any) -> tuple:
        """One policy evaluation under ``canary.tick_lock``; returns
        ``(result, batcher_to_drain)`` — the caller performs the drain
        after releasing the lock."""
        from mmlspark_tpu.serve.lifecycle import Hold, Promote, Rollback
        if entry.canary is not canary:
            return None, None  # a concurrent tick already decided
        sig = canary.signal()
        action = canary.policy.decide(sig, canary.ledger)
        canary.ledger.ticks += 1
        detail = {
            "model": name, "version": canary.version,
            "mode": canary.mode, "reason": action.reason,
            "burn_short": sig.burn_short, "burn_long": sig.burn_long,
            "terminal_window": sig.terminal_window,
            "parity_drift": sig.parity_drift,
            "clean_windows": canary.ledger.clean_windows,
            "ticks": canary.ledger.ticks,
        }
        if isinstance(action, Rollback):
            drain = self._end_canary(entry, canary, "rollback", detail)
            if drain is not None:
                return {"action": "rollback", **detail}, drain
            return None, None  # a racing close()/swap already detached it
        if isinstance(action, Promote):
            drain = self._promote(entry, canary, detail)
            if drain is not None:
                return {"action": "promote", **detail}, drain
            return None, None
        assert isinstance(action, Hold)
        canary.ledger.clean_windows = (
            canary.ledger.clean_windows + 1 if action.clean else 0)
        detail["clean_windows"] = canary.ledger.clean_windows
        self.journal.record("hold", detail)
        return {"action": "hold", **detail}, None

    def rollback(self, name: str, reason: str = "manual") -> dict | None:
        """Abort ``name``'s rollout now (the operator's big red
        button); None when no canary is deployed."""
        entry = self._entry(name)
        canary = entry.canary
        if canary is None:
            return None
        detail = {"model": name, "version": canary.version,
                  "mode": canary.mode, "reason": reason}
        drain = self._end_canary(entry, canary, "rollback", detail)
        if drain is not None:
            drain.close(drain=True)
            return {"action": "rollback", **detail}
        return None

    def promote(self, name: str, reason: str = "manual") -> dict | None:
        """Promote ``name``'s candidate to stable now — the flip an
        external rollout driver (the lifecycle Deployer) commands once
        its own policy is satisfied, same atomic entry-swap as a
        burn-engine promotion; None when no canary is deployed."""
        entry = self._entry(name)
        canary = entry.canary
        if canary is None:
            return None
        detail = {"model": name, "version": canary.version,
                  "mode": canary.mode, "reason": reason}
        drain = self._promote(entry, canary, detail)
        if drain is not None:
            drain.close(drain=True)
            return {"action": "promote", **detail}
        return None

    def _end_canary(self, entry: _ModelEntry, canary: Any,
                    kind: str, detail: dict) -> Any | None:
        """Atomically detach the canary; returns its batcher for the
        caller to drain with no lock held (None when another thread's
        decision already detached it — exactly one rollback/promote
        ever executes per rollout)."""
        with self._lock:
            if entry.canary is not canary:
                return None
            entry.canary = None
        self.journal.record(kind, {**detail, **canary.describe()})
        return canary.batcher

    def _promote(self, entry: _ModelEntry, canary: Any,
                 detail: dict) -> Any | None:
        """The candidate becomes stable: its (already warm) entry takes
        the name atomically, the outgoing stable drains — the same flip
        as a hot-swap, decided by the burn engine instead of an
        operator.  Returns the outgoing stable's batcher for the caller
        to drain with no lock held (None when a racing close()/swap won)."""
        with self._lock:
            if self._closed or entry.canary is not canary \
                    or self._models.get(entry.name) is not entry:
                # a racing close() owns teardown of whatever is still
                # attached — installing the promoted entry after close
                # snapshots would leak its batcher threads forever
                return None
            entry.canary = None
            promoted = canary.entry
            promoted.canary = None
            self._models[entry.name] = promoted
        self.journal.record("promote", {
            **detail, "from_version": entry.version,
            **canary.describe()})
        return entry.batcher

    def canary_status(self, name: str) -> dict | None:
        entry = self._entry(name)
        return None if entry.canary is None else entry.canary.describe()

    def lifecycle_decisions(self, kind: str | None = None) -> list[dict]:
        """The in-memory decision tail (``decisions.jsonl`` carries the
        same records on disk when ``lifecycle_dir`` is set)."""
        return self.journal.entries(kind)

    # -- introspection --

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def stats(self, name: str) -> ServerStats:
        return self._entry(name).batcher.stats

    def compiled_programs(self, name: str) -> int | None:
        return self._entry(name).batcher.compiled_programs()

    def snapshot(self) -> dict:
        """All models' stats in one JSON-safe dict (the /v1/stats body)."""
        with self._lock:
            entries = list(self._models.values())
            gens = list(self._generators.values())
        out = {}
        for g in gens:
            snap = g.engine.stats.snapshot()
            snap["queued"] = g.engine.queued
            programs = g.engine.compiled_programs()
            if programs is not None:
                snap["programs_compiled"] = programs
            snap["generator"] = True
            out[g.name] = snap
        for e in entries:
            snap = e.batcher.stats.snapshot()
            snap["queued"] = e.batcher.queued
            programs = e.batcher.compiled_programs()
            if programs is not None:
                snap["programs_compiled"] = programs
            if e.mesh_spec is not None:
                snap["mesh"] = e.mesh_spec.describe()
            if e.precision is not None:
                snap["precision"] = e.precision.describe()
                if e.parity is not None:
                    snap["precision_parity"] = e.parity
            if e.version is not None:
                snap["version"] = e.version
            snap["lane_health"] = e.batcher.lane_health()
            canary = e.canary
            if canary is not None:
                snap["canary"] = {
                    **canary.describe(),
                    **{f"stats_{k}": v for k, v in
                       canary.batcher.stats.snapshot().items()
                       if k in ("admitted", "completed", "failed",
                                "timed_out", "rejected_overload")},
                }
            out[e.name] = snap
        return out

    def metric_registries(self) -> list:
        """Every per-model stats registry (plus nothing else) — what the
        HTTP front end hands to the Prometheus exposition alongside the
        process-wide obs registry."""
        with self._lock:
            out = []
            for g in self._generators.values():
                out.append(g.engine.stats.registry)
            for e in self._models.values():
                out.append(e.batcher.stats.registry)
                if e.canary is not None:
                    # the candidate's per-version series (distinct
                    # version label) scrape alongside the stable's
                    out.append(e.canary.batcher.stats.registry)
            return out

    # -- SLO + health surfaces (obs/slo.py + obs/health.py) --

    def _sample_model_health(self, e) -> tuple[dict, dict]:
        """One SLO sample + health-machine advance for one model:
        (status dict, health dict). The single place the per-model
        health shape is built — ``/slo`` and ``/healthz`` must never
        diverge on it. Lane supervision merges in here: a model with a
        dispatch lane down is at least DEGRADED — restarted-but-
        shrunken capacity must show on the health surface, not hide
        behind still-clean latency percentiles."""
        from mmlspark_tpu.obs.health import DEGRADED, SEVERITY
        status = e.slo.sample()
        verdict = e.health.update_describe(status)
        lanes = e.batcher.lane_health()
        state, reason = verdict["state"], verdict["reason"]
        if lanes["alive"] < lanes["lanes"] \
                and SEVERITY[state] < SEVERITY[DEGRADED]:
            down = lanes["lanes"] - lanes["alive"]
            state = DEGRADED
            reason = (f"{down}/{lanes['lanes']} dispatch lane(s) down "
                      f"({lanes['restarts']} restart(s) used)")
        return status, {"state": state, "reason": reason,
                        "draining": e.batcher.closed, "lanes": lanes}

    def slo_snapshot(self) -> dict:
        """Sample every model's SLO tracker and advance its health
        machine; the JSON-safe ``/slo`` body. Each call is one burn-rate
        sample per model (registry reads only — no device work, no
        batcher locks beyond the queue-depth read), so polling this IS
        the sampling cadence — INCLUDING the rollout loop: a model with
        a canary deployed gets one :meth:`lifecycle_tick` per poll, so
        an HTTP-only operator's ``/slo`` probes drive auto-rollback/
        promotion without any in-process caller (the decision, if any,
        rides along under ``"lifecycle"``)."""
        with self._lock:
            entries = list(self._models.values())
            gens = list(self._generators.values())
        out = {}
        for g in gens:
            # a generator's SLO sample carries the per-token gauges
            # (TTFT/ITL percentiles published into its registry) next
            # to the shared burn-rate machinery
            out[g.name] = {**g.slo.sample(), "generator": True}
        for e in entries:
            decision = None
            if e.canary is not None:
                decision = self.lifecycle_tick(e.name)
            status, health = self._sample_model_health(e)
            body = {**status, "health": health}
            if decision is not None:
                body["lifecycle"] = decision
            out[e.name] = body
        return out

    def health(self) -> dict:
        """Drain-aware readiness: the ``/healthz`` body.

        ``status`` is the worst model health state (``ok`` with no
        models — an empty server is a healthy server), ``draining``
        reflects server-wide close, and ``ready`` is the load-balancer
        verdict: accepting traffic AND not unhealthy. The HTTP layer
        maps ``ready`` to 200/503."""
        from mmlspark_tpu.obs.health import UNHEALTHY, worst
        with self._lock:
            closed = self._closed
            entries = list(self._models.values())
        model_health = {}
        for e in entries:
            _status, model_health[e.name] = self._sample_model_health(e)
        overall = worst([h["state"] for h in model_health.values()])
        draining = closed or any(h["draining"]
                                 for h in model_health.values())
        return {
            "status": "draining" if closed else overall,
            "ready": not draining and overall != UNHEALTHY,
            "draining": draining,
            "models": sorted(model_health),
            "model_health": model_health,
        }

    # -- lifecycle --

    def close(self, drain: bool = True) -> None:
        """Shut down every model's batcher. ``drain=True`` (default)
        answers all admitted requests first; no threads survive."""
        from mmlspark_tpu.obs import fleet as _obs_fleet
        _obs_fleet.remove_registry_source(self.metric_registries)
        with self._lock:
            self._closed = True
            entries = list(self._models.values())
            gens = list(self._generators.values())
        for g in gens:
            g.engine.close(drain=drain)
        for e in entries:
            canary, e.canary = e.canary, None
            if canary is not None:
                canary.batcher.close(drain=drain)
            e.batcher.close(drain=drain)

    def __enter__(self) -> "ModelServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# what a client-side retry may NEVER retry, regardless of the policy it
# was handed: an expired deadline is the caller's latency budget spent
# (retrying busts it by construction), and a malformed request or
# unknown model will fail identically every time
_NEVER_RETRY = (DeadlineExceeded, BadRequest, ModelNotFound)

#: the ``retry=True`` policy: transient serving faults only —
#: ``Overloaded`` (admission backpressure: back off and re-offer) and
#: ``LaneFailed`` (a dispatch lane died mid-flight; the supervisor
#: restarts it, a retry lands on healthy capacity)
DEFAULT_PREDICT_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.05, max_delay_s=2.0,
    retry_on=(Overloaded, LaneFailed))


def _retry_policy(retry: Any) -> RetryPolicy | None:
    """Coerce the ``retry=`` argument (None/False = off, True = the
    default policy, or a caller ``RetryPolicy``) and pin the
    never-retry guard INTO the predicate — a caller policy with
    ``retry_on=(ServeError,)`` still cannot re-spend an expired
    deadline or replay a bad request."""
    if retry is None or retry is False:
        return None
    policy = DEFAULT_PREDICT_RETRY if retry is True else retry
    orig = policy.retry_if
    return dataclasses.replace(
        policy,
        retry_if=lambda e: not isinstance(e, _NEVER_RETRY)
        and (orig is None or orig(e)))


class Client:
    """In-process client: the deterministic test/bench surface, mirroring
    what the HTTP front end does without sockets.

    ``retry`` (per call, or a client-wide default) retries TRANSIENT
    serving faults through :mod:`mmlspark_tpu.core.retry` — by default
    ``Overloaded`` backpressure and ``LaneFailed`` lane deaths, with
    jittered exponential backoff. ``DeadlineExceeded``/``BadRequest``/
    ``ModelNotFound`` are never retried (enforced even against a
    broader caller policy). Each attempt is a fresh submission with a
    fresh ``deadline_ms`` budget."""

    def __init__(self, server: ModelServer, retry: Any = None):
        self.server = server
        self._retry = retry

    def predict(self, model: str,
                rows: DataTable | Iterable[Mapping[str, Any]],
                deadline_ms: float | None = None,
                columns: Iterable[str] | None = None,
                timeout: float | None = None,
                retry: Any = None) -> DataTable:
        if not isinstance(rows, DataTable):
            rows = DataTable.from_rows(list(rows))
        policy = _retry_policy(retry if retry is not None
                               else self._retry)
        if policy is None:
            out = self.server.predict(model, rows, deadline_ms, timeout)
        else:
            out = call_with_retry(
                lambda: self.server.predict(model, rows, deadline_ms,
                                            timeout), policy)
        if columns is not None:
            out = out.select(*columns)
        return out

    def predict_async(self, model: str,
                      rows: DataTable | Iterable[Mapping[str, Any]],
                      deadline_ms: float | None = None,
                      retry: Any = None) -> ServeRequest:
        """Async submit; ``retry`` covers the SUBMISSION (admission
        backpressure) only — once a handle exists, waiting on it is the
        caller's, and retrying a dispatched request would risk the
        double-response the whole pipeline is built to never produce."""
        if not isinstance(rows, DataTable):
            rows = DataTable.from_rows(list(rows))
        policy = _retry_policy(retry if retry is not None
                               else self._retry)
        if policy is None:
            return self.server.submit(model, rows, deadline_ms)
        return call_with_retry(
            lambda: self.server.submit(model, rows, deadline_ms), policy)

    def generate(self, model: str, prompt: Iterable[int],
                 max_new_tokens: int | None = None,
                 stream: bool = False,
                 timeout: float | None = None,
                 retry: Any = None) -> Any:
        """Token generation on a registered generator. ``stream=True``
        returns the :class:`~mmlspark_tpu.serve.generate.TokenStream`
        (iterate for tokens as they decode); the default blocks for the
        full token list. ``retry`` covers ADMISSION only (the same
        contract as :meth:`predict_async` — a stream that exists is
        never resubmitted)."""
        policy = _retry_policy(retry if retry is not None
                               else self._retry)
        prompt = list(prompt)
        if policy is None:
            handle = self.server.generate(model, prompt, max_new_tokens)
        else:
            handle = call_with_retry(
                lambda: self.server.generate(model, prompt,
                                             max_new_tokens), policy)
        return handle if stream else handle.result(timeout)

"""ModelServer — load, validate, warm, and serve fitted models.

Load path: a served model is any fitted table→table transformer
(``PipelineModel``, ``JaxModel``, …) or a raw :class:`ModelBundle` (wrapped
in a ``JaxModel`` on the spot). Every load runs the PR 2 pre-flight
analyzer first — a model that cannot survive ``analysis.analyze`` fails
the load with :class:`ModelLoadError` *before any device work* (no
compile, no transfer), mirroring transformSchema-at-submit in the
reference. Loads with a concrete input schema (given, or derived from the
bundle's ``input_spec``) also warm the bucket ladder: one compiled program
per (model, bucket) exists before the first request arrives.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.serve.batcher import DynamicBatcher, ServeRequest
from mmlspark_tpu.serve.config import ServeConfig
from mmlspark_tpu.serve.errors import (
    BadRequest, ModelLoadError, ModelNotFound, ServerClosed,
)
from mmlspark_tpu.serve.stats import ServerStats

_log = get_logger(__name__)


def _as_stages(model: Any) -> tuple[list, Any, Any]:
    """(stage list, cache_host, model) for any servable object.

    A ``ModelBundle`` is wrapped in a ``JaxModel`` reading column
    ``"input"`` and writing ``"scores"`` (the CLI's bundle-file path);
    a ``PipelineModel`` serves its fitted stages through its own
    compiled-segment cache, so online and offline execution share one
    compile + param upload.
    """
    from mmlspark_tpu.models.bundle import ModelBundle
    if isinstance(model, ModelBundle):
        from mmlspark_tpu.models.jax_model import JaxModel
        model = JaxModel(model=model, input_col="input",
                         output_col="scores")
    stages = getattr(model, "stages", None)
    if stages is not None and not callable(stages):
        return list(stages), model, model
    if not hasattr(model, "transform"):
        raise BadRequest(
            f"not a servable model: {type(model).__name__} (needs "
            ".transform or a ModelBundle)")
    return [model], model, model


def _derived_schema(stages: list) -> Any | None:
    """A concrete input schema derivable from the model itself: a leading
    ``JaxModel`` pins its input column to the bundle's ``input_spec``
    (as the flat vector ``coerce_input_matrix`` accepts)."""
    from mmlspark_tpu.analysis.info import ColumnInfo, TableSchema
    from mmlspark_tpu.models.jax_model import JaxModel
    if not stages or not isinstance(stages[0], JaxModel):
        return None
    bundle = stages[0].model
    if bundle is None:
        return None
    size = int(np.prod(tuple(bundle.input_spec)))
    return TableSchema({stages[0].input_col: ColumnInfo.vector(
        size, "float32")})


def _example_rows(schema: Any, n: int) -> DataTable | None:
    """Synthesize an ``n``-row table realizing ``schema`` — the warmup
    input. None when any column's layout is not concrete enough to build
    honest rows (warmup is then skipped; first request pays the compile)."""
    from mmlspark_tpu.analysis.info import (
        KIND_IMAGE, KIND_SCALAR, KIND_TEXT, KIND_VECTOR,
    )
    cols: dict[str, Any] = {}
    meta: dict[str, dict] = {}
    for name, info in schema.columns.items():
        if info.kind == KIND_IMAGE:
            shape = info.concrete_shape
            if shape is None or len(shape) != 3:
                return None
            from mmlspark_tpu.core.schema import make_image
            cols[name] = [make_image(f"warmup{i}",
                                     np.zeros(shape, np.uint8))
                          for i in range(n)]
            meta[name] = {"is_image": True}
        elif info.kind == KIND_VECTOR:
            size = info.row_size
            if size is None:
                return None
            dt = np.uint8 if info.dtype == "uint8" else np.float32
            cols[name] = [np.zeros(size, dt) for _ in range(n)]
        elif info.kind == KIND_SCALAR:
            dt = np.dtype(info.dtype or "float64")
            cols[name] = np.zeros(n, dt)
        elif info.kind == KIND_TEXT:
            cols[name] = [""] * n
        else:
            return None
    if not cols:
        return None
    table = DataTable(cols)
    for name, m in meta.items():
        table = table.with_meta(name, **m)
    return table


def _max_abs_parity(ref: DataTable, got: DataTable,
                    input_cols: set) -> float | None:
    """Worst max-abs difference across the transform's numeric output
    columns (columns the transform ADDED preferred; all shared numeric
    columns when it only rewrote existing ones). None when nothing
    numeric is comparable."""
    cols = [c for c in ref.columns
            if c in got.columns and c not in input_cols]
    if not cols:
        cols = [c for c in ref.columns if c in got.columns]
    worst = None
    for c in cols:
        pair = []
        for col in (ref[c], got[c]):
            try:
                if col.dtype == object:
                    pair.append(np.stack([np.asarray(v, np.float64)
                                          for v in col]))
                else:
                    pair.append(np.asarray(col, np.float64))
            except (TypeError, ValueError):
                pair = []
                break
        if len(pair) != 2 or pair[0].shape != pair[1].shape:
            continue  # non-numeric (images, text) or layout-changing
        diff = float(np.abs(pair[0] - pair[1]).max()) if pair[0].size \
            else 0.0
        worst = diff if worst is None else max(worst, diff)
    return worst


class _ModelEntry:
    def __init__(self, name: str, model: Any, batcher: DynamicBatcher,
                 schema: Any | None, mesh_spec: Any | None = None,
                 slo: Any = None, health: Any = None,
                 precision: Any = None, parity: float | None = None):
        self.name = name
        self.model = model
        self.batcher = batcher
        self.schema = schema
        self.mesh_spec = mesh_spec
        self.slo = slo          # obs.slo.SLOTracker
        self.health = health    # obs.health.HealthMonitor
        self.precision = precision  # core.precision.PrecisionPolicy | None
        self.parity = parity    # measured max-abs vs f32 offline at load


class ModelServer:
    """Serves one or more fitted models through per-model dynamic batchers.

    Thread-safe: :meth:`submit`/:meth:`predict` may be called from any
    number of client threads (the HTTP front end is one such client).
    """

    def __init__(self, config: ServeConfig | None = None):
        self.config = config or ServeConfig()
        self._models: dict[str, _ModelEntry] = {}
        self._lock = threading.Lock()
        self._closed = False

    # -- loading --

    def add_model(self, name: str, model: Any,
                  schema: Any | None = None,
                  example: DataTable | None = None,
                  mesh: Any = None, shard_params: Any = None,
                  precision: Any = None) -> None:
        """Register ``model`` under ``name``.

        1. **Validate** with the pre-flight analyzer over ``schema`` (or a
           schema derived from the model's own input contract, or an
           inexact empty schema) — error diagnostics raise
           :class:`ModelLoadError` before any device work.
        2. **Shard** (optional): ``mesh`` (or the server-wide
           ``ServeConfig.mesh``) selects the model's serving tier —
           ``dp=N`` replica fan-out and/or ``tp``/``pp`` model-parallel
           sub-meshes (:mod:`mmlspark_tpu.serve.mesh`); ``shard_params``
           optionally overrides every replica's param placement
           (``(mesh, params_tuple) → shardings``). A mesh that does
           not divide the host's device count, or a sharded segment that
           violates its SPMD contract (manual collectives on a dp
           replica; off-contract axes under tp/pp), is a typed
           :class:`ModelLoadError` — still before any device work.
        3. **Resolve precision** (optional): ``precision`` (or the
           server-wide ``ServeConfig.precision``) selects the serving
           :class:`~mmlspark_tpu.core.precision.PrecisionPolicy` —
           ``"bf16"`` activations or ``"int8w"`` weight-only int8, both
           folded into the compile-cache key so every (model, precision)
           owns its own program ladder and device param tree.
        4. **Warm** the bucket ladder when concrete example rows are
           available (``example``, or rows synthesized from the schema):
           one compiled program per bucket exists before the first
           request, on EVERY replica.
        5. **Calibrate** (low-precision loads): the quantized program's
           outputs on the sample batch are measured against the f32
           offline transform; drift past the policy's pinned tolerance
           is a typed :class:`ModelLoadError` (docs/quantization.md).
        6. **Start** the model's dispatch loop (one lane per replica).
        """
        from mmlspark_tpu.analysis import TableSchema, analyze
        from mmlspark_tpu.core.precision import PrecisionPolicy

        stages, cache_host, model = _as_stages(model)
        try:
            policy = PrecisionPolicy.parse(
                precision if precision is not None
                else self.config.precision)
        except (TypeError, ValueError) as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: invalid precision policy: {e}")) from e
        if policy is not None and not policy.active:
            policy = None  # f32 = the unwrapped fast path
        if schema is None:
            schema = _derived_schema(stages)
        check_schema = schema if schema is not None \
            else TableSchema({}, exact=False)
        report = analyze(model, check_schema)
        if not report.ok:
            raise ModelLoadError(name, report)

        mesh = mesh if mesh is not None else self.config.mesh
        replicas = lockstep = mesh_spec = None
        if mesh is not None:
            from mmlspark_tpu.serve.mesh import (
                LockstepCoordinator, ServeMeshSpec, build_replicas,
            )
            mesh_spec = ServeMeshSpec.parse(mesh)
            if mesh_spec.lockstep and mesh_spec.dp > 1:
                # lockstep drains every lane before each agreed dispatch,
                # so extra DP replicas could never serve a batch — they'd
                # only cost dp× warm compiles and param HBM. Typed error
                # beats silently serializing a fan-out the caller paid for.
                raise ModelLoadError(name, message=(
                    f"model {name!r}: lockstep serving dispatches one "
                    f"agreed batch at a time, which is incompatible with "
                    f"dp={mesh_spec.dp} replica fan-out — use dp=1 for "
                    f"lockstep models, or drop lockstep for DP scaling"))
            replicas = build_replicas(name, mesh_spec,
                                      shard_params=shard_params)
            self._audit_sharded(name, stages, schema, mesh_spec, replicas,
                                policy)
            # lockstep only on request: build_replicas carves sub-meshes
            # of THIS host's devices, so no serve program today contains
            # a cross-process collective — auto-enabling on process
            # count would fence (and allgather-stall) multi-host
            # processes that serve independent local traffic. The flag
            # exists for callers that feed every process the identical
            # stream (the dryrun harness; a future cross-process mesh).
            if mesh_spec.lockstep:
                lockstep = LockstepCoordinator(name)

        # SLO tracker + health monitor: burn rates over the stats
        # registry (reads only — obs/slo.py), the hysteretic
        # ok/degraded/unhealthy machine over them (obs/health.py).
        # Sampling is on-demand (each /slo, /healthz, or slo_snapshot
        # poll), so an unpolled server pays nothing. The spec parses
        # BEFORE the batcher exists: a malformed ServeConfig.slo must
        # fail the load without leaking dispatch threads
        from mmlspark_tpu.obs.health import HealthMonitor
        from mmlspark_tpu.obs.slo import SLOSpec, SLOTracker
        try:
            spec = SLOSpec.parse(self.config.slo)
        except (TypeError, ValueError) as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: invalid SLO spec: {e}")) from e
        stats = ServerStats(self.config.stats_window, model=name)
        batcher = DynamicBatcher(name, stages, cache_host, self.config,
                                 stats, replicas=replicas,
                                 lockstep=lockstep, precision=policy)
        tracker = SLOTracker(spec, stats,
                             queued_fn=lambda: batcher.queued)
        monitor = HealthMonitor.for_spec(spec)
        parity = None
        try:
            if self.config.warmup:
                warm = example
                if warm is None and schema is not None:
                    warm = _example_rows(schema, 1)
                if warm is not None and len(warm):
                    self._warm(batcher, warm)
                else:
                    _log.info("serve[%s]: no concrete input layout — "
                              "skipping warmup (first request per bucket "
                              "pays the compile)", name)
            parity = self._calibrate(name, model, batcher, policy,
                                     example, schema)
        except BaseException:
            batcher.close(drain=False)
            raise
        with self._lock:
            if self._closed:
                batcher.close(drain=False)
                raise ServerClosed("server is closed")
            old = self._models.get(name)
            self._models[name] = _ModelEntry(name, model, batcher, schema,
                                             mesh_spec, slo=tracker,
                                             health=monitor,
                                             precision=policy,
                                             parity=parity)
        if old is not None:
            old.batcher.close(drain=True)
        _log.info("serve[%s]: loaded (%d stage(s), buckets=%s, mesh=%s, "
                  "precision=%s)", name, len(stages), self.config.buckets,
                  mesh_spec.describe() if mesh_spec else "default",
                  policy.describe() if policy else "f32")

    def _audit_sharded(self, name: str, stages: list, schema: Any,
                       mesh_spec: Any, replicas: Any,
                       policy: Any = None) -> None:
        """Static SPMD gate for a sharded serve entry, at load time.

        The served segment runs on every replica's sub-mesh, so it must
        honor the sharded-serving contract *before* any compile: a
        DP-replica segment stays manual-collective-free (replicas are
        independent — a collective would deadlock the fan-out), and a
        tp/pp model-parallel segment may communicate only over its
        model-parallel axes, never ``dp``. A low-precision load audits
        the QUANTIZED composite (``policy`` threads into the plan
        replay), so the verified program is the dispatched one. Needs a
        concrete entry layout; a model with no derivable schema skips
        the audit (the analyzer already passed) and relies on the
        repo-wide ``check_spmd_clean`` gate."""
        if schema is None or not replicas.replicas:
            return
        from mmlspark_tpu.analysis.spmd import audit_plan_spmd
        from mmlspark_tpu.serve.mesh import MODEL_PARALLEL_AXES

        expect_axes = (tuple(a for a in MODEL_PARALLEL_AXES)
                       if mesh_spec.model_parallel else None)
        try:
            audit = audit_plan_spmd(stages, schema.entry_meta,
                                    mesh=replicas.replicas[0].mesh,
                                    expect_axes=expect_axes,
                                    precision=policy)
        except Exception as e:  # abstract trace failed: not a verdict
            _log.info("serve[%s]: sharded SPMD audit skipped (%s)",
                      name, e)
            return
        if not audit.ok:
            raise ModelLoadError(name, message=(
                f"model {name!r} failed the sharded-serving SPMD audit "
                f"on mesh {mesh_spec.describe()}:\n" + audit.format()))

    def _warm(self, batcher: DynamicBatcher, example: DataTable) -> None:
        """Compile every bucket by running one padded batch per rung
        through the SAME dispatch path requests take."""
        row = example.take(np.arange(1))
        for bucket in self.config.buckets:
            padded = row if bucket == 1 else row.concat(
                row.take(np.zeros(bucket - 1, dtype=np.int64)))
            batcher.warm(padded)

    def _calibrate(self, name: str, model: Any, batcher: DynamicBatcher,
                   policy: Any, example: DataTable | None,
                   schema: Any) -> float | None:
        """Measured max-abs parity of a low-precision serve program vs
        the f32 offline transform, on the calibration batch (the caller's
        ``example`` sample, else one schema-synthesized row). Weight
        scales need no activation statistics (symmetric per-channel
        max-abs over the weights themselves); what IS calibrated from
        data is the *observed* output drift, checked against the
        policy's pinned tolerance — drift past it fails the load with a
        typed :class:`ModelLoadError` before the model ever serves.
        Returns the measured parity (None when no policy is active or no
        concrete rows exist to calibrate with)."""
        if policy is None:
            return None
        calib = example
        if calib is None and schema is not None:
            calib = _example_rows(schema, 1)
        if calib is None or not len(calib):
            _log.info("serve[%s]: no calibration rows — %s parity "
                      "unverified at load (first requests trust the "
                      "pinned tolerance)", name, policy.describe())
            return None
        n = min(len(calib), self.config.max_bucket)
        calib = calib.take(np.arange(n))
        bucket = self.config.bucket_for(n, name)
        padded = calib if bucket == n else calib.take(
            np.arange(bucket) % n)
        try:
            ref = model.transform(calib)          # the f32 offline path
            got = batcher.probe(padded)           # the served program
        except BaseException as e:
            raise ModelLoadError(name, message=(
                f"model {name!r}: {policy.describe()} calibration run "
                f"failed: {type(e).__name__}: {e}")) from e
        if len(got) != len(padded):  # row-changing transform: serving
            #                          rejects it per batch anyway
            _log.info("serve[%s]: calibration transform changed the row "
                      "count — parity unverified", name)
            return None
        parity = _max_abs_parity(ref, got.take(np.arange(n)),
                                 set(calib.columns))
        tol = policy.resolve_tolerance()
        if parity is not None and parity > tol:
            raise ModelLoadError(name, message=(
                f"model {name!r}: {policy.describe()} serving diverges "
                f"from the f32 offline transform by max-abs {parity:.4g} "
                f"on the {n}-row calibration batch (pinned tolerance "
                f"{tol:g}) — pin a wider per-model tolerance explicitly "
                "or serve at a wider precision"))
        return parity

    # -- request surface --

    def _entry(self, name: str) -> _ModelEntry:
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise ModelNotFound(name, list(self._models))
            return entry

    def submit(self, name: str, table: DataTable,
               deadline_ms: float | None = None) -> ServeRequest:
        """Admit a request; returns the awaitable handle. ``deadline_ms``
        defaults to the server-wide ``ServeConfig.deadline_ms``."""
        if deadline_ms is None:
            deadline_ms = self.config.deadline_ms
        return self._entry(name).batcher.submit(table, deadline_ms)

    def predict(self, name: str, table: DataTable,
                deadline_ms: float | None = None,
                timeout: float | None = None) -> DataTable:
        """Blocking submit+wait."""
        return self.submit(name, table, deadline_ms).result(timeout)

    # -- introspection --

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def stats(self, name: str) -> ServerStats:
        return self._entry(name).batcher.stats

    def compiled_programs(self, name: str) -> int | None:
        return self._entry(name).batcher.compiled_programs()

    def snapshot(self) -> dict:
        """All models' stats in one JSON-safe dict (the /v1/stats body)."""
        with self._lock:
            entries = list(self._models.values())
        out = {}
        for e in entries:
            snap = e.batcher.stats.snapshot()
            snap["queued"] = e.batcher.queued
            programs = e.batcher.compiled_programs()
            if programs is not None:
                snap["programs_compiled"] = programs
            if e.mesh_spec is not None:
                snap["mesh"] = e.mesh_spec.describe()
            if e.precision is not None:
                snap["precision"] = e.precision.describe()
                if e.parity is not None:
                    snap["precision_parity"] = e.parity
            out[e.name] = snap
        return out

    def metric_registries(self) -> list:
        """Every per-model stats registry (plus nothing else) — what the
        HTTP front end hands to the Prometheus exposition alongside the
        process-wide obs registry."""
        with self._lock:
            return [e.batcher.stats.registry
                    for e in self._models.values()]

    # -- SLO + health surfaces (obs/slo.py + obs/health.py) --

    def _sample_model_health(self, e) -> tuple[dict, dict]:
        """One SLO sample + health-machine advance for one model:
        (status dict, health dict). The single place the per-model
        health shape is built — ``/slo`` and ``/healthz`` must never
        diverge on it."""
        status = e.slo.sample()
        verdict = e.health.update_describe(status)
        return status, {**verdict, "draining": e.batcher.closed}

    def slo_snapshot(self) -> dict:
        """Sample every model's SLO tracker and advance its health
        machine; the JSON-safe ``/slo`` body. Each call is one burn-rate
        sample per model (registry reads only — no device work, no
        batcher locks beyond the queue-depth read), so polling this IS
        the sampling cadence."""
        with self._lock:
            entries = list(self._models.values())
        out = {}
        for e in entries:
            status, health = self._sample_model_health(e)
            out[e.name] = {**status, "health": health}
        return out

    def health(self) -> dict:
        """Drain-aware readiness: the ``/healthz`` body.

        ``status`` is the worst model health state (``ok`` with no
        models — an empty server is a healthy server), ``draining``
        reflects server-wide close, and ``ready`` is the load-balancer
        verdict: accepting traffic AND not unhealthy. The HTTP layer
        maps ``ready`` to 200/503."""
        from mmlspark_tpu.obs.health import UNHEALTHY, worst
        with self._lock:
            closed = self._closed
            entries = list(self._models.values())
        model_health = {}
        for e in entries:
            _status, model_health[e.name] = self._sample_model_health(e)
        overall = worst([h["state"] for h in model_health.values()])
        draining = closed or any(h["draining"]
                                 for h in model_health.values())
        return {
            "status": "draining" if closed else overall,
            "ready": not draining and overall != UNHEALTHY,
            "draining": draining,
            "models": sorted(model_health),
            "model_health": model_health,
        }

    # -- lifecycle --

    def close(self, drain: bool = True) -> None:
        """Shut down every model's batcher. ``drain=True`` (default)
        answers all admitted requests first; no threads survive."""
        with self._lock:
            self._closed = True
            entries = list(self._models.values())
        for e in entries:
            e.batcher.close(drain=drain)

    def __enter__(self) -> "ModelServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class Client:
    """In-process client: the deterministic test/bench surface, mirroring
    what the HTTP front end does without sockets."""

    def __init__(self, server: ModelServer):
        self.server = server

    def predict(self, model: str,
                rows: DataTable | Iterable[Mapping[str, Any]],
                deadline_ms: float | None = None,
                columns: Iterable[str] | None = None,
                timeout: float | None = None) -> DataTable:
        if not isinstance(rows, DataTable):
            rows = DataTable.from_rows(list(rows))
        out = self.server.predict(model, rows, deadline_ms, timeout)
        if columns is not None:
            out = out.select(*columns)
        return out

    def predict_async(self, model: str,
                      rows: DataTable | Iterable[Mapping[str, Any]],
                      deadline_ms: float | None = None) -> ServeRequest:
        if not isinstance(rows, DataTable):
            rows = DataTable.from_rows(list(rows))
        return self.server.submit(model, rows, deadline_ms)

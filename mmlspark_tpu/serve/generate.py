"""Autoregressive token serving — slot-based continuous batching.

The serve plane's streaming-generate traffic class (Orca-style
iteration-level scheduling with the KV-cache as explicit device state,
the vLLM insight), built from three repo primitives:

* the KV-cache is a **stateful plan segment**
  (:class:`~mmlspark_tpu.core.plan.StatefulSegment`): one slot-major
  pair ``[slots, layers, heads, T_max, head_dim]`` allocated per engine,
  carried as a *donated* argument so every prefill/decode program
  updates it in place — no per-token reallocation, no H2D re-upload;
* **prefill** packs waiting prompts through a PR 15 length-bucketed
  ladder (``GenerateConfig.prefill_buckets`` — validated, warmable) at a
  fixed row width, runs the full causal forward once, and scatters each
  prompt's per-layer K/V into its assigned slot (pad rows scatter to the
  out-of-bounds slot id and are dropped by XLA);
* **decode** is ONE fixed-shape program ``[slots]`` forever: requests
  join and leave per token step via the active-slot mask, inactive
  rows' cache writes are masked off, and the per-row argmax is greedy —
  so a request's token stream is **bit-identical** whether it decodes
  alone or packed with churning neighbors (row independence through the
  SAME compiled program; the correctness anchor the tier-1 gate pins
  against :meth:`GenerateBatcher.oneshot`).

Total compiled programs ≤ ``len(prefill_buckets) + 1``, counted
honestly via :func:`mmlspark_tpu.obs.runtime.compiled_programs` over
the engine's own plan cache (the engine is its own cache host).

The decode loop never blocks on the token it just dispatched: the host
fetch lags one step (consume step *t* while step *t+1* computes), the
carry token rides forward on the device, and prompt joins inject their
prefill token through the in-program merge — the JX109 lint exists to
keep it that way.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import flight as _obs_flight
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.lockwitness import named_condition
from mmlspark_tpu.obs.spans import span as _obs_span
from mmlspark_tpu.serve import faults as _faults
from mmlspark_tpu.serve.batcher import THREAD_PREFIX
from mmlspark_tpu.serve.config import GenerateConfig
from mmlspark_tpu.serve.errors import BadRequest, Overloaded, ServerClosed
from mmlspark_tpu.serve.faults import InjectedFault
from mmlspark_tpu.serve.stats import ServerStats

_log = get_logger(__name__)


# ---- the two programs (built once per engine; also what the SPMD
#      entry point `serve_decode_replica` traces) ----

def build_prefill_step(model):
    """``(bufs, params, tokens [P, L], attn_mask [P, L], lengths [P],
    slot_ids [P]) -> (bufs', first_token [P])`` — the prefill program.

    One full causal forward over the packed prompt batch; every layer's
    K/V scatters into the slot-major cache at the assigned slots (a pad
    row carries ``slot_id == slots``, out of bounds, which XLA drops
    from the scatter — the guard that keeps pad rows from clobbering a
    live slot), and the returned first token is the greedy argmax at
    each prompt's last real position."""
    import jax.numpy as jnp

    def prefill_step(bufs, params, tokens, attn_mask, lengths, slot_ids):
        L = tokens.shape[1]
        logits, (pk, pv) = model.apply(
            {"params": params}, tokens, mask=attn_mask, return_cache=True)
        ck = bufs["k"].at[slot_ids, :, :, :L, :].set(pk)
        cv = bufs["v"].at[slot_ids, :, :, :L, :].set(pv)
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        first = jnp.argmax(last, axis=-1).astype(jnp.int32)
        return {"k": ck, "v": cv}, first

    return prefill_step


def build_decode_step(model, decode_attention_fn=None):
    """``(bufs, params, carry [S], injected [S], inject [S], positions
    [S], active [S]) -> (bufs', next_token [S])`` — THE decode program.

    ``carry`` is the previous step's own output (a device array that
    never visits the host on the hot path); a slot that just joined
    overrides it with its prefill token through ``inject``. The model
    writes the new token's K/V at ``positions`` (inactive rows masked
    off), attends ``q_len=1`` against the cache, and the next token is
    the greedy per-row argmax — inactive rows pass their input through
    unchanged, so the program's shape (and its ONE compilation) never
    depends on who is active."""
    import jax.numpy as jnp

    def decode_step(bufs, params, carry, injected, inject, positions,
                    active):
        tokens = jnp.where(inject, injected, carry).astype(jnp.int32)
        logits, (ck, cv) = model.apply(
            {"params": params}, tokens[:, None],
            cache=(bufs["k"], bufs["v"]), positions=positions,
            update_mask=active, decode_attention_fn=decode_attention_fn)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tokens)
        return {"k": ck, "v": cv}, nxt

    return decode_step


# ---- per-request surfaces ----

class TokenStream:
    """Streaming handle for one generate request.

    Iterate to receive tokens as they are produced, or block on
    :meth:`result` for the full list. Terminal exactly once: finished
    (``cancelled`` True when a churn cancel truncated it — the stream
    delivered a *prefix* of the full decode, never a wrong token) or
    failed with one typed error.
    """

    __slots__ = ("model", "_cv", "_tokens", "_done", "_error", "cancelled")

    def __init__(self, model: str):
        self.model = model
        self._cv = named_condition("serve.generate.TokenStream._cv")
        self._tokens: list[int] = []
        self._done = False
        self._error: BaseException | None = None
        self.cancelled = False

    # -- engine side --

    def _push(self, tok: int) -> None:
        with self._cv:
            self._tokens.append(tok)
            self._cv.notify_all()

    def _finish(self, cancelled: bool = False) -> None:
        with self._cv:
            self._done = True
            self.cancelled = cancelled
            self._cv.notify_all()

    def _fail(self, err: BaseException) -> None:
        with self._cv:
            self._error = err
            self._done = True
            self._cv.notify_all()

    # -- client side --

    @property
    def done(self) -> bool:
        with self._cv:
            return self._done

    @property
    def tokens(self) -> list[int]:
        """Snapshot of everything streamed so far."""
        with self._cv:
            return list(self._tokens)

    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while len(self._tokens) <= i and not self._done:
                    self._cv.wait()
                if len(self._tokens) > i:
                    tok = self._tokens[i]
                else:
                    if self._error is not None:
                        raise self._error
                    return
            yield tok
            i += 1

    def result(self, timeout: float | None = None) -> list[int]:
        """Block until terminal; the full token list, or the typed
        error."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._done:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    raise TimeoutError(
                        f"model {self.model!r}: stream not terminal "
                        f"within {timeout}s")
                self._cv.wait(rem)
            if self._error is not None:
                raise self._error
            return list(self._tokens)


class GenerateRequest:
    """Engine-internal state of one admitted generate request."""

    __slots__ = ("prompt", "max_new", "stream", "slot", "emitted",
                 "steps_done", "steps_needed", "done", "cancelled",
                 "submitted", "last_token_t")

    def __init__(self, prompt: list[int], max_new: int,
                 stream: TokenStream):
        self.prompt = prompt
        self.max_new = max_new
        self.stream = stream
        self.slot: int | None = None
        self.emitted = 0
        self.steps_done = 0
        self.steps_needed = max_new - 1  # prefill delivers token 1
        self.done = False
        self.cancelled = False
        self.submitted = time.monotonic()
        self.last_token_t = self.submitted


class SlotTable:
    """Slot ownership ledger — the no-double-assignment invariant.

    Assignment and release are the ONLY mutation points, both called
    with the engine lock held; a slot handed out while still owned, or
    released by a non-owner, is an engine bug the chaos gate must see
    as a raise, never as silent cache corruption."""

    __slots__ = ("_owner",)

    def __init__(self, slots: int):
        self._owner: list[GenerateRequest | None] = [None] * slots

    def assign(self, req: GenerateRequest) -> int | None:
        """First free slot (None when full)."""
        for s, owner in enumerate(self._owner):
            if owner is None:
                if req.slot is not None:
                    raise RuntimeError(
                        f"request already owns slot {req.slot}")
                self._owner[s] = req
                req.slot = s
                return s
        return None

    def release(self, req: GenerateRequest) -> None:
        s = req.slot
        if s is None or self._owner[s] is not req:
            raise RuntimeError(
                f"slot release by non-owner (slot={s}) — "
                "double-assignment or double-release")
        self._owner[s] = None
        req.slot = None

    @property
    def free(self) -> int:
        return sum(1 for o in self._owner if o is None)

    def owner(self, s: int) -> GenerateRequest | None:
        return self._owner[s]


class GenerateBatcher:
    """Continuous-batching token engine for ONE causal model.

    ``model`` is a cache-capable module (``TransformerTagger`` with
    ``causal=True``); ``params`` its fitted variables. The engine owns
    the slot-major KV-cache as plan-managed device state, packs waiting
    prompts through the prefill ladder, and runs the single fixed-shape
    decode program with per-step join/leave. One engine thread does
    everything ordered (prefill ↔ decode interleave at step
    granularity), so slot assignment needs no cross-thread dance —
    the :class:`SlotTable` invariants still raise if the ordering is
    ever broken."""

    def __init__(self, name: str, model: Any, params: Any,
                 config: GenerateConfig | None = None,
                 stats: ServerStats | None = None,
                 decode_attention_fn: Any = None):
        import jax
        import jax.numpy as jnp

        from mmlspark_tpu.core import plan

        if not getattr(model, "causal", False):
            raise BadRequest(
                f"model {name!r}: token generation needs a causal "
                "model (causal=True)")
        self.name = name
        self.model = model
        self.config = config or GenerateConfig()
        self.stats = stats or ServerStats(self.config.stats_window,
                                          model=name)
        self._params = params
        cfg = self.config
        S = cfg.slots
        layers = model.num_layers
        heads = model.num_heads
        hd = model.embed_dim // model.num_heads
        shape = (S, layers, heads, cfg.t_max, hd)
        self._state = plan.allocate_segment_state(
            f"{name}.kv", {"k": shape, "v": shape})
        # the engine IS the cache host: obs.runtime.compiled_programs
        # walks this object's _plan_cache, so the two stateful programs
        # below are the ONLY entries and the ladder budget is auditable
        self._prefill = plan.StatefulSegment(
            "generate.prefill", build_prefill_step(model), self._state,
            cache_host=self)
        self._decode = plan.StatefulSegment(
            "generate.decode",
            build_decode_step(model, decode_attention_fn), self._state,
            cache_host=self)
        # host mirror of the device-side slot state (engine-thread only
        # once running; guarded by _cv during startup/submit)
        self._slots = SlotTable(S)
        self._positions = np.zeros(S, np.int32)
        self._inject_tok = np.zeros(S, np.int32)
        self._inject = np.zeros(S, bool)
        self._mask = np.zeros(S, bool)
        self._carry = jnp.zeros(S, jnp.int32)
        # lagged-consume state: (out device array, per-slot request refs
        # at dispatch time, active snapshot)
        self._pending: tuple | None = None
        self._cv = named_condition("serve.generate.GenerateBatcher._cv")
        self._queue: deque[GenerateRequest] = deque()
        self._closed = False
        self._abort = False
        self._hb = f"serve/{name}/generate"
        self._thread = threading.Thread(
            target=self._run, name=f"{THREAD_PREFIX}[{name}]/generate",
            daemon=True)
        self._thread.start()

    # -- admission --

    def submit(self, prompt, max_new_tokens: int | None = None
               ) -> TokenStream:
        """Admit one prompt; returns its :class:`TokenStream`."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise BadRequest(f"model {self.name!r}: empty prompt")
        max_new = (self.config.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise BadRequest(
                f"model {self.name!r}: max_new_tokens must be >= 1")
        self.config.prefill_bucket_for(len(prompt), self.name)
        if len(prompt) + max_new > self.config.t_max:
            raise BadRequest(
                f"model {self.name!r}: prompt ({len(prompt)}) + "
                f"max_new_tokens ({max_new}) exceeds the cache horizon "
                f"t_max={self.config.t_max}")
        stream = TokenStream(self.name)
        req = GenerateRequest(prompt, max_new, stream)
        with self._cv:
            if self._closed:
                raise ServerClosed(
                    f"model {self.name!r} is shutting down",
                    retry_after_s=self.config.retry_after_s)
            if len(self._queue) >= self.config.max_queue:
                self.stats.record_rejected()
                raise Overloaded(self.name, len(self._queue),
                                 self.config.max_queue,
                                 retry_after_s=self.config.retry_after_s)
            self._queue.append(req)
            self.stats.record_generate_admitted(len(prompt))
            self._cv.notify()
        return stream

    @property
    def queued(self) -> int:
        with self._cv:
            return len(self._queue)

    def compiled_programs(self) -> int | None:
        """Live XLA program count over the engine's two stateful
        entries — the ladder-budget observable (≤ prefill buckets + 1)."""
        return _obs_rt.compiled_programs(self)

    # -- the engine loop --

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as e:  # noqa: BLE001 — no stranded stream
            _log.exception("GenerateBatcher[%s] engine loop died",
                           self.name)
            self._fail_outstanding(e)
            if _obs_flight._rec is not None:
                _obs_flight._rec.disarm(self._hb)

    def _fail_outstanding(self, err: BaseException) -> None:
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
            active = [self._slots.owner(s)
                      for s in range(self.config.slots)]
        for req in leftovers + [r for r in active if r is not None]:
            if not req.done:
                req.done = True
                req.stream._fail(err)
                self.stats.record_failed()

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._abort:
                    break
            worked = False
            group = self._next_prefill_group()
            if group:
                self._do_prefill(group)
                worked = True
            if self._mask.any():
                self._churn_tick()
                self.advance_decode()
                worked = True
            elif self._pending is not None:
                # trailing lagged output after the last active slot left
                self._consume(self._pending)
                self._pending = None
                worked = True
            if worked:
                if _obs_flight._rec is not None:
                    _obs_flight._rec.beat(self._hb)
                continue
            with self._cv:
                if self._queue:
                    continue  # raced with a submit
                if self._closed or self._abort:
                    break
                if _obs_flight._rec is not None:
                    _obs_flight._rec.disarm(self._hb)
                self._cv.wait()
        self._shutdown_flush()

    def _shutdown_flush(self) -> None:
        """Terminal sweep: every admitted request must resolve."""
        if self._pending is not None:
            self._consume(self._pending)
            self._pending = None
        err = ServerClosed(f"model {self.name!r} closed")
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for req in leftovers:
            req.done = True
            req.stream._fail(err)
            self.stats.record_failed()
        for s in range(self.config.slots):
            req = self._slots.owner(s)
            if req is not None and not req.done:
                req.done = True
                self._mask[s] = False
                self._slots.release(req)
                req.stream._fail(err)
                self.stats.record_failed()
        if _obs_flight._rec is not None:
            _obs_flight._rec.disarm(self._hb)

    def _next_prefill_group(self) -> list[GenerateRequest]:
        """FIFO prompts sharing ONE prefill bucket, up to the free-slot
        and row-width caps. Same-bucket-only packing is the bit-identity
        discipline: a prompt must go through the same ℓ-program whether
        it prefills alone or packed (row independence covers the rest)."""
        cfg = self.config
        group: list[GenerateRequest] = []
        with self._cv:
            free = self._slots.free
            cap = min(free, cfg.prefill_rows)
            bucket = None
            while self._queue and len(group) < cap:
                req = self._queue[0]
                b = cfg.prefill_bucket_for(len(req.prompt), self.name)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    break
                self._queue.popleft()
                group.append(req)
        return group

    def _do_prefill(self, group: list[GenerateRequest]) -> None:
        cfg = self.config
        S = cfg.slots
        bucket = cfg.prefill_bucket_for(len(group[0].prompt), self.name)
        P = cfg.prefill_rows
        toks = np.zeros((P, bucket), np.int32)
        am = np.zeros((P, bucket), bool)
        lengths = np.ones(P, np.int32)
        slot_ids = np.full(P, S, np.int32)  # pad rows scatter off-range
        with self._cv:
            for r, req in enumerate(group):
                s = self._slots.assign(req)
                assert s is not None  # group was capped at free slots
                n = len(req.prompt)
                toks[r, :n] = req.prompt
                am[r, :n] = True
                lengths[r] = n
                slot_ids[r] = s
        labels = ({"model": self.name, "bucket": bucket,
                   "rows": len(group)} if _obs_rt._enabled else None)
        try:
            with _obs_span("serve/prefill", "serve", labels):
                first = self._prefill.dispatch(self._params, toks, am,
                                               lengths, slot_ids)
                # prefill is the TTFT seam, not the decode loop: the
                # blocking fetch here is what time-to-first-token means
                vals = np.asarray(first)
        except BaseException as e:  # noqa: BLE001 — relayed per stream
            with self._cv:
                for req in group:
                    req.done = True
                    self._slots.release(req)
            for req in group:
                req.stream._fail(e)
                self.stats.record_failed()
            return
        now = time.monotonic()
        for r, req in enumerate(group):
            tok = int(vals[r])
            self.stats.record_ttft((now - req.submitted) * 1e3)
            req.stream._push(tok)
            req.emitted = 1
            req.last_token_t = now
            self.stats.record_tokens(1)
            s = req.slot
            if req.max_new == 1 or tok == cfg.eos_token:
                self._retire(req, now)
                continue
            self._positions[s] = len(req.prompt)
            self._inject_tok[s] = tok
            self._inject[s] = True
            self._mask[s] = True

    def advance_decode(self) -> None:
        """One token step: dispatch the fixed-shape decode program over
        the current slot state, then consume the PREVIOUS step's output
        (the one-step-lagged host fetch — step *t+1* computes while
        step *t*'s tokens stream out)."""
        import jax.numpy as jnp

        S = self.config.slots
        act = self._mask.copy()
        refs = [self._slots.owner(s) for s in range(S)]
        out = self._decode.dispatch(
            self._params, self._carry, jnp.asarray(self._inject_tok),
            jnp.asarray(self._inject), jnp.asarray(self._positions),
            jnp.asarray(act))
        self._carry = out
        self._inject[:] = False
        n_active = int(act.sum())
        self.stats.record_decode_step(n_active, S)
        for s in np.nonzero(act)[0]:
            req = refs[s]
            self._positions[s] += 1
            req.steps_done += 1
            if req.steps_done >= req.steps_needed:
                # generation budget reached: this dispatch was the
                # request's last — nothing further joins the batch, and
                # the lagged consume below (next call) retires it
                self._mask[s] = False
        prev, self._pending = self._pending, (out, refs, act)
        if prev is not None:
            self._consume(prev)

    def _consume(self, pending: tuple) -> None:
        out, refs, act = pending
        vals = np.asarray(out)  # lint-jax: allow(JX109) — one-step
        # lagged: this output's step already overlapped the dispatch
        # above; the fetch drains a finished computation
        now = time.monotonic()
        cfg = self.config
        for s in np.nonzero(act)[0]:
            req = refs[s]
            if req is None or req.done:
                continue
            if req.cancelled:
                self._retire(req, now, cancelled=True)
                continue
            tok = int(vals[s])
            req.stream._push(tok)
            self.stats.record_itl((now - req.last_token_t) * 1e3)
            self.stats.record_tokens(1)
            req.last_token_t = now
            req.emitted += 1
            if req.emitted >= req.max_new or tok == cfg.eos_token:
                self._retire(req, now)

    def _retire(self, req: GenerateRequest, now: float,
                cancelled: bool = False) -> None:
        req.done = True
        with self._cv:
            if req.slot is not None:
                self._mask[req.slot] = False
                self._slots.release(req)
        req.stream._finish(cancelled=cancelled)
        if cancelled:
            self.stats.record_generate_cancelled()
        self.stats.record_done((now - req.submitted) * 1e3, 0.0)

    def _churn_tick(self) -> None:
        """The ``generate_cancel`` injection point: a seeded churn plan
        models clients abandoning streams mid-decode. The oldest active
        request is cancelled — its slot frees at the next lagged
        consume, exactly the join/leave path real traffic exercises."""
        try:
            _faults.hit("generate_cancel", model=self.name)
        except InjectedFault:
            oldest = None
            for s in np.nonzero(self._mask)[0]:
                req = self._slots.owner(int(s))
                if req is not None and not req.cancelled and (
                        oldest is None
                        or req.submitted < oldest.submitted):
                    oldest = req
            if oldest is not None:
                oldest.cancelled = True

    # -- the one-shot reference (the bit-identity anchor) --

    def oneshot(self, prompt, max_new_tokens: int | None = None
                ) -> list[int]:
        """Whole-sequence decode of one prompt through the SAME two
        compiled programs on FRESH buffers (no engine state touched, no
        stats): prefill alone, then decode alone to the budget. The
        tier-1 gate pins every continuously-batched stream bit-identical
        to this."""
        import jax.numpy as jnp

        cfg = self.config
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        max_new = (cfg.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        S = cfg.slots
        bucket = cfg.prefill_bucket_for(len(prompt), self.name)
        shape = self._state.buffers["k"].shape
        bufs = {"k": jnp.zeros(shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.float32)}
        P = cfg.prefill_rows
        toks = np.zeros((P, bucket), np.int32)
        am = np.zeros((P, bucket), bool)
        lengths = np.ones(P, np.int32)
        slot_ids = np.full(P, S, np.int32)
        n = len(prompt)
        toks[0, :n] = prompt
        am[0, :n] = True
        lengths[0] = n
        slot_ids[0] = 0
        bufs, first = self._prefill.jitted(bufs, self._params, toks, am,
                                           lengths, slot_ids)
        tokens = [int(np.asarray(first)[0])]
        if max_new == 1 or tokens[0] == cfg.eos_token:
            return tokens
        carry = jnp.zeros(S, jnp.int32)
        inject_tok = np.zeros(S, np.int32)
        inject = np.zeros(S, bool)
        positions = np.zeros(S, np.int32)
        active = np.zeros(S, bool)
        inject_tok[0] = tokens[0]
        inject[0] = True
        positions[0] = n
        active[0] = True
        for _ in range(max_new - 1):
            bufs, carry = self._decode.jitted(
                bufs, self._params, carry, jnp.asarray(inject_tok),
                jnp.asarray(inject), jnp.asarray(positions),
                jnp.asarray(active))
            inject[0] = False
            positions[0] += 1
            # the reference path is DELIBERATELY synchronous: one
            # request, one token per round-trip — it exists to anchor
            # bit-identity, not to be fast
            tok = int(np.asarray(carry)[0])  # lint-jax: allow(JX109)
            tokens.append(tok)
            if tok == cfg.eos_token:
                break
        return tokens

    # -- lifecycle --

    def close(self, drain: bool = True) -> None:
        """Stop admission; ``drain=True`` finishes every admitted
        stream first, ``drain=False`` fails outstanding work typed.
        Idempotent; joins the engine thread (no leaked thread)."""
        with self._cv:
            self._closed = True
            if not drain:
                self._abort = True
            self._cv.notify_all()
        self._thread.join(timeout=self.config.drain_timeout_s)
        if self._thread.is_alive():  # pragma: no cover - defensive
            _log.warning("GenerateBatcher[%s] did not stop within %.1fs",
                         self.name, self.config.drain_timeout_s)
        elif _obs_flight._rec is not None:
            _obs_flight._rec.forget(self._hb)

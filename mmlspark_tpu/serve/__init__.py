"""Online serving subsystem — dynamic-batching model server.

The reference's endgame was turning fitted pipelines into request/response
services (`spark-serving`'s HTTP sources/sinks); everything before this
package was offline batch. The TPU-native constraint an online server must
design around is that **every distinct input shape is a recompile**, so the
dynamic batcher quantizes request coalescing to a fixed bucket ladder and
compiles exactly one program per (model, bucket) — see docs/serving.md.

* :class:`ModelServer` — loads saved ``PipelineModel``s / ``ModelBundle``s,
  validates each with the pre-flight analyzer at load time, and executes
  requests through the fused device plan (``core.plan.transform_async``).
* :class:`DynamicBatcher` — per-model bounded queue + coalescing dispatch
  loop with admission control, deadlines, and graceful drain.
* :class:`Client` — in-process client (deterministic tests, the bench);
  ``retry=`` retries transient faults through ``core/retry``.
* :mod:`mmlspark_tpu.serve.lifecycle` — zero-downtime model lifecycle:
  hot-swap via ``add_model`` re-registration, shadow/canary routing
  with the pure SLO-driven :class:`PromotionPolicy` (auto-rollback on
  canary fast-burn or parity drift), every decision journaled; the
  versioned artifact source is :mod:`mmlspark_tpu.models.repo`.
* :mod:`mmlspark_tpu.serve.faults` — deterministic seeded fault
  injection at the serve seams (the reproducible-chaos harness behind
  the lane self-healing and lifecycle gates).
* :mod:`mmlspark_tpu.serve.mesh` — sharded serving: DP-replica fan-out,
  tp/pp model-parallel sub-meshes, and multi-host lockstep
  (``ServeMeshSpec``, ``--mesh dp=N[,tp=M]`` on the CLI).
* :mod:`mmlspark_tpu.serve.http` — stdlib-only HTTP front end (JSON +
  Arrow bodies); ``tools/serve.py`` is the CLI.
"""

from mmlspark_tpu.serve.config import (  # noqa: F401
    GenerateConfig, ServeConfig,
)
from mmlspark_tpu.serve.errors import (  # noqa: F401
    BadRequest, DeadlineExceeded, LaneFailed, ModelLoadError,
    ModelNotFound, Overloaded, ServeError, ServerClosed,
)
from mmlspark_tpu.serve.faults import (  # noqa: F401
    FaultPlan, FaultSpec, InjectedFault,
)
from mmlspark_tpu.serve.ladder import (  # noqa: F401
    LadderAdvisor, expected_padded_rows, fit_ladder, validate_ladder,
)
from mmlspark_tpu.serve.lifecycle import (  # noqa: F401
    CanarySignal, DecisionJournal, Hold, Promote, PromotionLedger,
    PromotionPolicy, Rollback,
)
from mmlspark_tpu.serve.batcher import (  # noqa: F401
    DynamicBatcher, ServeRequest, THREAD_PREFIX,
)
from mmlspark_tpu.serve.generate import (  # noqa: F401
    GenerateBatcher, TokenStream,
)
from mmlspark_tpu.serve.mesh import (  # noqa: F401
    LockstepCoordinator, Replica, ReplicaSet, ServeMeshSpec,
    build_replicas,
)
from mmlspark_tpu.serve.server import Client, ModelServer  # noqa: F401
from mmlspark_tpu.serve.stats import ServerStats  # noqa: F401

__all__ = [
    "BadRequest",
    "CanarySignal",
    "Client",
    "DeadlineExceeded",
    "DecisionJournal",
    "DynamicBatcher",
    "FaultPlan",
    "FaultSpec",
    "GenerateBatcher",
    "GenerateConfig",
    "Hold",
    "InjectedFault",
    "LadderAdvisor",
    "LaneFailed",
    "ModelLoadError",
    "LockstepCoordinator",
    "ModelNotFound",
    "ModelServer",
    "Promote",
    "PromotionLedger",
    "PromotionPolicy",
    "Replica",
    "ReplicaSet",
    "Rollback",
    "ServeMeshSpec",
    "build_replicas",
    "Overloaded",
    "ServeConfig",
    "ServeError",
    "ServeRequest",
    "ServerClosed",
    "ServerStats",
    "THREAD_PREFIX",
    "TokenStream",
    "expected_padded_rows",
    "fit_ladder",
    "validate_ladder",
]

"""Online serving subsystem — dynamic-batching model server.

The reference's endgame was turning fitted pipelines into request/response
services (`spark-serving`'s HTTP sources/sinks); everything before this
package was offline batch. The TPU-native constraint an online server must
design around is that **every distinct input shape is a recompile**, so the
dynamic batcher quantizes request coalescing to a fixed bucket ladder and
compiles exactly one program per (model, bucket) — see docs/serving.md.

* :class:`ModelServer` — loads saved ``PipelineModel``s / ``ModelBundle``s,
  validates each with the pre-flight analyzer at load time, and executes
  requests through the fused device plan (``core.plan.transform_async``).
* :class:`DynamicBatcher` — per-model bounded queue + coalescing dispatch
  loop with admission control, deadlines, and graceful drain.
* :class:`Client` — in-process client (deterministic tests, the bench).
* :mod:`mmlspark_tpu.serve.mesh` — sharded serving: DP-replica fan-out,
  tp/pp model-parallel sub-meshes, and multi-host lockstep
  (``ServeMeshSpec``, ``--mesh dp=N[,tp=M]`` on the CLI).
* :mod:`mmlspark_tpu.serve.http` — stdlib-only HTTP front end (JSON +
  Arrow bodies); ``tools/serve.py`` is the CLI.
"""

from mmlspark_tpu.serve.config import ServeConfig  # noqa: F401
from mmlspark_tpu.serve.errors import (  # noqa: F401
    BadRequest, DeadlineExceeded, ModelLoadError, ModelNotFound,
    Overloaded, ServeError, ServerClosed,
)
from mmlspark_tpu.serve.batcher import (  # noqa: F401
    DynamicBatcher, ServeRequest, THREAD_PREFIX,
)
from mmlspark_tpu.serve.mesh import (  # noqa: F401
    LockstepCoordinator, Replica, ReplicaSet, ServeMeshSpec,
    build_replicas,
)
from mmlspark_tpu.serve.server import Client, ModelServer  # noqa: F401
from mmlspark_tpu.serve.stats import ServerStats  # noqa: F401

__all__ = [
    "BadRequest",
    "Client",
    "DeadlineExceeded",
    "DynamicBatcher",
    "ModelLoadError",
    "LockstepCoordinator",
    "ModelNotFound",
    "ModelServer",
    "Replica",
    "ReplicaSet",
    "ServeMeshSpec",
    "build_replicas",
    "Overloaded",
    "ServeConfig",
    "ServeError",
    "ServeRequest",
    "ServerClosed",
    "ServerStats",
    "THREAD_PREFIX",
]

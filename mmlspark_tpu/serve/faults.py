"""Deterministic seeded fault injection for the serve plane.

Chaos testing a self-healing server is only worth anything when the
chaos replays: a flaky fault schedule turns every recovery gate into a
flaky gate. This module is the ONE fault source the serving seams
consult — a :class:`FaultPlan` of named injection points with
count-based (``after``/``times``) or seeded-probability (``prob`` drawn
from ``random.Random(seed)``) triggers, so the same plan + seed fires
the same faults at the same seam passes, every run.

Injection points live at the seams the real failure modes hit (the
same seams the flight recorder already heartbeats):

=====================  ====================================================
point                  seam
=====================  ====================================================
``lane_death``         ``serve/batcher._Lane._run`` — a non-request
                       exception kills the lane worker (the motivating
                       self-healing bug: stranded queue, silent capacity
                       loss)
``dispatch_raise``     ``_Lane._dispatch`` just before the device
                       dispatch — relayed per request as a failed batch
``dispatch_slow``      same seam, a ``delay_s`` sleep — a wedged/slow
                       dispatch without an exception
``repo_torn_publish``  ``models/repo.ModelRepo.publish`` after the
                       version files are written, before the atomic
                       rename — a crash mid-publish
``load_failure``       ``models/repo.ModelRepo.load`` before
                       deserialization — a model that cannot come up
``compile_cache_torn_put``  ``core/compile_cache.CompileCache.put``
                       after the entry files are staged, before the
                       atomic rename — a crash mid-publish of an AOT
                       program (the staging dir is inert; loads miss
                       and fall back to in-memory compiles)
``generate_cancel``    ``serve/generate.GenerateBatcher`` decode loop,
                       once per token step — a client abandoning its
                       stream mid-decode: the engine cancels the oldest
                       active request, releases its slot, and the
                       join/leave churn gate asserts no slot
                       double-assignment under the schedule
``backend_down``       ``serve/fleet/router.FleetRouter`` before it
                       connects to the picked backend (``lane`` scopes
                       the backend id) — a backend that died between
                       selection and connect; the router must re-route,
                       never drop
``backend_slow``       same router seam, a ``delay_s`` sleep — a
                       backend answering slowly without failing, the
                       case deadline-aware selection must ride out
``backend_torn_response``  router response-read seam — the TCP stream
                       tears mid-body (backend killed -9 with bytes in
                       flight); predicts resend elsewhere, generate
                       streams replay on a new backend minus the
                       already-delivered token prefix
=====================  ====================================================

The seams pay ONE module-attribute check when no plan is installed
(the ``obs/flight.py`` discipline), so production dispatch loops are
untouched. Install with :func:`install`/:func:`clear` or the
:func:`inject` context manager; every firing is recorded in
``plan.fired`` for assertions and post-mortems.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time
from typing import Any, Iterator


class InjectedFault(RuntimeError):
    """A fault fired by the installed :class:`FaultPlan`.

    Deliberately NOT a ``ServeError``: an injected lane death must look
    exactly like the unexpected non-request exception it models, so the
    recovery machinery can never special-case "it was only a test".
    """

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"injected fault at {point!r}")
        self.point = point


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault rule. ``point`` names the seam; ``model``/``lane``
    (None = any) scope it. The trigger is deterministic: the spec fires
    on seam passes ``after <= k < after + times`` (k counts MATCHING
    passes, from 0), optionally gated by a seeded coin flip ``prob``
    (each matching pass draws once from the spec's own
    ``random.Random``, so the draw sequence is a pure function of the
    plan seed and the pass order). ``delay_s`` makes the fault a sleep
    (slow seam) instead of a raise."""

    point: str
    model: str | None = None
    lane: int | None = None
    after: int = 0
    times: int = 1
    prob: float | None = None
    delay_s: float | None = None
    message: str = ""

    def __post_init__(self):
        if self.after < 0 or self.times < 1:
            raise ValueError(
                f"need after >= 0 and times >= 1: {self.after}/{self.times}")
        if self.prob is not None and not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1]: {self.prob}")

    def matches(self, point: str, model: str | None,
                lane: int | None) -> bool:
        return (point == self.point
                and (self.model is None or model == self.model)
                and (self.lane is None or lane == self.lane))


class FaultPlan:
    """A set of :class:`FaultSpec` rules plus the seed that makes their
    probabilistic triggers replayable. Thread-safe: serve lanes hit the
    seams concurrently, and the per-spec pass counters (what ``after``
    indexes) must not lose updates."""

    def __init__(self, specs: Iterator[FaultSpec] | list,
                 seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)
        self._rngs = [random.Random(self.seed + i)
                      for i in range(len(self.specs))]
        self._passes = [0] * len(self.specs)
        self._lock = threading.Lock()
        #: every firing, in order: (point, model, lane, kind) — the
        #: reproducibility observable chaos tests assert on
        self.fired: list[tuple] = []

    def fire(self, point: str, model: str | None,
             lane: int | None) -> None:
        """Evaluate every matching spec for one seam pass; raises
        :class:`InjectedFault` or sleeps when a spec triggers."""
        delay = None
        raise_spec = None
        with self._lock:
            for i, spec in enumerate(self.specs):
                if not spec.matches(point, model, lane):
                    continue
                k = self._passes[i]
                self._passes[i] = k + 1
                if not spec.after <= k < spec.after + spec.times:
                    continue
                if spec.prob is not None \
                        and self._rngs[i].random() >= spec.prob:
                    continue
                if spec.delay_s is not None:
                    delay = max(delay or 0.0, spec.delay_s)
                    self.fired.append((point, model, lane, "delay"))
                else:
                    raise_spec = spec
                    self.fired.append((point, model, lane, "raise"))
        if delay is not None:
            time.sleep(delay)
        if raise_spec is not None:
            raise InjectedFault(point, raise_spec.message)

    def counts(self) -> dict:
        """Per-point firing counts (JSON-safe; for gate reports)."""
        out: dict[str, int] = {}
        with self._lock:
            for point, _m, _l, _k in self.fired:
                out[point] = out.get(point, 0) + 1
        return out


# ---- module surface (the seams check ONE attribute: `_plan`) ----

_plan: FaultPlan | None = None


def install(plan: FaultPlan) -> None:
    """Install ``plan`` as the process-wide fault source (replacing any
    prior plan — plans don't stack; a chaos run is one schedule)."""
    global _plan
    _plan = plan


def clear() -> None:
    global _plan
    _plan = None


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """``with faults.inject(plan):`` — install for the block, always
    cleared on exit (a leaked plan would fault unrelated tests)."""
    install(plan)
    try:
        yield plan
    finally:
        clear()


def hit(point: str, model: str | None = None,
        lane: int | None = None) -> None:
    """The seam call: free (one attribute check) when no plan is
    installed; may raise :class:`InjectedFault` or sleep otherwise."""
    if _plan is None:
        return
    _plan.fire(point, model, lane)


def active() -> bool:
    return _plan is not None

"""ServerStats — honest per-model serving metrics, obs-backed.

Same accounting discipline as ``Trainer.input_stats``: every number is
counted or timed at the seam where it happens (admission, pack, dispatch,
drain, resolve), nothing is inferred, and the snapshot says exactly what
was measured. Metrics glossary in docs/serving.md.

Since the obs subsystem (docs/observability.md) the storage is the shared
telemetry primitives — :class:`~mmlspark_tpu.obs.metrics.Counter` and
windowed :class:`~mmlspark_tpu.obs.metrics.Histogram` in a per-model
:class:`~mmlspark_tpu.obs.metrics.MetricsRegistry`, labeled
``model=<name>``/``bucket=<n>`` — instead of a private deque-and-int
class. ``snapshot()`` keys and values are unchanged (the percentile
interpolation and rounding are the histogram's own), and the per-instance
registry keeps one server's numbers isolated from another's (and from the
process-wide registry plan/train record into; the ``/metrics`` endpoint
merges both views).
"""

from __future__ import annotations

import threading

from mmlspark_tpu.obs.metrics import MetricsRegistry


class ServerStats:
    """Thread-safe metrics surface of one served model."""

    def __init__(self, window: int = 4096, model: str = "",
                 extra_labels: dict | None = None):
        self.model = model
        # per-instance registry: a reloaded model (or a second server in
        # the same process/test) starts from zero, never from a prior
        # instance's interned series
        self.registry = MetricsRegistry()
        self._window = int(window)
        lbl = {"model": model} if model else {}
        if extra_labels:
            # per-VERSION registries (the model lifecycle): a stable
            # v1 and its v2 canary carry distinguishable series in
            # /metrics even while both serve under one model name
            lbl = {**lbl, **{k: str(v) for k, v in extra_labels.items()}}
        self._lbl = lbl
        reg = self.registry
        # request-side counters (admission → terminal state)
        self._admitted = reg.counter("serve.admitted", **lbl)
        self._completed = reg.counter("serve.completed", **lbl)
        self._rejected = reg.counter("serve.rejected_overload", **lbl)
        self._expired = reg.counter("serve.expired_deadline", **lbl)
        self._timed_out = reg.counter("serve.timed_out", **lbl)
        self._failed = reg.counter("serve.failed", **lbl)
        # batch-side counters
        self._batches = reg.counter("serve.batches", **lbl)
        self._rows_dispatched = reg.counter("serve.rows_dispatched", **lbl)
        self._rows_padded = reg.counter("serve.rows_padded", **lbl)
        # lane self-healing counters (the supervisor's seam — a lane
        # death that silently shrank capacity would be invisible in
        # every latency percentile until overload)
        self._lane_deaths = reg.counter("serve.lane_deaths", **lbl)
        self._lane_restarts = reg.counter("serve.lane_restarts", **lbl)
        self._requeued = reg.counter("serve.requeued_batches", **lbl)
        # bounded reservoirs (latest `window` observations)
        self._e2e_ms = reg.histogram("serve.e2e_ms", window=window, **lbl)
        self._queue_ms = reg.histogram("serve.queue_wait_ms",
                                       window=window, **lbl)
        self._device_ms = reg.histogram("serve.device_ms",
                                        window=window, **lbl)
        self._occupancy = reg.histogram("serve.batch_occupancy",
                                        window=window, **lbl)
        # request SIZES as admitted (pre-padding, per request — not the
        # per-batch occupancy): the adaptive-ladder fit input
        # (serve/ladder.py) needs the raw size distribution, which
        # occupancy hides behind packing
        self._request_rows = reg.histogram("serve.request_rows",
                                           window=window, **lbl)
        # wall seconds the load spent warming the bucket ladder (gauge:
        # one value per load/swap) — with the persistent compile cache
        # this is the warm-start observable bench A/Bs
        self._warm_wall = reg.gauge("serve.warm_wall_s", **lbl)
        # token-serving seams (continuous batching, serve/generate.py):
        # TTFT is prefill-completion minus submit (per request), ITL is
        # the gap between consecutive streamed tokens (per token) — the
        # two per-token SLOs the /slo surface publishes. Slot occupancy
        # is observed once per decode step (active slots / table size):
        # the padding-waste observable of the fixed-shape decode program
        self._ttft_ms = reg.histogram("serve.ttft_ms", window=window,
                                      **lbl)
        self._itl_ms = reg.histogram("serve.itl_ms", window=window, **lbl)
        self._tokens_out = reg.counter("serve.tokens_out", **lbl)
        self._gen_requests = reg.counter("serve.generate_requests", **lbl)
        self._gen_cancelled = reg.counter("serve.generate_cancelled",
                                          **lbl)
        self._decode_steps = reg.counter("serve.decode_steps", **lbl)
        self._slot_occupancy = reg.histogram("serve.slot_occupancy",
                                             window=window, **lbl)
        # distinct batch shapes OBSERVED entering the device (reported by
        # the dispatch handle, one per uploaded chunk — not the intended
        # bucket label): for a fixed program each new shape is one XLA
        # compile, so this set is the recompile observable independent of
        # jit internals
        self._shape_lock = threading.Lock()
        self.dispatch_shapes: set = set()

    # back-compat int views of the counters (the pre-obs attributes)

    @property
    def admitted(self) -> int:
        return int(self._admitted.value)

    @property
    def completed(self) -> int:
        return int(self._completed.value)

    @property
    def rejected_overload(self) -> int:
        return int(self._rejected.value)

    @property
    def expired_deadline(self) -> int:
        return int(self._expired.value)

    @property
    def timed_out(self) -> int:
        return int(self._timed_out.value)

    @property
    def failed(self) -> int:
        return int(self._failed.value)

    @property
    def batches(self) -> int:
        return int(self._batches.value)

    @property
    def rows_dispatched(self) -> int:
        return int(self._rows_dispatched.value)

    @property
    def rows_padded(self) -> int:
        return int(self._rows_padded.value)

    @property
    def lane_deaths(self) -> int:
        return int(self._lane_deaths.value)

    @property
    def lane_restarts(self) -> int:
        return int(self._lane_restarts.value)

    @property
    def requeued_batches(self) -> int:
        return int(self._requeued.value)

    # registry-read accessors for the SLO engine (obs/slo.py): burn
    # rates and derived gauges are computed ONLY from these reads —
    # never from new side-channel counters

    @property
    def labels(self) -> dict:
        """The model label set every series of this registry carries."""
        return dict(self._lbl)

    def e2e_percentiles(self) -> dict | None:
        """Current e2e latency percentiles (None pre-traffic) — the
        latency-objective read of the SLO tracker."""
        return self._e2e_ms.percentiles(ndigits=None)

    def occupancy_mean(self) -> float | None:
        """Mean batch occupancy over the window — the adaptive-ladder
        signal."""
        return self._occupancy.mean()

    def replica_batch_counts(self) -> dict[int, int]:
        """Per-replica dispatched-batch counts (empty unless sharded) —
        the DP load-balance/skew read."""
        return {int(dict(c.labels)["replica"]): int(c.value)
                for c in self.registry.series("serve.replica_batches")}

    def request_sizes(self) -> list[int]:
        """Admitted request row counts over the window — the
        adaptive-ladder fit input (``LadderAdvisor.propose``)."""
        return [int(v) for v in self._request_rows.values()]

    def ttft_percentiles(self) -> dict | None:
        """Time-to-first-token percentiles (None pre-traffic) — the
        prefill-latency SLO read."""
        return self._ttft_ms.percentiles(ndigits=None)

    def itl_percentiles(self) -> dict | None:
        """Inter-token-latency percentiles (None pre-traffic) — the
        streaming-cadence SLO read."""
        return self._itl_ms.percentiles(ndigits=None)

    def slot_occupancy_mean(self) -> float | None:
        """Mean active-slot fraction over the decode-step window."""
        return self._slot_occupancy.mean()

    @property
    def tokens_out(self) -> int:
        return int(self._tokens_out.value)

    @property
    def generate_requests(self) -> int:
        return int(self._gen_requests.value)

    @property
    def generate_cancelled(self) -> int:
        return int(self._gen_cancelled.value)

    @property
    def decode_steps(self) -> int:
        return int(self._decode_steps.value)

    def record_warm_wall(self, seconds: float) -> None:
        self._warm_wall.set(seconds)

    @property
    def warm_wall_s(self) -> float | None:
        return self._warm_wall.value

    # -- request side --

    def record_admitted(self, rows: int = 1) -> None:
        self._admitted.add()
        self._request_rows.observe(rows)

    def record_rejected(self) -> None:
        self._rejected.add()

    def record_expired(self) -> None:
        self._expired.add()

    def record_timeout(self) -> None:
        self._timed_out.add()

    def record_failed(self) -> None:
        self._failed.add()

    # -- lane supervision side --

    def record_lane_death(self) -> None:
        self._lane_deaths.add()

    def record_lane_restart(self) -> None:
        self._lane_restarts.add()

    def record_requeued(self, batches: int = 1) -> None:
        self._requeued.add(batches)

    def record_done(self, e2e_ms: float, queue_ms: float) -> None:
        self._completed.add()
        self._e2e_ms.observe(e2e_ms)
        self._queue_ms.observe(queue_ms)

    # -- token-serving side (serve/generate.py) --

    def record_generate_admitted(self, prompt_tokens: int) -> None:
        self._gen_requests.add()
        self._request_rows.observe(prompt_tokens)

    def record_generate_cancelled(self) -> None:
        self._gen_cancelled.add()

    def record_ttft(self, ms: float) -> None:
        self._ttft_ms.observe(ms)

    def record_itl(self, ms: float) -> None:
        self._itl_ms.observe(ms)

    def record_tokens(self, n: int = 1) -> None:
        self._tokens_out.add(n)

    def record_decode_step(self, active: int, slots: int) -> None:
        self._decode_steps.add()
        self._slot_occupancy.observe(active / slots if slots else 0.0)

    # -- batch side --

    def record_batch(self, bucket: int, occupancy: int, device_ms: float,
                     shapes: tuple = (),
                     replica: int | None = None) -> None:
        self._batches.add()
        self._rows_dispatched.add(occupancy)
        self._rows_padded.add(max(bucket - occupancy, 0))
        self._device_ms.observe(device_ms)
        self._occupancy.observe(occupancy)
        self.registry.counter("serve.bucket_batches",
                              bucket=int(bucket), **self._lbl).add()
        if replica is not None:
            # replica-labeled series (sharded serving): per-replica
            # dispatch counts, occupancy, and device-time percentiles
            # stay distinguishable in /metrics and the snapshot — the
            # load-balance observable of the DP fan-out
            r = int(replica)
            self.registry.counter("serve.replica_batches",
                                  replica=r, **self._lbl).add()
            self.registry.counter("serve.replica_rows",
                                  replica=r, **self._lbl).add(occupancy)
            self.registry.histogram("serve.replica_device_ms",
                                    window=self._window, replica=r,
                                    **self._lbl).observe(device_ms)
            self.registry.histogram("serve.replica_occupancy",
                                    window=self._window, replica=r,
                                    **self._lbl).observe(occupancy)
        if shapes:
            with self._shape_lock:
                for s in shapes:
                    self.dispatch_shapes.add(tuple(s))

    # -- presentation --

    def snapshot(self) -> dict:
        """One JSON-safe dict of everything measured so far. Safe before
        any traffic: empty histograms report ``None`` (never a
        zero-division or an empty-array percentile)."""
        buckets = {
            int(dict(c.labels)["bucket"]): int(c.value)
            for c in self.registry.series("serve.bucket_batches")
        }
        replicas: dict[int, dict] = {}
        for c in self.registry.series("serve.replica_batches"):
            replicas.setdefault(int(dict(c.labels)["replica"]),
                                {})["batches"] = int(c.value)
        for c in self.registry.series("serve.replica_rows"):
            replicas.setdefault(int(dict(c.labels)["replica"]),
                                {})["rows_dispatched"] = int(c.value)
        for h in self.registry.series("serve.replica_device_ms"):
            replicas.setdefault(int(dict(h.labels)["replica"]),
                                {})["device_ms"] = h.percentiles()
        for h in self.registry.series("serve.replica_occupancy"):
            replicas.setdefault(int(dict(h.labels)["replica"]),
                                {})["occupancy_mean"] = h.mean()
        with self._shape_lock:
            n_shapes = len(self.dispatch_shapes)
        return {
            "admitted": self.admitted,
            "completed": self.completed,
            "rejected_overload": self.rejected_overload,
            "expired_deadline": self.expired_deadline,
            "timed_out": self.timed_out,
            "failed": self.failed,
            "batches": self.batches,
            "rows_dispatched": self.rows_dispatched,
            "rows_padded": self.rows_padded,
            "lane_deaths": self.lane_deaths,
            "lane_restarts": self.lane_restarts,
            "requeued_batches": self.requeued_batches,
            "batch_occupancy_mean": self._occupancy.mean(),
            "request_rows_mean": self._request_rows.mean(),
            "warm_wall_s": self._warm_wall.value,
            "occupancy_by_bucket": dict(sorted(buckets.items())),
            "e2e_ms": self._e2e_ms.percentiles(),
            "queue_wait_ms": self._queue_ms.percentiles(),
            "device_ms": self._device_ms.percentiles(),
            # token-serving view (zero/None for pure batch models)
            "tokens_out": self.tokens_out,
            "generate_requests": self.generate_requests,
            "generate_cancelled": self.generate_cancelled,
            "decode_steps": self.decode_steps,
            "ttft_ms": self._ttft_ms.percentiles(),
            "itl_ms": self._itl_ms.percentiles(),
            "slot_occupancy_mean": self._slot_occupancy.mean(),
            "distinct_batch_shapes": n_shapes,
            # per-replica breakdown (empty unless the model serves
            # sharded): dispatch counts / rows / device-time percentiles
            # keyed by replica index — the DP fan-out's load balance
            "replicas": {k: replicas[k] for k in sorted(replicas)},
        }

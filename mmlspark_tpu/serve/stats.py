"""ServerStats — honest per-model serving metrics.

Same accounting discipline as ``Trainer.input_stats``: every number is
counted or timed at the seam where it happens (admission, pack, dispatch,
drain, resolve), nothing is inferred, and the snapshot says exactly what
was measured. Metrics glossary in docs/serving.md.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np


def _percentiles(values) -> dict | None:
    if not values:
        return None
    arr = np.asarray(values, dtype=np.float64)
    p50, p95, p99 = np.percentile(arr, [50, 95, 99])
    return {"p50": round(float(p50), 3), "p95": round(float(p95), 3),
            "p99": round(float(p99), 3), "n": int(arr.size)}


class ServerStats:
    """Thread-safe metrics surface of one served model."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        # request-side counters (admission → terminal state)
        self.admitted = 0
        self.completed = 0
        self.rejected_overload = 0   # Overloaded at submit
        self.expired_deadline = 0    # cancelled in queue, before dispatch
        self.timed_out = 0           # client gave up post-admission
        self.failed = 0              # dispatch/model error relayed
        # batch-side counters
        self.batches = 0
        self.rows_dispatched = 0
        self.rows_padded = 0         # padding rows (bucket - occupancy)
        # bounded reservoirs (latest `window` observations)
        self._e2e_ms: deque = deque(maxlen=window)
        self._queue_ms: deque = deque(maxlen=window)
        self._device_ms: deque = deque(maxlen=window)
        self._occupancy: deque = deque(maxlen=window)
        self._bucket_batches: dict[int, int] = {}
        # distinct batch shapes OBSERVED entering the device (reported by
        # the dispatch handle, one per uploaded chunk — not the intended
        # bucket label): for a fixed program each new shape is one XLA
        # compile, so this set is the recompile observable independent of
        # jit internals
        self.dispatch_shapes: set = set()

    # -- request side --

    def record_admitted(self) -> None:
        with self._lock:
            self.admitted += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected_overload += 1

    def record_expired(self) -> None:
        with self._lock:
            self.expired_deadline += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timed_out += 1

    def record_failed(self) -> None:
        with self._lock:
            self.failed += 1

    def record_done(self, e2e_ms: float, queue_ms: float) -> None:
        with self._lock:
            self.completed += 1
            self._e2e_ms.append(e2e_ms)
            self._queue_ms.append(queue_ms)

    # -- batch side --

    def record_batch(self, bucket: int, occupancy: int, device_ms: float,
                     shapes: tuple = ()) -> None:
        with self._lock:
            self.batches += 1
            self.rows_dispatched += occupancy
            self.rows_padded += max(bucket - occupancy, 0)
            self._device_ms.append(device_ms)
            self._occupancy.append(occupancy)
            self._bucket_batches[bucket] = (
                self._bucket_batches.get(bucket, 0) + 1)
            for s in shapes:
                self.dispatch_shapes.add(tuple(s))

    # -- presentation --

    def snapshot(self) -> dict:
        """One JSON-safe dict of everything measured so far."""
        with self._lock:
            occ = list(self._occupancy)
            mean_occ = (round(float(np.mean(occ)), 3) if occ else None)
            return {
                "admitted": self.admitted,
                "completed": self.completed,
                "rejected_overload": self.rejected_overload,
                "expired_deadline": self.expired_deadline,
                "timed_out": self.timed_out,
                "failed": self.failed,
                "batches": self.batches,
                "rows_dispatched": self.rows_dispatched,
                "rows_padded": self.rows_padded,
                "batch_occupancy_mean": mean_occ,
                "occupancy_by_bucket": dict(
                    sorted(self._bucket_batches.items())),
                "e2e_ms": _percentiles(self._e2e_ms),
                "queue_wait_ms": _percentiles(self._queue_ms),
                "device_ms": _percentiles(self._device_ms),
                "distinct_batch_shapes": len(self.dispatch_shapes),
            }

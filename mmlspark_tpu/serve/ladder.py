"""Traffic-learned bucket ladders — fit the ladder to observed load.

The static 1/8/32/128 default ladder ignores the request-size
distribution the server actually sees: traffic concentrated at 24 rows
pads every request to 32 (25% wasted device work), while the Round 8
occupancy data says the ladder is the main p99-vs-throughput lever.
This module learns a better ladder from the observed request-size
histogram under an explicit **program budget** (each rung is one
compiled XLA program per model per precision — the
``programs <= len(buckets)`` discipline), and the serve plane rolls a
change out per model through the existing hot-swap path. With the
persistent compile cache on, the flip's new programs load from disk —
a ladder change costs a deserialize, not an XLA compile.

Three layers:

* :func:`validate_ladder` — the ONE ladder validation (``ServeConfig``
  and fitted ladders both pass through it): positive ints, strictly
  ascending. A misordered ladder used to be silently re-sorted; it is
  now a typed refusal at load.
* :func:`fit_ladder` — exact DP over the distinct observed sizes
  minimizing expected padded rows dispatched, with the top rung PINNED
  to ``max_bucket`` so the admission contract (``bucket_for`` accepts
  any request ≤ the max bucket) never shrinks mid-flight — a rollout
  drops zero requests by construction. Deterministic: ties prefer
  fewer rungs, then the earlier split.
* :class:`LadderAdvisor` — the re-fit policy: only on SLO-clean
  windows, only with enough traffic, only when the fitted ladder beats
  the current one by a real margin. ``ModelServer.ladder_tick`` feeds
  it the ``ServerStats`` request-size histogram and applies accepted
  proposals via ``apply_ladder`` (the hot-swap path).

See docs/serving.md §adaptive bucket ladder.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Iterable, Mapping

#: cap on distinct histogram sizes fed to the DP (O(budget·m²)); denser
#: histograms are coarsened to quantile boundaries — merging a size into
#: the next retained boundary only ever over-pads, never mis-packs
MAX_CANDIDATES = 256


def validate_ladder(buckets: Iterable[Any]) -> tuple[int, ...]:
    """Normalize + validate one bucket ladder: every rung a positive
    int, strictly ascending (no duplicates). Returns the tuple;
    raises ``ValueError`` naming the offending rung. The ONE ladder
    gate — ``ServeConfig`` wraps the error into a typed
    ``ModelLoadError`` at load."""
    try:
        out = tuple(int(b) for b in buckets)
    except (TypeError, ValueError) as e:
        raise ValueError(f"bucket ladder {buckets!r}: not ints ({e})")
    if not out:
        raise ValueError("bucket ladder is empty")
    for i, b in enumerate(out):
        if b < 1:
            raise ValueError(
                f"bucket ladder {out!r}: rung {b} at index {i} is not "
                f"a positive row count")
    for i in range(1, len(out)):
        if out[i] == out[i - 1]:
            raise ValueError(
                f"bucket ladder {out!r}: duplicate rung {out[i]}")
        if out[i] < out[i - 1]:
            raise ValueError(
                f"bucket ladder {out!r}: rung {out[i]} after "
                f"{out[i - 1]} — rungs must be strictly ascending")
    return out


def _histogram(sizes: Any) -> Counter:
    """Request sizes → ``{size: count}``. Accepts a mapping (already a
    histogram) or an iterable of observed row counts."""
    if isinstance(sizes, Mapping):
        return Counter({int(s): int(c) for s, c in sizes.items()
                        if int(c) > 0})
    return Counter(int(s) for s in sizes)


def expected_padded_rows(sizes: Any, buckets: Iterable[int]) -> int:
    """Total rows *dispatched* (after bucket padding) serving the
    histogram on ``buckets`` — the cost the fit minimizes. Raises when
    a size exceeds the top rung (such a request would be refused at
    admission; a candidate ladder must cover the observed traffic)."""
    hist = _histogram(sizes)
    ladder = validate_ladder(buckets)
    total = 0
    for size, count in hist.items():
        for b in ladder:
            if b >= size:
                total += count * b
                break
        else:
            raise ValueError(
                f"size {size} exceeds top rung {ladder[-1]}")
    return total


def _coarsen(sizes: list[int], limit: int) -> list[int]:
    """Keep at most ``limit`` boundary sizes (quantile-spaced, always
    keeping the largest): merged sizes round up to the next retained
    boundary, which over-pads slightly but stays admissible."""
    if len(sizes) <= limit:
        return sizes
    step = len(sizes) / limit
    picked = sorted({sizes[min(len(sizes) - 1, int((i + 1) * step) - 1)]
                     for i in range(limit)} | {sizes[-1]})
    return picked


def fit_ladder(sizes: Any, budget: int, max_bucket: int
               ) -> tuple[int, ...]:
    """Fit a ladder of at most ``budget`` rungs over row sizes
    ``1..max_bucket`` minimizing :func:`expected_padded_rows` on the
    observed histogram. The top rung is always ``max_bucket`` (the
    admission contract is immutable: whatever was servable stays
    servable). Deterministic for a given histogram: exact DP with
    stable tie-breaks (fewer rungs win a cost tie, then the earlier
    split). Sizes above ``max_bucket`` are ignored defensively — the
    server never admits them, so they cannot appear in honest stats."""
    budget = int(budget)
    max_bucket = int(max_bucket)
    if budget < 1:
        raise ValueError(f"program budget {budget} < 1")
    if max_bucket < 1:
        raise ValueError(f"max_bucket {max_bucket} < 1")
    hist = _histogram(sizes)
    hist = Counter({s: c for s, c in hist.items()
                    if 1 <= s <= max_bucket})
    if not hist:
        return (max_bucket,)
    cands = _coarsen(sorted(set(hist) | {max_bucket}), MAX_CANDIDATES)
    m = len(cands)
    # cnt[j] = requests of size in (cands[j-1], cands[j]] — after
    # coarsening every observed size rounds up to its boundary
    cnt = [0] * m
    for s, c in hist.items():
        for j, b in enumerate(cands):
            if b >= s:
                cnt[j] += c
                break
    pc = [0] * (m + 1)  # prefix counts: pc[j] = sum(cnt[:j])
    for j in range(m):
        pc[j + 1] = pc[j] + cnt[j]

    def seg_cost(i: int, j: int) -> int:
        # requests with size in (cands[i], cands[j]] dispatch at rung
        # cands[j]; i == -1 means "everything up to cands[j]"
        return (pc[j + 1] - pc[i + 1]) * cands[j]

    budget = min(budget, m)
    inf = float("inf")
    # dp[j] after k rungs = min cost covering sizes ≤ cands[j] with the
    # k-th (largest) rung exactly cands[j]
    dp = [seg_cost(-1, j) for j in range(m)]
    parent: list[list[int | None]] = [[None] * m]
    best_cost, best_k = dp[m - 1], 1
    for _k in range(2, budget + 1):
        ndp = [inf] * m
        npar: list[int | None] = [None] * m
        for j in range(m):
            for i in range(j):
                c = dp[i] + seg_cost(i, j)
                if c < ndp[j]:
                    ndp[j], npar[j] = c, i
        dp = ndp
        parent.append(npar)
        if dp[m - 1] < best_cost:  # strict: cost ties keep fewer rungs
            best_cost, best_k = dp[m - 1], _k
    rungs = []
    j: int | None = m - 1
    for k in range(best_k - 1, -1, -1):
        rungs.append(cands[j])
        j = parent[k][j]
    return tuple(reversed(rungs))


class LadderAdvisor:
    """The re-fit policy around :func:`fit_ladder`.

    A ladder change is a per-model hot-swap (recompile/reload + atomic
    flip), so it must be *worth it* and *safe*: :meth:`propose` returns
    a new ladder only when the observation window is SLO-clean (never
    reshape the fleet while burning error budget — the canary
    discipline), carries at least ``min_requests`` observations, and
    the fitted ladder cuts expected padded work by at least
    ``min_improvement`` (fractional). Anything else returns ``None``.
    """

    def __init__(self, budget: int | None = None,
                 min_requests: int = 256,
                 min_improvement: float = 0.05):
        self.budget = budget
        self.min_requests = int(min_requests)
        self.min_improvement = float(min_improvement)

    def propose(self, sizes: Any, current: Iterable[int], *,
                slo_clean: bool = True,
                budget: int | None = None) -> tuple[int, ...] | None:
        current = validate_ladder(current)
        if not slo_clean:
            return None
        hist = _histogram(sizes)
        max_bucket = current[-1]
        hist = Counter({s: c for s, c in hist.items()
                        if 1 <= s <= max_bucket})
        n = sum(hist.values())
        if n < self.min_requests:
            return None
        budget = budget or self.budget or len(current)
        fitted = fit_ladder(hist, budget, max_bucket)
        if fitted == current:
            return None
        cur_cost = expected_padded_rows(hist, current)
        new_cost = expected_padded_rows(hist, fitted)
        if cur_cost <= 0 or \
                new_cost > (1.0 - self.min_improvement) * cur_cost:
            return None
        return fitted

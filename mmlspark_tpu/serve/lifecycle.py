"""Model lifecycle — promotion policy, canary state, decision journal.

The serving analog of the training service's supervision split
(``train/service.py``, PR 11): **sensors** are the PR 8 SLO burn engine
evaluated over the canary's own stats registry (plus shadow-mode output
parity); **policy** is :class:`PromotionPolicy` — a PURE decision
function from a typed :class:`CanarySignal` and the
:class:`PromotionLedger` to a typed action (promote / rollback / hold),
unit-testable without a server; the **actuator** is ``ModelServer``
(``serve/server.py``), which routes the traffic split, samples the
signal on each lifecycle tick, executes the action, and records every
decision through :class:`DecisionJournal` (``decisions.jsonl`` on disk
when ``ServeConfig.lifecycle_dir`` is set, always in memory, mirrored
as obs ``lifecycle/*`` events + ``serve.lifecycle.*`` counters when the
tracer is on).

Rollout modes (``ModelServer.deploy_canary``):

* **shadow** — the split fraction of admissions is *mirrored*: the
  client always gets the stable version's answer; the copy exercises
  the canary and its outputs are diffed against the stable answers
  (max-abs parity, the calibration discipline of
  docs/quantization.md). Zero blast radius; catches crashes, burn,
  and numerical drift before any client sees the new version.
* **canary** — the split fraction is *routed*: those clients get the
  canary's answers. Real exposure, bounded by the fraction.

The routing fraction is a deterministic Bresenham accumulator (every
``1/fraction``-th admission), not a coin flip: the same admission
sequence always splits the same way, which is what makes the chaos gate
reproducible.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.lockwitness import named_lock
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import event as _obs_event

_log = get_logger(__name__)

#: decision kinds that bump a ``serve.lifecycle.<kind>s`` counter
COUNTED_KINDS = ("swap", "canary_deploy", "promote", "rollback",
                 "lane_death", "lane_restart")


def max_abs_parity(ref: Any, got: Any, input_cols: set) -> float | None:
    """Worst max-abs difference across two tables' numeric output
    columns (columns beyond the request's inputs preferred; all shared
    numeric columns when the transform only rewrote existing ones).
    None when nothing numeric is comparable — the shadow-parity and
    load-calibration read, shared so the two tolerances mean the same
    thing."""
    cols = [c for c in ref.columns
            if c in got.columns and c not in input_cols]
    if not cols:
        cols = [c for c in ref.columns if c in got.columns]
    worst = None
    for c in cols:
        pair = []
        for col in (ref[c], got[c]):
            try:
                if col.dtype == object:
                    pair.append(np.stack([np.asarray(v, np.float64)
                                          for v in col]))
                else:
                    pair.append(np.asarray(col, np.float64))
            except (TypeError, ValueError):
                pair = []
                break
        if len(pair) != 2 or pair[0].shape != pair[1].shape:
            continue  # non-numeric (images, text) or layout-changing
        diff = float(np.abs(pair[0] - pair[1]).max()) if pair[0].size \
            else 0.0
        worst = diff if worst is None else max(worst, diff)
    return worst


# ---------------------------------------------------------------------------
# decision journal
# ---------------------------------------------------------------------------


class DecisionJournal:
    """Every lifecycle decision, recorded where forensics can find it.

    Appends one JSON line per decision to ``<dir>/decisions.jsonl``
    when a directory is configured (the training service's discipline:
    supervision forensics must not depend on telemetry being enabled),
    always keeps a bounded in-memory tail, and mirrors into obs
    (``lifecycle/<kind>`` events + ``serve.lifecycle.<kind>s``
    counters) when the tracer is on."""

    def __init__(self, directory: str | None = None,
                 keep: int = 1024):
        self.path = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, "decisions.jsonl")
        self._tail: deque = deque(maxlen=int(keep))
        self._lock = named_lock("serve.lifecycle.DecisionJournal._lock")

    def record(self, kind: str, payload: dict) -> dict:
        entry = {"ts": time.time(), "kind": kind, **payload}
        with self._lock:
            self._tail.append(entry)
            if self.path is not None:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(entry, default=str) + "\n")
        _log.info("serve lifecycle: %s %s", kind, payload)
        if _obs_rt._enabled:
            _obs_event(f"lifecycle/{kind}", "serve",
                       {k: str(v) for k, v in payload.items()})
            if kind in COUNTED_KINDS:
                _obs_registry().counter(f"serve.lifecycle.{kind}s").add()
        return entry

    def entries(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            tail = list(self._tail)
        return tail if kind is None \
            else [e for e in tail if e["kind"] == kind]


# ---------------------------------------------------------------------------
# signal, ledger, policy
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CanarySignal:
    """One lifecycle tick's sensor reading, fully typed: the canary's
    burn rates (from its own :class:`~mmlspark_tpu.obs.slo.SLOTracker`
    sample — ``None`` = not enough canary traffic for a verdict), the
    short window's terminal count, and — in shadow mode — the worst
    observed output drift vs the stable version, with the tolerance it
    is judged against."""

    burn_short: float | None = None
    burn_long: float | None = None
    terminal_window: int = 0
    parity_drift: float | None = None
    parity_tolerance: float | None = None


@dataclasses.dataclass
class PromotionLedger:
    """What the policy conditions on across ticks: consecutive clean
    windows banked toward promotion, and total ticks taken."""

    clean_windows: int = 0
    ticks: int = 0


@dataclasses.dataclass(frozen=True)
class Promote:
    reason: str


@dataclasses.dataclass(frozen=True)
class Rollback:
    reason: str


@dataclasses.dataclass(frozen=True)
class Hold:
    reason: str = ""
    clean: bool = False   # this window banks toward promote_after


Action = Any  # Promote | Rollback | Hold


@dataclasses.dataclass(frozen=True)
class PromotionPolicy:
    """Signal → action, pure. The table (docs/serving.md):

    ===============================  ===================================
    signal                           action
    ===============================  ===================================
    shadow parity drift > tolerance  rollback (wrong answers waiting to
                                     happen)
    short-window burn ≥ fast_burn    rollback (the canary is torching
                                     its error budget)
    long-window burn ≥ slow_burn     hold, streak reset (sustained
                                     degradation is not promotable)
    no burn verdict                  hold (no traffic ≠ healthy)
    clean window                     bank it; ``promote_after``
                                     consecutive clean windows promote
    ===============================  ===================================

    ``fast_burn``/``slow_burn`` default from the SLO spec driving the
    canary's tracker (:meth:`for_spec`), so "unhealthy for the stable
    version" and "rollback the canary" mean the same burn.
    """

    fast_burn: float = 14.0
    slow_burn: float = 2.0
    promote_after: int = 3

    def __post_init__(self):
        if self.promote_after < 1:
            raise ValueError(
                f"promote_after must be >= 1: {self.promote_after}")
        if not (self.fast_burn > 0 and self.slow_burn > 0):
            raise ValueError("burn thresholds must be > 0")

    @classmethod
    def for_spec(cls, spec: Any, promote_after: int = 3
                 ) -> "PromotionPolicy":
        return cls(fast_burn=spec.fast_burn, slow_burn=spec.slow_burn,
                   promote_after=promote_after)

    def decide(self, sig: CanarySignal, ledger: PromotionLedger) -> Action:
        if (sig.parity_drift is not None
                and sig.parity_tolerance is not None
                and sig.parity_drift > sig.parity_tolerance):
            return Rollback(
                f"shadow parity drift {sig.parity_drift:.4g} exceeds "
                f"tolerance {sig.parity_tolerance:g}")
        if sig.burn_short is not None \
                and sig.burn_short >= self.fast_burn:
            return Rollback(
                f"canary fast-burn {sig.burn_short:.1f}x >= "
                f"{self.fast_burn:g}x budget over the short window "
                f"({sig.terminal_window} terminal)")
        if sig.burn_long is not None \
                and sig.burn_long >= self.slow_burn:
            return Hold(f"long-window burn {sig.burn_long:.1f}x >= "
                        f"{self.slow_burn:g}x budget")
        if sig.burn_short is None:
            return Hold("insufficient canary traffic for a verdict")
        if sig.burn_short < self.slow_burn:
            if ledger.clean_windows + 1 >= self.promote_after:
                return Promote(
                    f"{ledger.clean_windows + 1} consecutive clean "
                    f"windows (burn {sig.burn_short:.2f}x < "
                    f"{self.slow_burn:g}x)")
            return Hold(f"clean window "
                        f"{ledger.clean_windows + 1}/{self.promote_after}",
                        clean=True)
        return Hold(f"short-window burn {sig.burn_short:.1f}x above the "
                    f"promote threshold {self.slow_burn:g}x")


# ---------------------------------------------------------------------------
# canary routing state (owned by ModelServer)
# ---------------------------------------------------------------------------


class CanaryState:
    """One model's in-flight rollout: the candidate version's batcher
    plus everything the tick needs — the deterministic router, the
    shadow comparison ring, the SLO tracker over the canary's own
    stats, and the promotion ledger."""

    def __init__(self, name: str, version: Any, mode: str,
                 fraction: float, batcher: Any, tracker: Any,
                 policy: PromotionPolicy,
                 parity_tolerance: float | None = None,
                 max_pending_pairs: int = 256):
        if mode not in ("canary", "shadow"):
            raise ValueError(
                f"canary mode must be 'canary' or 'shadow': {mode!r}")
        if not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1]: {fraction}")
        self.name = name
        self.version = version
        self.mode = mode
        self.fraction = float(fraction)
        self.batcher = batcher
        self.tracker = tracker          # obs.slo.SLOTracker (canary's)
        self.policy = policy
        self.ledger = PromotionLedger()
        self.entry = None               # the full _ModelEntry promotion
        #                                 flips in (set by the server)
        # one policy evaluation at a time: two concurrent /slo pollers
        # must not interleave sample → decide → ledger-update (a clean
        # window would double-count toward promotion)
        self.tick_lock = named_lock("serve.lifecycle.CanaryState.tick_lock")
        self.parity_tolerance = parity_tolerance
        self._lock = named_lock("serve.lifecycle.CanaryState._lock")
        self._acc = 0.0
        # shadow mode: (stable request, mirror request) pairs awaiting
        # both resolutions; bounded drop-oldest — parity is a sampled
        # signal, not an audit log
        self._pairs: deque = deque(maxlen=int(max_pending_pairs))
        self.parity_max: float | None = None
        self.pairs_compared = 0
        self.shadow_errors = 0

    # -- routing --

    def route(self) -> bool:
        """True when this admission belongs to the split — the
        deterministic Bresenham accumulator: over any window of N
        admissions, ``round(N * fraction) ± 1`` are taken, in a fixed
        pattern."""
        with self._lock:
            self._acc += self.fraction
            if self._acc >= 1.0 - 1e-12:
                self._acc -= 1.0
                return True
            return False

    def note_pair(self, stable_req: Any, mirror_req: Any) -> None:
        with self._lock:
            self._pairs.append((stable_req, mirror_req))

    # -- sampling --

    def collect_parity(self) -> None:
        """Fold every fully-resolved shadow pair into the parity
        signal; unresolved pairs stay pending. Called on the tick (and
        only there — no comparison thread; an unticked canary costs
        nothing beyond its mirrored dispatches)."""
        with self._lock:
            pending = []
            done = []
            while self._pairs:
                pair = self._pairs.popleft()
                if pair[0].done and pair[1].done:
                    done.append(pair)
                else:
                    pending.append(pair)
            self._pairs.extend(pending)
        for stable_req, mirror_req in done:
            if mirror_req._error is not None:
                # already burn-visible via the canary stats' failed
                # counter; tallied here so the status surface can say
                # "mirrors are dying" explicitly
                with self._lock:
                    self.shadow_errors += 1
                continue
            if stable_req._error is not None:
                continue  # stable-side timeout: nothing to diff
            drift = max_abs_parity(stable_req._result,
                                   mirror_req._result,
                                   set(stable_req.table.columns))
            if drift is None:
                continue
            with self._lock:
                self.pairs_compared += 1
                self.parity_max = drift if self.parity_max is None \
                    else max(self.parity_max, drift)
            self.batcher.stats.registry.histogram(
                "serve.canary_parity", window=1024,
                **self.batcher.stats.labels).observe(drift)

    def signal(self) -> CanarySignal:
        """Sample the burn engine (one SLO sample on the canary's
        registry) + the parity ring into one typed signal."""
        if self.mode == "shadow":
            self.collect_parity()
        status = self.tracker.sample()
        with self._lock:
            drift = self.parity_max
        return CanarySignal(
            burn_short=status.get("burn_rate_short"),
            burn_long=status.get("burn_rate_long"),
            terminal_window=(status.get("window_short") or {}).get(
                "terminal", 0),
            parity_drift=drift,
            parity_tolerance=self.parity_tolerance)

    def describe(self) -> dict:
        with self._lock:
            return {
                "version": self.version,
                "mode": self.mode,
                "fraction": self.fraction,
                "clean_windows": self.ledger.clean_windows,
                "ticks": self.ledger.ticks,
                "parity_max": self.parity_max,
                "pairs_compared": self.pairs_compared,
                "shadow_errors": self.shadow_errors,
            }

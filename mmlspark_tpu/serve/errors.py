"""Typed serving errors — the admission/deadline contract surface.

Every rejection a client can see is a distinct type, so callers (and the
HTTP front end's status mapping) dispatch on type, never on message text.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base of every serving-layer error.

    ``retry_after_s`` is the server-provided backpressure hint (the same
    value the HTTP layer sends as ``Retry-After``): ``None`` means the
    server offered none. Backpressure errors (:class:`Overloaded`,
    :class:`ServerClosed`) stamp it from the rejecting server's config;
    ``core/retry.call_with_retry`` treats it as a floor on its backoff
    delay so a client never retries sooner than the server asked.
    """

    retry_after_s: float | None = None


class Overloaded(ServeError):
    """Admission rejected: the model's request queue is full.

    Backpressure, not failure — the client should retry with backoff or
    shed load. Carries the observed depth so callers can log honestly.
    """

    def __init__(self, model: str, queued: int, max_queue: int,
                 retry_after_s: float | None = None):
        super().__init__(
            f"model {model!r} overloaded: {queued} requests queued "
            f"(max_queue={max_queue})")
        self.model = model
        self.queued = queued
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s


class DeadlineExceeded(ServeError):
    """The request's deadline expired before a result was delivered.

    Raised both for queue-expiry (the batcher cancels the request before
    dispatch) and for client-side expiry mid-flight; in either case the
    caller gets ONLY this error, never a partial result.
    """

    def __init__(self, model: str, deadline_ms: float, where: str):
        super().__init__(
            f"model {model!r}: deadline of {deadline_ms:.0f} ms exceeded "
            f"({where})")
        self.model = model
        self.deadline_ms = deadline_ms
        self.where = where  # "queued" | "in-flight"


class BadRequest(ServeError):
    """Malformed request: empty, larger than the biggest bucket, or
    column-incompatible with the served model."""


class ModelNotFound(ServeError):
    """No model registered under the requested name."""

    def __init__(self, name: str, available: list[str]):
        super().__init__(
            f"no model {name!r}; serving: {sorted(available)}")
        self.name = name
        self.available = list(available)


class ServerClosed(ServeError):
    """Submission after shutdown began (new work is rejected during
    drain)."""

    def __init__(self, message: str = "server is closed",
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class LaneFailed(ServeError):
    """The dispatch lane carrying this request died before its batch was
    drained (a non-request exception killed the lane worker, or the
    restart budget ran out with no survivor to absorb the queue).

    Transient from the client's point of view — the lane supervisor
    restarts the lane and UNDISPATCHED requests are requeued onto
    survivors automatically, so only in-flight batches ever surface
    this; a retry (``Client(..., retry=...)``) lands on a healthy lane.
    Carries the original lane exception as ``__cause__``.
    """

    def __init__(self, model: str, lane: int, detail: str):
        super().__init__(
            f"model {model!r}: dispatch lane {lane} failed before the "
            f"result was drained ({detail}); safe to retry")
        self.model = model
        self.lane = lane


class ModelLoadError(ServeError):
    """The model was rejected at load time, before any device work.

    Raised with no compile and no transfer performed, for either cause:
    the pre-flight analyzer found errors (``report`` is the full
    :class:`~mmlspark_tpu.analysis.AnalysisReport`), or the requested
    serving mesh cannot be realized on this host's devices / the sharded
    segment fails its SPMD contract (``message`` carries the reason and
    ``report`` is None).
    """

    def __init__(self, name: str, report=None, message: str | None = None):
        if message is None:
            errors = "\n  ".join(str(d) for d in report.errors)
            message = f"model {name!r} failed pre-flight analysis:\n  {errors}"
        super().__init__(message)
        self.name = name
        self.report = report

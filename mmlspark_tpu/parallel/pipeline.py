"""Pipeline parallelism over the ``pp`` mesh axis — GPipe-style collective
pipelining.

**Beyond reference parity by design.** The reference has no pipeline
parallelism of any kind (SURVEY §2.6: PP "No"); its only distributed
training is a single-node MPI data-parallel ring (reference:
cntk-train/src/main/scala/CommandBuilders.scala:79-93). On TPU pods,
pipelining layers across the ``pp`` axis is one of the standard scale-out
dimensions, so the framework ships a real implementation, not a reserved
axis name.

Design (the collective-pipelining recipe — one SPMD program, no
per-stage programs):

* the L identical blocks' parameters are **stacked on a leading layer
  axis** and sharded over ``pp`` — stage *s* holds layers
  ``[s·L/P, (s+1)·L/P)``,
* inside one ``shard_map``, every stage steps the same loop
  ``M + P - 1`` times (M microbatches, P stages): apply the local layer
  stack to the in-flight activation, then ``ppermute`` it to the next
  stage. Stage 0 injects microbatch *t* at step *t*; the last stage
  collects microbatch *j* at step ``j + P - 1``. The ``P - 1`` bubble
  steps compute on stale activations whose results are never collected,
* outputs are zeroed off the last stage and ``psum``-replicated over
  ``pp``, so the caller sees an ordinary ``[B, ...]`` array,
* everything (``ppermute``, ``psum``, the scan) is differentiable, so
  ``jax.grad`` through :func:`pipeline_apply` yields exact gradients —
  the numerics match the unpipelined layer stack bit-for-bit in f32
  (asserted by the tests on the virtual CPU mesh),
* the batch axis simultaneously shards over ``dp``/``fsdp`` (each dp
  group pipelines its own microbatch slices), composing PP×DP in one
  program.

Scheduling note: this is the GPipe fill-drain schedule — bubble fraction
``(P-1)/(M+P-1)``, driven down by more microbatches. 1F1B-style
schedules reduce activation memory, not bubbles; with ``jax.grad`` the
backward replays the same collective schedule in reverse, which is the
natural fit for XLA's compilation model.
"""

from __future__ import annotations

from typing import Any, Callable


def commit_replicated(tree: Any, mesh) -> Any:
    """Pin traced shard_map operands fully replicated before entry.

    GSPMD full-to-shard sharp edge (jax ≤ 0.4.37): an operand computed
    *inside* an enclosing jit trace (e.g. per-block params re-stacked at
    trace time) can reach the partitioner sharded over mesh axes its
    ``in_spec`` leaves unmentioned; with the replication check off
    (``check_vma=False`` — required by per-shard code) the conversion
    consumes it as an **unreduced partial sum**: every shard sees
    axis-extent × the true value. On a dp×pp mesh this silently scaled
    the pipelined ViT forward by the dp extent (the dp×pp loss-parity
    seed failure). An explicit replicated sharding constraint on traced
    leaves forces the correct (local-slice) conversion; concrete arrays
    committed by ``jax.device_put`` never hit the edge and pass through
    untouched. The SPMD verifier's partial-sum escape check
    (:mod:`mmlspark_tpu.analysis.spmd`) flags shard_map call sites that
    feed trace-computed operands without this pin."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())

    def one(leaf):
        if isinstance(leaf, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(leaf, repl)
        return leaf

    return jax.tree_util.tree_map(one, tree)


def stack_layer_params(layer_params: list) -> Any:
    """Stack per-layer pytrees (one per block, identical structure) into a
    single pytree with a leading layer axis — the shape
    :func:`pipeline_apply` shards over ``pp``."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *layer_params)


def pipeline_spec(mesh, stacked_params) -> Any:
    """NamedShardings placing stacked layer params on the pipeline: layer
    axis over ``pp``, replicated over every other mesh axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def one(leaf):
        return NamedSharding(mesh, P("pp"))

    return jax.tree_util.tree_map(one, stacked_params)


def pipeline_apply(block_fn: Callable, stacked_params: Any, x: Any,
                   mesh, num_microbatches: int) -> Any:
    """Run ``x`` through L pipelined blocks: ``block_fn(layer_params, h)``
    applied layer-by-layer, stages sharded over ``pp``.

    ``stacked_params``: pytree with leading layer axis L (from
    :func:`stack_layer_params`), L divisible by the ``pp`` extent.
    ``x``: ``[B, ...]`` with B divisible by
    ``num_microbatches × dp-extent``. Returns ``[B, ...]`` activations
    after all L blocks, identical (up to dtype rounding) to applying the
    blocks sequentially.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    pp = mesh.shape["pp"]
    M = int(num_microbatches)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = int(leaves[0].shape[0])
    if L % pp:
        raise ValueError(f"{L} layers not divisible by pp={pp}")
    B = x.shape[0]
    dp_ext = mesh.shape["dp"] * mesh.shape["fsdp"]
    if B % (M * dp_ext):
        raise ValueError(
            f"batch {B} not divisible by microbatches {M} x dp {dp_ext}")
    mb = B // M
    xm = x.reshape((M, mb) + x.shape[1:])

    def stage_fn(stacked, xm_local):
        # stacked: [L/pp, ...] this stage's layers
        # xm_local: [M, mb/dp, ...] this dp-slice's microbatches
        idx = jax.lax.axis_index("pp")

        def apply_stage(h):
            def body(h, layer):
                return block_fn(layer, h), None
            h, _ = jax.lax.scan(body, h, stacked)
            return h

        shape = xm_local.shape[1:]
        state0 = jnp.zeros(shape, xm_local.dtype)
        out0 = jnp.zeros((M,) + shape, xm_local.dtype)

        def step(carry, t):
            state, out = carry
            # stage 0 injects microbatch t (clip keeps the gather legal
            # during the drain steps; the value is unused off stage 0)
            inject = jax.lax.dynamic_index_in_dim(
                xm_local, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            h = jnp.where(idx == 0, inject, state)
            h = apply_stage(h)
            # last stage collects microbatch t-(P-1) while the pipe drains
            wi = jnp.clip(t - (pp - 1), 0, M - 1)
            valid = (idx == pp - 1) & (t >= pp - 1)
            cur = jax.lax.dynamic_index_in_dim(out, wi, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, h.astype(out.dtype), cur), wi, 0)
            # rotate the in-flight activation one stage down the ring
            state = jax.lax.ppermute(
                h, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (state, out), None

        (_, out), _ = jax.lax.scan(step, (state0, out0),
                                   jnp.arange(M + pp - 1))
        # outputs live on the last stage only; replicate over pp
        out = jnp.where(idx == pp - 1, out, jnp.zeros_like(out))
        return jax.lax.psum(out, "pp")

    data_axes = ("dp", "fsdp")
    from mmlspark_tpu.parallel.mesh import shard_map
    # trace-computed layer stacks (the Trainer re-stacks block{i} params
    # at trace time) must be pinned replicated or the pp-unaware dp axis
    # corrupts them on entry — see commit_replicated
    stacked_params = commit_replicated(stacked_params, mesh)
    out = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(P("pp"), P(None, data_axes)),
        out_specs=P(None, data_axes),
        check_vma=False,
    )(stacked_params, xm)
    return out.reshape((B,) + out.shape[2:])

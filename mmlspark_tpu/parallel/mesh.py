"""Device-mesh construction and axis conventions.

Canonical mesh axes, in order:

* ``dp``  — data parallelism (gradient all-reduce, batch sharding)
* ``fsdp``— parameter/optimizer sharding across the data axis (zero-style)
* ``tp``  — tensor parallelism (matmul column/row sharding)
* ``sp``  — sequence/context parallelism (ring attention)
* ``pp``  — pipeline stages
* ``ep``  — expert parallelism (MoE)

The reference's only strategy is single-node MPI data parallelism with GPU
count discovered via ``nvidia-smi`` (reference:
cntk-train/src/main/scala/CommandBuilders.scala:79-93,
core/env/src/main/scala/EnvironmentUtils.scala:20-50); here every strategy
is a mesh axis and XLA inserts the collectives. Multi-host: the same mesh
spans all processes' devices (``jax.devices()`` is global after
``jax.distributed.initialize``), with DCN-friendly axis ordering (dp
outermost so cross-slice traffic is gradient-only).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping, Sequence

import numpy as np

# the canonical axis vocabulary. Collectives must name these axes
# literally where possible: the JX202 lint (tools/lint_jax.py keeps a
# jax-free mirror of this tuple) rejects any other literal, and the
# SPMD verifier (analysis/spmd.py) checks traced axis names against the
# concrete mesh
AXES = ("dp", "fsdp", "tp", "sp", "pp", "ep")


def shard_map(f: Any, mesh: Any, in_specs: Any, out_specs: Any,
              check_vma: bool | None = None) -> Any:
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it as ``jax.shard_map`` (with the replication check
    named ``check_vma``); older releases only have
    ``jax.experimental.shard_map.shard_map`` (named ``check_rep``). Every
    in-repo shard_map call goes through this shim so the parallel paths run
    on both."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as sm_exp
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout; -1 on ``dp`` means "all remaining"."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dataclasses.asdict(self)
        fixed = math.prod(v for v in sizes.values() if v != -1)
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {free}")
        if free:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[free[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh {sizes} covers {total} devices, have {n_devices}")
        return sizes


def make_mesh(spec: MeshSpec | Mapping[str, int] | None = None,
              devices: Sequence[Any] | None = None):
    """Build a ``jax.sharding.Mesh`` over all (or given) devices."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec()
    if isinstance(spec, Mapping):
        spec = MeshSpec(**dict(spec))
    explicit = dataclasses.asdict(spec)
    if -1 not in explicit.values() and jax.process_count() == 1:
        # a fully-explicit spec smaller than the host's device count means
        # "use this many devices" — take a prefix instead of raising.
        # Single-process only: in a multi-host run a prefix would be
        # host-0's devices, leaving other processes nothing addressable —
        # there the loud size-mismatch ValueError below is correct
        total = math.prod(explicit.values())
        if total < len(devices):
            import logging
            logging.getLogger(__name__).info(
                "make_mesh: explicit spec uses %d of %d local devices",
                total, len(devices))
            devices = devices[:total]
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXES)


def default_mesh_spec(n_devices: int | None = None) -> MeshSpec:
    """Pure data parallelism over every device — the reference-parity
    strategy (MPI DP ring analog)."""
    return MeshSpec(dp=-1)


def single_device(mesh) -> Any | None:
    """The 1-device fast-path criterion: the bare device when the mesh has
    exactly one, else None. THE single source of truth — the train step's
    plain-jit path, the Trainer's commit target, and the elastic reshard
    targets (:func:`state_shardings`) must always agree, or batches
    committed with a NamedSharding would feed a plain-jit program (or
    vice versa)."""
    if int(mesh.devices.size) == 1:
        return mesh.devices.reshape(-1)[0]
    return None


def batch_sharding(mesh) -> Any:
    """Sharding for a [batch, ...] array: batch split over dp (and fsdp)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh) -> Any:
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def param_shardings(mesh, params, rules: Any = None) -> Any:
    """Pytree of shardings for the params.

    * ``rules`` (optional): ``callable(path: str, leaf) -> PartitionSpec |
      None`` consulted FIRST — how model families place structurally
      special params (e.g. a TransformerTagger's stacked MoE expert
      weights over ``ep``; see ``Module.mesh_hooks`` in
      :mod:`mmlspark_tpu.train.loop`). ``path`` is the ``/``-joined key
      path of the leaf. Returning None falls through to the generic
      rules below.
    * ``tp > 1``: every ≥2-D leaf's LAST (output-feature) dim shards over
      the tensor-parallel axis when divisible — column-parallel matmuls;
      GSPMD propagates the activation shardings and inserts the
      all-reduces/all-gathers (the annotate-and-let-XLA recipe; no manual
      collectives).
    * ``fsdp > 1``: the largest remaining divisible dim shards over fsdp
      (zero-style parameter sharding; XLA all-gathers for the forward and
      reduce-scatters the grads).
    * Leaves with no divisible dim — and everything on a pure-dp mesh —
      replicate. ``pp`` layouts are structural, not per-leaf: pipeline
      stages shard stacked layer params via
      :func:`mmlspark_tpu.parallel.pipeline.pipeline_spec` (the Trainer
      re-stacks per-block params at trace time instead).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    fsdp = mesh.shape["fsdp"]
    tp = mesh.shape["tp"]

    def one(path, leaf):
        if rules is not None:
            spec = rules("/".join(str(getattr(k, "key", k)) for k in path),
                         leaf)
            if spec is not None:
                return NamedSharding(mesh, spec)
        shape = getattr(leaf, "shape", ())
        spec: list = [None] * len(shape)
        if tp > 1 and len(shape) >= 2 and shape[-1] % tp == 0:
            spec[-1] = "tp"
        if fsdp > 1 and len(shape) > 0:
            divisible = [(d, s) for d, s in enumerate(shape)
                         if spec[d] is None and s % fsdp == 0]
            if divisible:
                d = max(divisible, key=lambda t: t[1])[0]
                spec[d] = "fsdp"
        if all(s is None for s in spec):
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(one, params)


def state_shardings(mesh, state: Mapping[str, Any],
                    rules: Any = None) -> Any:
    """Placement targets for a FULL train-state pytree
    (``{params, opt_state, step}``) on ``mesh`` — the elastic-rescale
    counterpart of the placement ``Trainer.init_state`` performs:

    * ``params`` leaves place via :func:`param_shardings` (module
      ``rules`` first, then the generic tp/fsdp rules),
    * optimizer moments mirror the params: any subtree of a non-param
      entry whose tree STRUCTURE equals the params tree (optax moments
      — adam's ``mu``/``nu``, momentum's ``trace`` — are built by
      ``tree_map`` over the params) takes the params shardings leaf for
      leaf, exactly where eager ``zeros_like`` propagation put them at
      init — including ``rules``-placed leaves (MoE expert stacks over
      ``ep``),
    * remaining leaves place by the generic per-leaf rule on their own
      shape; scalar leaves (optax step counts) replicate,
    * a 1-device mesh returns the bare device for every leaf (the
      plain-placement fast path ``single_device`` defines).

    This is what makes ``reshard_state`` (train/checkpoint.py) exact: a
    state restored or re-placed through these targets is
    indistinguishable from one built by ``init_state`` on the same mesh.
    """
    import jax

    dev0 = single_device(mesh)
    if dev0 is not None:
        return jax.tree_util.tree_map(lambda leaf: dev0, state)
    placed = dict(state)
    params_sh = param_shardings(mesh, state["params"], rules=rules)
    placed["params"] = params_sh
    p_treedef = jax.tree_util.tree_structure(state["params"])
    repl = replicated(mesh)

    # bare-leaf params would make every leaf "mirror" them (a scalar
    # optax count included) — mirroring only means anything for a real
    # params CONTAINER
    leaf_def = jax.tree_util.tree_structure(0)

    def mirrors_params(node) -> bool:
        return (p_treedef != leaf_def
                and jax.tree_util.tree_structure(node) == p_treedef)

    def one(node):
        if mirrors_params(node):  # a params-shaped moment subtree
            return params_sh
        if getattr(node, "shape", ()):
            return param_shardings(mesh, {"leaf": node})["leaf"]
        return repl

    for key in state:
        if key != "params":
            placed[key] = jax.tree_util.tree_map(
                one, state[key], is_leaf=mirrors_params)
    return placed

"""Expert parallelism over the ``ep`` mesh axis — a Switch-style
mixture-of-experts layer with all-to-all token dispatch.

**Beyond reference parity by design.** The reference has no MoE/expert
parallelism (SURVEY §2.6: EP "No"). The TPU-native formulation is the
Mesh-TensorFlow / Switch-Transformer dispatch algebra expressed as one
``shard_map`` over ``ep``:

* tokens shard over ``ep`` (each shard routes its own slice); expert
  parameters shard over ``ep`` on the expert axis (each shard OWNS
  ``E / ep`` experts),
* top-1 gating with a fixed per-expert **capacity** shared by the whole
  ``ep`` ring: slot positions are assigned *globally* — each shard
  ``all_gather``s the per-expert routed counts, offsets its local cumsum
  ranks by the lower shards' counts, and keeps tokens whose global rank
  fits the capacity (overflow tokens dropped — they contribute zero and
  pass through the residual). Per-shard capacity splits were the
  pad-capacity bug class: a token's survival depended on which shard its
  padding landed on, not on the global expert load,
* each shard scatters its ``[E, C, d]`` dispatch buffer with
  ``psum_scatter`` over ``ep`` — global slots are disjoint across source
  shards, so the reduce-scatter IS the union and every shard receives
  exactly its own experts' fully-populated slots — applies its local
  expert FFNs, and ``all_gather``s the expert outputs back to the source
  shards for the combine,
* a load-balancing auxiliary loss (mean gate prob × token fraction per
  expert, Switch §2.2 style) is returned alongside the outputs,
* everything is differentiable; numerics match a dense (every-expert)
  reference exactly when capacity is ample (asserted on the CPU mesh).

**Declared sharding contract** (verified statically by
:mod:`mmlspark_tpu.analysis.spmd`, pinned against the lowered program
in tests/test_spmd.py): tokens/mask ``P(('dp','fsdp','ep'))``, expert
stacks ``P('ep')``, gate replicated; collective schedule
``all_gather(ep)`` counts → ``psum_scatter(ep)`` dispatch →
``all_gather(ep)`` outputs → 3 × ``psum(dp,fsdp,ep)`` aux. The
capacity-dispatch rule (SPMD104/JX204) requires exactly the leading
count exchange this layout performs.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def init_moe_params(key, num_experts: int, d_model: int, d_hidden: int,
                    dtype=None) -> dict:
    """Gate + stacked expert-FFN params (expert axis leading)."""
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    kg, k1, k2 = jax.random.split(key, 3)
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return {
        "gate": jax.random.normal(kg, (d_model, num_experts), dtype) * s1,
        "w_in": jax.random.normal(
            k1, (num_experts, d_model, d_hidden), dtype) * s1,
        "b_in": jnp.zeros((num_experts, d_hidden), dtype),
        "w_out": jax.random.normal(
            k2, (num_experts, d_hidden, d_model), dtype) * s2,
        "b_out": jnp.zeros((num_experts, d_model), dtype),
    }


def moe_param_spec(mesh, params) -> Any:
    """Shardings for the param dict — derived from the SAME layout
    :func:`moe_in_specs` hands to shard_map, so device placement can
    never drift from the kernel's expectations."""
    from jax.sharding import NamedSharding

    specs = moe_in_specs()
    return {k: NamedSharding(mesh, specs[k]) for k in params}


def _expert_ffn(params_e, x):
    """One expert's FFN on [n, d] tokens; params_e carries that expert's
    slices (no expert axis)."""
    import jax.numpy as jnp
    h = jnp.maximum(x @ params_e["w_in"] + params_e["b_in"], 0.0)
    return h @ params_e["w_out"] + params_e["b_out"]


def moe_apply(params: dict, x: Any, mesh, capacity_factor: float = 2.0,
              token_mask: Any = None) -> tuple[Any, Any]:
    """Route ``x`` ``[N, d]`` through expert-parallel top-1 MoE.

    Returns ``(y, aux_loss)`` — ``y[i]`` is ``gate_i · expert(x_i)`` for
    routed tokens and 0 for capacity-dropped ones (callers add the
    residual), ``aux_loss`` is the Switch load-balancing scalar.

    ``token_mask`` (``[N]``, 1 = real token): masked-out (padding) tokens
    never claim capacity slots, output exact zeros, and are excluded from
    the aux statistics — so a sequence's real-token routing does not
    depend on how much padding its bucket added (the padding invariant
    the sequence models promise).

    ``N`` must divide by the ``dp × fsdp × ep`` extent (tokens shard over
    the data axes AND ``ep``, so a dp×ep mesh splits work instead of
    replicating it); the expert count is the leading dim of the stacked
    expert params and must divide by ``ep``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    ep = mesh.shape["ep"]
    dp_ext = mesh.shape["dp"] * mesh.shape["fsdp"]
    E = int(params["w_in"].shape[0])
    d = int(x.shape[-1])
    N = int(x.shape[0])
    if E % ep:
        raise ValueError(f"{E} experts not divisible by ep={ep}")
    if N % (ep * dp_ext):
        raise ValueError(
            f"{N} tokens not divisible by dp*fsdp*ep = {ep * dp_ext}")
    n_local = N // (ep * dp_ext)
    # per-expert slots for the WHOLE ep ring (fixed shape for XLA). The
    # budget must be global: splitting it per source shard makes a
    # token's survival depend on how the batch (and its padding) lands
    # across shards instead of on the expert's global load — the
    # pad-capacity bug the SPMD verifier's divisibility check flags
    C = max(1, int(np.ceil(capacity_factor * n_local * ep / E)))
    e_local = E // ep
    if token_mask is None:
        token_mask = jnp.ones((N,), jnp.float32)
    token_axes = ("dp", "fsdp", "ep")

    def shard_fn(p, xs, m):
        # xs: [n_local, d] this shard's tokens; m: [n_local] 0/1 mask
        m = m.astype(jnp.float32)
        logits = xs @ p["gate"]                       # [n, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)           # [n] top-1
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
        # routing bookkeeping in int32/f32 REGARDLESS of the token dtype:
        # a bf16 cumsum saturates at 256, silently aliasing slot positions.
        # Masked tokens zero their one-hot row up front: they claim no
        # capacity and vanish from dispatch, combine, and aux alike
        onehot_i = jax.nn.one_hot(expert, E, dtype=jnp.int32) \
            * m.astype(jnp.int32)[:, None]                      # [n, E]
        # GLOBAL position of each token within its expert's capacity
        # slots: local cumsum rank + the routed counts of every lower
        # ep shard (one all_gather of a tiny [E] int vector). This is
        # the cross-shard count exchange that makes capacity a property
        # of the expert, not of where the token (or its padding) landed
        counts = onehot_i.sum(axis=0)                            # [E]
        counts_all = jax.lax.all_gather(counts, "ep")            # [ep, E]
        me = jax.lax.axis_index("ep")
        before = (jnp.arange(ep) < me)[:, None].astype(jnp.int32)
        offset = (counts_all * before).sum(axis=0)               # [E]
        pos = (jnp.cumsum(onehot_i, axis=0) - onehot_i) * onehot_i
        pos = jnp.sum(pos, axis=-1)                              # [n] int32
        pos = pos + (onehot_i * offset[None, :]).sum(axis=-1)
        keep = pos < C
        # dispatch tensor [n, E, C]: one-hot over (expert, global slot)
        onehot = onehot_i.astype(jnp.float32)
        slot = jax.nn.one_hot(pos, C, dtype=jnp.float32) \
            * keep[:, None].astype(jnp.float32)
        dispatch = onehot[:, :, None] * slot[:, None, :]        # [n, E, C]
        slots = jnp.einsum("nec,nd->ecd", dispatch,
                           xs.astype(jnp.float32)).astype(xs.dtype)
        # deliver every expert's slots to its owning shard: global slots
        # are disjoint across source shards, so the reduce-scatter's sum
        # is the union, and each shard receives [e_local, C, d]
        slots = jax.lax.psum_scatter(slots.reshape(ep, e_local, C, d),
                                     "ep", scatter_dimension=0,
                                     tiled=False)
        # apply local experts to their C slots (scan unstacks the
        # expert axis of params and slots together; reverse-mode safe)
        stacked_pe = {k: p[k] for k in ("w_in", "b_in", "w_out", "b_out")}

        def one_expert(_, args):
            pe, slot = args
            return None, _expert_ffn(pe, slot)

        _, outs = jax.lax.scan(one_expert, None, (stacked_pe, slots))
        # route back: every source shard combines from the full expert
        # set, so gather the [e_local, C, d] outputs into [E, C, d]
        outs = jax.lax.all_gather(outs, "ep")                   # [ep,el,C,d]
        outs = outs.reshape(E, C, d)
        y = (jnp.einsum("nec,ecd->nd", dispatch,
                        outs.astype(jnp.float32))
             * gate.astype(jnp.float32)[:, None]).astype(xs.dtype)
        # Switch load-balance loss over REAL tokens only: global masked
        # means via psum of (numerator, count)
        cnt = jnp.maximum(jax.lax.psum(m.sum(), token_axes), 1.0)
        frac = jax.lax.psum(onehot.sum(axis=0), token_axes) / cnt
        mean_p = jax.lax.psum(
            (probs.astype(jnp.float32) * m[:, None]).sum(axis=0),
            token_axes) / cnt
        aux = E * jnp.sum(frac * mean_p)
        return y, aux[None]

    from mmlspark_tpu.parallel.mesh import shard_map
    y, aux = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(moe_in_specs(), P(token_axes), P(token_axes)),
        out_specs=(P(token_axes), P()),
        check_vma=False,
    )(params, x, token_mask)
    return y, aux[0]


def moe_in_specs() -> Any:
    from jax.sharding import PartitionSpec as P
    return {"gate": P(), "w_in": P("ep"), "b_in": P("ep"),
            "w_out": P("ep"), "b_out": P("ep")}


def moe_dense(params: dict, x: Any, token_mask: Any = None
              ) -> tuple[Any, Any]:
    """Dense top-1 MoE: every token through its argmax expert, no
    capacity, no parallelism. Returns ``(y, aux)`` with the same Switch
    load-balance aux as :func:`moe_apply` — the single-device execution
    path for MoE models (and the oracle the parallel path must match
    when capacity is ample). ``token_mask`` as in :func:`moe_apply`:
    masked tokens output zero and are excluded from the aux statistics."""
    import jax
    import jax.numpy as jnp

    m = (jnp.ones((x.shape[0],), jnp.float32) if token_mask is None
         else token_mask.astype(jnp.float32))
    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0] * m
    E = params["w_in"].shape[0]
    outs = []
    for e in range(E):
        pe = {k: params[k][e] for k in ("w_in", "b_in", "w_out", "b_out")}
        outs.append(_expert_ffn(pe, x))
    dense = jnp.stack(outs, axis=1)                   # [N, E, d]
    sel = jnp.take_along_axis(
        dense, expert[:, None, None].repeat(dense.shape[-1], -1), 1)[:, 0]
    cnt = jnp.maximum(m.sum(), 1.0)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32) * m[:, None]
    frac = onehot.sum(axis=0) / cnt
    mean_p = (probs.astype(jnp.float32) * m[:, None]).sum(axis=0) / cnt
    aux = E * jnp.sum(frac * mean_p)
    return sel * gate[:, None], aux


def moe_reference(params: dict, x: Any) -> Any:
    """Back-compat oracle wrapper: just the outputs of :func:`moe_dense`."""
    return moe_dense(params, x)[0]

"""Distributed layer: device meshes, sharding rules, and collectives.

The TPU-native replacement for the reference's two communication planes —
Spark shuffle/broadcast for data and an external MPI ring for training
(reference: cntk-train/src/main/scala/CommandBuilders.scala:60-117) —
expressed as XLA collectives over ICI/DCN via ``jax.sharding.Mesh`` +
``jit``/``shard_map``. There is no external process and no MPI: gradients
all-reduce over ICI inside the compiled step function.

Every module here carries a **declared sharding contract** (its
in/out specs and collective schedule), statically verified by the SPMD
verifier (:mod:`mmlspark_tpu.analysis.spmd`; ``ENTRY_POINTS`` is the
registry) and gated at zero findings in tier-1 — see
docs/spmd_analysis.md.
"""

from mmlspark_tpu.parallel.mesh import (
    MeshSpec,
    default_mesh_spec,
    make_mesh,
)
from mmlspark_tpu.parallel.moe import (
    init_moe_params,
    moe_apply,
    moe_param_spec,
)
from mmlspark_tpu.parallel.pipeline import (
    commit_replicated,
    pipeline_apply,
    pipeline_spec,
    stack_layer_params,
)

__all__ = ["MeshSpec", "make_mesh", "default_mesh_spec",
           "commit_replicated",
           "pipeline_apply", "pipeline_spec", "stack_layer_params",
           "moe_apply", "moe_param_spec", "init_moe_params"]

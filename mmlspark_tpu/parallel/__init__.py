"""Distributed layer: device meshes, sharding rules, and collectives.

The TPU-native replacement for the reference's two communication planes —
Spark shuffle/broadcast for data and an external MPI ring for training
(reference: cntk-train/src/main/scala/CommandBuilders.scala:60-117) —
expressed as XLA collectives over ICI/DCN via ``jax.sharding.Mesh`` +
``jit``/``shard_map``. There is no external process and no MPI: gradients
all-reduce over ICI inside the compiled step function.
"""

from mmlspark_tpu.parallel.mesh import (
    MeshSpec,
    default_mesh_spec,
    make_mesh,
)
from mmlspark_tpu.parallel.moe import (
    init_moe_params,
    moe_apply,
    moe_param_spec,
)
from mmlspark_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_spec,
    stack_layer_params,
)

__all__ = ["MeshSpec", "make_mesh", "default_mesh_spec",
           "pipeline_apply", "pipeline_spec", "stack_layer_params",
           "moe_apply", "moe_param_spec", "init_moe_params"]

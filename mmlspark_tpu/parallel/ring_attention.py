"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

**Beyond reference parity by design.** The reference's only sequence
workload pads sentences host-side to a fixed 613 tokens and feeds them one
at a time (minibatch 1) through a pretrained BiLSTM — no sequence
parallelism of any kind exists there (SURVEY §2.6/§5; reference:
notebooks/samples/304 - Medical Entity Extraction.ipynb). A TPU-native
framework must instead treat long context as a first-class axis: sequences
shard over the ``sp`` mesh axis and attention runs distributed.

Two standard strategies, both expressed as ``shard_map`` collectives so XLA
schedules them on the ICI rings:

* :func:`ring_attention` — K/V blocks rotate around the ``sp`` ring via
  ``ppermute`` while each device keeps its Q shard resident; softmax is
  accumulated online (flash-attention style running max/denominator), so
  memory stays O(L/sp) per device and compute overlaps the ring transfers.
* :func:`ulysses_attention` — ``all_to_all`` re-shards [B, L/sp, H, D] to
  [B, L, H/sp, D] (sequence → head sharding), runs ordinary local attention
  per head group, and all-to-alls back. Cheaper for moderate L when heads
  divide the axis; ring wins at very long L.

Both shard the batch dim over ``dp`` as well (each dp group computes only
its batch slice on a dp×sp mesh), and both match single-device attention
numerics — including all-zero outputs for fully-masked query rows (tests
assert this on the 8-virtual-device CPU mesh).

**Declared sharding contracts** (verified statically by
:mod:`mmlspark_tpu.analysis.spmd`, pinned against the lowered program in
tests/test_spmd.py): q/k/v ``P('dp','sp',None,None)``, mask
``P('dp','sp')``, outputs sharded like q; ring = ``ppermute(sp)`` per
hop per rotating operand, Ulysses = ``all_to_all(sp)`` ×3 in,
``all_gather(sp)`` for the mask, ``all_to_all(sp)`` back. Neither
strategy may communicate over any other axis.
"""

from __future__ import annotations

import numpy as np


def _masked_softmax(scores, jnp):
    """Softmax over the last axis where -inf marks masked entries; rows with
    ALL entries masked yield zero weights (not NaN), matching the ring
    path's guarded accumulator."""
    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(scores - m)  # exp(-inf) == 0 for masked entries
    return e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)


def _local_attention(q, k, v, scale, mask=None):
    """Plain softmax attention on local blocks: [B, Lq, H, D] x [B, Lk, H, D]."""
    import jax.numpy as jnp

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    w = _masked_softmax(scores, jnp)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def attention_reference(q, k, v, causal: bool = False, kv_mask=None):
    """Single-device reference attention (the numerics oracle).

    ``kv_mask``: [B, Lk] bool, True for real (non-pad) keys.
    """
    import jax.numpy as jnp

    scale = 1.0 / np.sqrt(q.shape[-1])
    mask = None
    if causal:
        L = q.shape[1]
        mask = jnp.tril(jnp.ones((L, L), bool))[None, None, :, :]
    if kv_mask is not None:
        key_mask = kv_mask[:, None, None, :]
        mask = key_mask if mask is None else (mask & key_mask)
    return _local_attention(q, k, v, scale, mask)


def _resolve_batch_axis(mesh, batch_axis):
    """Batch dim shards over ``batch_axis`` when the mesh has it (size-1
    axes are harmless); None disables batch sharding."""
    if batch_axis is not None and batch_axis in mesh.shape:
        return batch_axis
    return None


def _run_sharded(body, mesh, axis, batch_axis, q, k, v, kv_mask):
    """Shared tail of both strategies: build specs, commit inputs, shard_map."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    b_axis = _resolve_batch_axis(mesh, batch_axis)
    spec = P(b_axis, axis, None, None)
    mask_spec = P(b_axis, axis)
    from mmlspark_tpu.parallel.mesh import shard_map
    fn = shard_map(body, mesh=mesh,
                       in_specs=(spec, spec, spec, mask_spec),
                       out_specs=spec, check_vma=False)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    sharding = NamedSharding(mesh, spec)
    q = jax.device_put(q, sharding)
    k = jax.device_put(k, sharding)
    v = jax.device_put(v, sharding)
    kv_mask = jax.device_put(jnp.asarray(kv_mask, bool),
                             NamedSharding(mesh, mask_spec))
    return fn(q, k, v, kv_mask)


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                   kv_mask=None, batch_axis: str | None = "dp",
                   impl: str = "auto"):
    """Distributed attention over sequence shards.

    Args are *global* [B, L, H, D] arrays (or already sharded); output is
    sharded like q. L must divide by the ``axis`` size, B by the
    ``batch_axis`` size. ``kv_mask`` ([B, L] bool, True = real key) rotates
    around the ring with its K/V block so pad keys never receive attention
    weight.

    ``impl`` selects the LOCAL block's implementation — the collective
    schedule (one ``ppermute`` per hop per rotating operand) is identical
    either way. Each hop is one flash online-softmax block update
    (:func:`mmlspark_tpu.ops.pallas.attention.attention_block_update`,
    the ONE shared body): ``"xla"`` runs it vmapped under plain XLA,
    ``"pallas"`` as the fused kernel (the per-hop score block never
    leaves VMEM), ``"auto"`` = the kernel on TPU, XLA elsewhere.
    """
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.ops.pallas.attention import (
        attention_block_update, resolve_impl,
    )

    resolved = resolve_impl(impl)
    sp = mesh.shape[axis]
    scale = np.float32(1.0 / np.sqrt(q.shape[-1]))

    def body(ql, kl, vl, maskl):
        # ql/kl/vl: [B, l, H, D] local shards; online-softmax accumulation
        # while K/V blocks rotate around the ring (one hop per step)
        me = jax.lax.axis_index(axis)
        B, l, H, D = ql.shape
        acc = jnp.zeros((B, H, l, D), jnp.float32)
        denom = jnp.zeros((B, H, l, 1), jnp.float32)
        m = jnp.full((B, H, l, 1), -jnp.inf, jnp.float32)
        qf = ql.astype(jnp.float32).transpose(0, 2, 1, 3)  # [B,H,l,D]
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        kv = (kl.astype(jnp.float32), vl.astype(jnp.float32), maskl)
        for step in range(sp):
            kc, vc, mc = kv
            # K block index currently resident on this device
            kv_idx = (me - step) % sp
            keep = jnp.broadcast_to(mc[:, None, :], (B, l, l))
            if causal:
                q_pos = me * l + jnp.arange(l)[:, None]
                k_pos = kv_idx * l + jnp.arange(l)[None, :]
                keep = keep & (k_pos <= q_pos)[None]
            m, denom, acc = attention_block_update(
                qf, kc.transpose(0, 2, 1, 3), vc.transpose(0, 2, 1, 3),
                keep, m, denom, acc, scale, impl=resolved)
            if step + 1 < sp:
                kv = jax.lax.ppermute(kv, axis, perm)
        out = acc / jnp.maximum(denom, 1e-30)
        return jnp.einsum("bhqd->bqhd", out).astype(ql.dtype)

    return _run_sharded(body, mesh, axis, batch_axis, q, k, v, kv_mask)


def ulysses_attention(q, k, v, mesh, axis: str = "sp",
                      causal: bool = False, kv_mask=None,
                      batch_axis: str | None = "dp",
                      impl: str = "auto"):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Re-shards sequence → heads with one ``all_to_all``, runs full-sequence
    local attention on each head group, and re-shards back. H must divide by
    the ``axis`` size. ``kv_mask``: [B, L] bool, True = real key.

    ``impl`` selects the local attention after the re-shard (the
    collective schedule is identical either way): ``"xla"`` keeps the
    plain full-softmax path, ``"pallas"`` runs the fused flash kernel
    (:func:`mmlspark_tpu.ops.pallas.attention.flash_attention`),
    ``"auto"`` = the kernel on TPU, plain XLA elsewhere.
    """
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.ops.pallas.attention import (
        flash_attention, resolve_impl,
    )

    resolved = resolve_impl(impl)
    sp = mesh.shape[axis]
    if q.shape[2] % sp:
        raise ValueError(
            f"heads ({q.shape[2]}) must divide the {axis!r} axis ({sp})")
    scale = np.float32(1.0 / np.sqrt(q.shape[-1]))

    def body(ql, kl, vl, maskl):
        # [B, l, H, D] → all_to_all → [B, L, H/sp, D]
        def a2a(x, split, concat):
            return jax.lax.all_to_all(x, axis, split_axis=split,
                                      concat_axis=concat, tiled=True)

        qg = a2a(ql, 2, 1)
        kg = a2a(kl, 2, 1)
        vg = a2a(vl, 2, 1)
        # the mask has no head axis: gather the full [B, L] key mask
        mask_g = jax.lax.all_gather(maskl, axis, axis=1, tiled=True)
        if resolved == "pallas":
            out4 = flash_attention(
                qg.astype(jnp.float32).transpose(0, 2, 1, 3),
                kg.astype(jnp.float32).transpose(0, 2, 1, 3),
                vg.astype(jnp.float32).transpose(0, 2, 1, 3),
                kv_mask=mask_g, causal=causal, scale=scale,
                impl="pallas")
            out = out4.transpose(0, 2, 1, 3)
        else:
            mask = mask_g[:, None, None, :]
            if causal:
                L = qg.shape[1]
                mask = mask & jnp.tril(jnp.ones((L, L), bool))[None, None]
            out = _local_attention(qg.astype(jnp.float32),
                                   kg.astype(jnp.float32),
                                   vg.astype(jnp.float32), scale, mask)
        return a2a(out.astype(ql.dtype), 1, 2)

    return _run_sharded(body, mesh, axis, batch_axis, q, k, v, kv_mask)

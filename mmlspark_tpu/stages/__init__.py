"""Data-prep pipeline stages.

Analog of the reference's L4 layer: ``src/image-transformer/``,
``src/featurize/``, ``src/text-featurizer/``, ``src/clean-missing-data/``,
``src/data-conversion/``, ``src/value-indexer/``, ``src/pipeline-stages/``,
etc.
"""

"""Data-prep pipeline stages.

Analog of the reference's L4 layer: ``src/image-transformer/``,
``src/featurize/``, ``src/text-featurizer/``, ``src/clean-missing-data/``,
``src/data-conversion/``, ``src/value-indexer/``, ``src/pipeline-stages/``,
etc.
"""

from mmlspark_tpu.stages.conversion import DataConversion
from mmlspark_tpu.stages.ensemble import EnsembleByKey
from mmlspark_tpu.stages.featurize import (
    AssembleFeatures, AssembleFeaturesModel, Featurize,
)
from mmlspark_tpu.stages.image import (
    ImageSetAugmenter, ImageTransformer, UnrollImage,
)
from mmlspark_tpu.stages.indexers import (
    IndexToValue, ValueIndexer, ValueIndexerModel,
)
from mmlspark_tpu.stages.missing import (
    CleanMissingData, CleanMissingDataModel,
)
from mmlspark_tpu.stages.sampling import PartitionSample
from mmlspark_tpu.stages.summarize import SummarizeData
from mmlspark_tpu.stages.text import (
    IDF, IDFModel, HashingTF, NGram, StopWordsRemover, TextFeaturizer,
    Tokenizer,
)
from mmlspark_tpu.stages.word2vec import Word2Vec, Word2VecModel
from mmlspark_tpu.stages.utility import (
    Cacher, CheckpointData, ClassBalancer, ClassBalancerModel, DropColumns,
    MultiColumnAdapter, RenameColumns, Repartition, SelectColumns, Timer,
    TimerModel,
)

__all__ = [
    "AssembleFeatures", "AssembleFeaturesModel", "Cacher", "CheckpointData",
    "ClassBalancer", "ClassBalancerModel", "CleanMissingData",
    "CleanMissingDataModel", "DataConversion", "DropColumns", "EnsembleByKey",
    "Featurize", "HashingTF", "IDF", "IDFModel", "ImageSetAugmenter",
    "ImageTransformer", "IndexToValue", "MultiColumnAdapter", "NGram",
    "PartitionSample", "RenameColumns", "Repartition", "SelectColumns",
    "StopWordsRemover", "SummarizeData", "TextFeaturizer", "Timer",
    "TimerModel", "Tokenizer", "UnrollImage", "ValueIndexer",
    "ValueIndexerModel", "Word2Vec", "Word2VecModel",
]

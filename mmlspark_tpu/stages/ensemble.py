"""EnsembleByKey — group-by-key score averaging.

Analog of the reference's ``src/ensemble/`` (reference:
EnsembleByKey.scala:20-140): groups rows by key column(s) and replaces the
chosen score columns by their per-group mean (vector or scalar). With
``collapse_group`` the output has one row per group; otherwise the group
mean is broadcast back onto every row.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.table import DataTable, is_missing, to_py_scalar


class EnsembleByKey(Transformer):
    """Group-by-key score ensembling (mean strategy) over vector or scalar
    columns (reference: ensemble/src/main/scala/EnsembleByKey.scala:20-80)."""

    keys = Param(default=None, doc="key columns to group by",
                 type_=(list, tuple))
    cols = Param(default=None, doc="score columns to ensemble",
                 type_=(list, tuple))
    col_names = Param(default=None, doc="output names per score column",
                      type_=(list, tuple))
    strategy = Param(default="mean", doc="ensembling strategy", type_=str,
                     validator=Param.one_of("mean"))
    collapse_group = Param(default=True,
                           doc="one output row per group", type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        keys = list(self.keys or [])
        cols = list(self.cols or [])
        if not keys or not cols:
            raise ValueError("keys and cols must be set")
        names = list(self.col_names or
                     [f"{self.strategy}({c})" for c in cols])
        if len(names) != len(cols):
            raise ValueError("col_names and cols length mismatch")

        key_arrays = [table[k] for k in keys]
        group_ids: dict[tuple, int] = {}
        row_group = np.empty(len(table), dtype=np.int64)
        group_rows: list[list[int]] = []  # one grouping pass, reused below
        for i in range(len(table)):
            # missing keys normalize to None so all NaN/null rows form ONE
            # group (Spark groupBy null semantics); NaN != NaN would
            # otherwise give every missing-key row its own group
            key = tuple(None if is_missing(a[i]) else to_py_scalar(a[i])
                        for a in key_arrays)
            g = group_ids.setdefault(key, len(group_ids))
            if g == len(group_rows):
                group_rows.append([])
            group_rows[g].append(i)
            row_group[i] = g
        n_groups = len(group_ids)
        group_idx = [np.asarray(rows, dtype=np.intp) for rows in group_rows]

        # per-group means; vector cells stack into a matrix mean
        means: dict[str, list[Any]] = {}
        for col in cols:
            data = table[col]
            is_vec = data.dtype == object
            acc: list[Any] = []
            for idx in group_idx:
                if is_vec:
                    acc.append(np.mean(
                        np.stack([np.asarray(data[i], dtype=np.float64)
                                  for i in idx]), axis=0))
                else:
                    acc.append(float(np.mean(data[idx].astype(np.float64))))
            means[col] = acc

        if self.collapse_group:
            out_cols: dict[str, Any] = {}
            first_row = np.asarray([idx[0] for idx in group_idx],
                                   dtype=np.intp)
            for k, arr in zip(keys, key_arrays):
                out_cols[k] = arr[first_row]
            for col, name in zip(cols, names):
                out_cols[name] = means[col]
            return DataTable(out_cols, {k: table.column_meta(k)
                                        for k in keys if table.column_meta(k)})

        out = table
        for col, name in zip(cols, names):
            vals = [means[col][g] for g in row_group]
            out = out.with_column(name, vals)
        return out

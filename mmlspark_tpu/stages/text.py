"""Text featurization: Tokenizer → StopWordsRemover → NGram → HashingTF → IDF.

Analog of the reference's ``src/text-featurizer/`` (reference:
TextFeaturizer.scala:18-280), which composes SparkML feature stages into a
param-gated pipeline. Here each sub-stage is its own vectorized transformer
(so they are also usable standalone, as the reference's core/ml tests use
Spark's) and :class:`TextFeaturizer` is the estimator that wires them by
flags.

TPU-first notes: hashing uses a stable CRC32 (process-independent, so fitted
models round-trip), term frequencies land in a **dense float32 matrix** of
``num_features`` slots — dense rows feed the MXU directly; use
AssembleFeatures' non-zero slot selection to keep dims small rather than
sparse vectors.
"""

from __future__ import annotations

import re
import zlib
from typing import Any, Iterable, Sequence

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core.schema import SchemaConstants
from mmlspark_tpu.core.stage import (
    Estimator, HasInputCol, HasOutputCol, Transformer, UnaryTransformer,
)
from mmlspark_tpu.data.table import DataTable, is_missing

# A compact English stop-word list (SparkML ships per-language lists; the
# "english" default is what the reference's defaultStopWordLanguage uses).
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are as at be because been
before being below between both but by could did do does doing down during
each few for from further had has have having he her here hers herself him
himself his how i if in into is it its itself just me more most my myself no
nor not now of off on once only or other our ours ourselves out over own same
she should so some such than that the their theirs them themselves then there
these they this those through to too under until up very was we were what when
where which while who whom why will with you your yours yourself yourselves
""".split())


def hash_term(term: str, num_features: int) -> int:
    """Stable term → slot index (HashingTF analog; CRC32 instead of murmur3
    — both are uniform enough and CRC32 is C-speed in the stdlib)."""
    return zlib.crc32(term.encode("utf-8")) % num_features


class Tokenizer(UnaryTransformer):
    """Regex tokenizer: splits on gaps or matches tokens
    (RegexTokenizer analog)."""

    gaps = Param(default=True, doc="regex splits on gaps (true) or matches "
                 "tokens (false)", type_=bool)
    pattern = Param(default=r"\s+", doc="delimiter (gaps) or token pattern",
                    type_=str)
    to_lowercase = Param(default=True, doc="lowercase before tokenizing",
                         type_=bool)
    min_token_length = Param(default=1, doc="minimum token length",
                             type_=int, validator=Param.ge(0))

    def _tokenize_one(self, text: Any, rx: re.Pattern) -> list[str]:
        s = "" if is_missing(text) else str(text)
        if self.to_lowercase:
            s = s.lower()
        toks = rx.split(s) if self.gaps else rx.findall(s)
        return [t for t in toks if len(t) >= self.min_token_length]

    def _transform_column(self, values: np.ndarray, table: DataTable) -> Any:
        rx = re.compile(self.pattern)
        return [self._tokenize_one(v, rx) for v in values]


class StopWordsRemover(UnaryTransformer):
    """Filters stop words from token lists (built-in English list by
    default); part of the TextFeaturizer chain (reference:
    text-featurizer/src/main/scala/TextFeaturizer.scala)."""

    stop_words = Param(default=None, doc="words to filter out (None = "
                       "built-in English list)", type_=(list, tuple))
    case_sensitive = Param(default=False, doc="case-sensitive comparison",
                           type_=bool)

    def _transform_column(self, values: np.ndarray, table: DataTable) -> Any:
        words = (set(self.stop_words) if self.stop_words is not None
                 else set(ENGLISH_STOP_WORDS))
        if not self.case_sensitive:
            words = {w.lower() for w in words}
            return [[t for t in toks if t.lower() not in words]
                    for toks in values]
        return [[t for t in toks if t not in words] for toks in values]


class NGram(UnaryTransformer):
    """Token lists → space-joined n-grams (TextFeaturizer chain)."""

    n = Param(default=2, doc="n-gram length", type_=int,
              validator=Param.gt(0))

    def _transform_column(self, values: np.ndarray, table: DataTable) -> Any:
        n = self.n
        return [[" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]
                for toks in values]


class HashingTF(UnaryTransformer):
    """Token list → dense term-frequency row of ``num_features`` slots."""

    num_features = Param(default=1 << 12, doc="number of hash buckets",
                         type_=int, validator=Param.gt(0))
    binary = Param(default=False, doc="clip all counts to 1", type_=bool)

    def _transform_column(self, values: np.ndarray, table: DataTable) -> Any:
        n = self.num_features
        out = np.zeros((len(values), n), dtype=np.float32)
        for i, toks in enumerate(values):
            for t in toks:
                out[i, hash_term(t, n)] += 1.0
        if self.binary:
            np.minimum(out, 1.0, out=out)
        return out

    def transform(self, table: DataTable) -> DataTable:
        mat = self._transform_column(table[self.input_col], table)
        out = table.with_column(self.output_col, mat)
        return out.with_meta(
            self.output_col,
            **{SchemaConstants.K_VECTOR_SIZE: self.num_features})


class IDF(Estimator, HasInputCol, HasOutputCol):
    """Inverse-document-frequency scaling over a TF vector column.

    Uses Spark's formula idf = log((m + 1) / (df + 1)).
    """

    min_doc_freq = Param(default=0, doc="minimum number of documents a term "
                         "must appear in", type_=int, validator=Param.ge(0))

    def fit(self, table: DataTable) -> "IDFModel":
        tf = table.column_matrix(self.input_col, dtype=np.float64)
        m = tf.shape[0]
        df = (tf > 0).sum(axis=0)
        idf = np.log((m + 1.0) / (df + 1.0))
        if self.min_doc_freq > 0:
            idf = np.where(df >= self.min_doc_freq, idf, 0.0)
        return IDFModel(input_col=self.input_col, output_col=self.output_col,
                        idf=idf.astype(np.float32))


class IDFModel(Transformer, HasInputCol, HasOutputCol):
    """Fitted :class:`IDF`: rescales term-frequency vectors by the learned
    inverse-document-frequency weights."""

    idf = Param(default=None, doc="per-slot idf weights", is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        tf = table.column_matrix(self.input_col, dtype=np.float32)
        out = table.with_column(self.output_col, tf * self.idf[None, :])
        return out.with_meta(
            self.output_col,
            **{SchemaConstants.K_VECTOR_SIZE: int(len(self.idf))})


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """One-call text → feature-vector pipeline, param-gated like the
    reference (reference: TextFeaturizer.scala:183-280)."""

    use_tokenizer = Param(default=True, doc="tokenize the input", type_=bool)
    tokenizer_gaps = Param(default=True, doc="regex splits on gaps", type_=bool)
    tokenizer_pattern = Param(default=r"\s+", doc="tokenizer regex", type_=str)
    to_lowercase = Param(default=True, doc="lowercase first", type_=bool)
    min_token_length = Param(default=1, doc="min token length", type_=int)
    use_stop_words_remover = Param(default=False, doc="remove stop words",
                                   type_=bool)
    case_sensitive_stop_words = Param(default=False,
                                      doc="case-sensitive stop words",
                                      type_=bool)
    stop_words = Param(default=None, doc="custom stop words",
                       type_=(list, tuple))
    use_ngram = Param(default=False, doc="enumerate n-grams", type_=bool)
    ngram_length = Param(default=2, doc="n-gram length", type_=int)
    binary = Param(default=False, doc="clip term counts to 1", type_=bool)
    num_features = Param(default=1 << 12, doc="hash buckets", type_=int)
    use_idf = Param(default=True, doc="scale TF by IDF", type_=bool)
    min_doc_freq = Param(default=1, doc="min document frequency", type_=int)

    def fit(self, table: DataTable) -> PipelineModel:
        col, out = self.input_col, self.output_col
        # intermediate names must not collide with existing user columns
        # (they would be overwritten and then dropped)
        intermediates: list[str] = []

        def fresh(base: str) -> str:
            name = base
            i = 1
            while name in table or name in intermediates or name == out:
                name = f"{base}_{i}"
                i += 1
            intermediates.append(name)
            return name

        stages: list = []
        cur = col
        if self.use_tokenizer:
            nxt = fresh("__tokens")
            stages.append(Tokenizer(
                input_col=cur, output_col=nxt,
                gaps=self.tokenizer_gaps, pattern=self.tokenizer_pattern,
                to_lowercase=self.to_lowercase,
                min_token_length=self.min_token_length))
            cur = nxt
        if self.use_stop_words_remover:
            nxt = fresh("__nostop")
            stages.append(StopWordsRemover(
                input_col=cur, output_col=nxt,
                stop_words=list(self.stop_words) if self.stop_words else None,
                case_sensitive=self.case_sensitive_stop_words))
            cur = nxt
        if self.use_ngram:
            nxt = fresh("__ngrams")
            stages.append(NGram(input_col=cur, output_col=nxt,
                                n=self.ngram_length))
            cur = nxt
        tf_out = fresh("__tf") if self.use_idf else out
        stages.append(HashingTF(input_col=cur, output_col=tf_out,
                                num_features=self.num_features,
                                binary=self.binary))
        if self.use_idf:
            stages.append(IDF(input_col=tf_out, output_col=out,
                              min_doc_freq=self.min_doc_freq))
        model = Pipeline(stages).fit(table)
        return PipelineModel(stages=list(model.stages) +
                             [_DropIfPresent(cols=intermediates)])


class _DropIfPresent(Transformer):
    cols = Param(default=None, doc="columns to drop when present",
                 type_=(list, tuple))

    def transform(self, table: DataTable) -> DataTable:
        present = [c for c in (self.cols or []) if c in table]
        return table.drop(*present) if present else table

"""DataConversion — column type conversion transformer.

Analog of the reference's ``src/data-conversion/`` (reference:
DataConversion.scala:17-130): converts a set of columns to a target type —
boolean/int/long/float/double/string/date — or to/from categorical codes
(``toCategorical`` delegates to :class:`ValueIndexer`, ``clearCategorical``
to :class:`IndexToValue`).
"""

from __future__ import annotations

from datetime import datetime
from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.table import DataTable, is_missing

CONVERSIONS = ("boolean", "byte", "short", "integer", "long", "float",
               "double", "string", "date", "toCategorical",
               "clearCategorical")

_NUMPY_TARGETS = {
    "boolean": np.bool_, "byte": np.int8, "short": np.int16,
    "integer": np.int32, "long": np.int64, "float": np.float32,
    "double": np.float64,
}


def _to_date(v: Any, fmt: str) -> Any:
    if is_missing(v):
        return None
    if isinstance(v, datetime):
        return v
    if isinstance(v, (int, float, np.number)):
        return datetime.fromtimestamp(float(v))
    return datetime.strptime(str(v), fmt)


class DataConversion(Transformer):
    """Converts columns between numeric/string/boolean/date types, with
    categorical conversion via ValueIndexer semantics (reference:
    data-conversion/src/main/scala/DataConversion.scala:17-60)."""

    cols = Param(default=None, doc="columns to convert",
                 type_=(list, tuple))
    convert_to = Param(default="double", doc="target type",
                       type_=str, validator=Param.one_of(*CONVERSIONS))
    date_time_format = Param(default="%Y-%m-%d %H:%M:%S",
                             doc="strptime format for date conversion",
                             type_=str)

    def transform(self, table: DataTable) -> DataTable:
        target = self.convert_to
        out = table
        for col in (self.cols or []):
            if target == "toCategorical":
                from mmlspark_tpu.stages.indexers import ValueIndexer
                model = ValueIndexer(input_col=col, output_col=col).fit(out)
                out = model.transform(out)
            elif target == "clearCategorical":
                from mmlspark_tpu.core.schema import SchemaConstants
                from mmlspark_tpu.stages.indexers import IndexToValue
                out = IndexToValue(input_col=col, output_col=col).transform(out)
                stale = {SchemaConstants.K_CATEGORICAL_LEVELS,
                         SchemaConstants.K_IS_CATEGORICAL}
                out.meta[col] = {k: v for k, v in out.column_meta(col).items()
                                 if k not in stale}
            elif target == "date":
                fmt = self.date_time_format
                out = out.with_column(
                    col, [_to_date(v, fmt) for v in out[col]])
            elif target == "string":
                out = out.with_column(
                    col, [None if is_missing(v) else str(v)
                          for v in out[col]])
            else:
                dtype = _NUMPY_TARGETS[target]
                src = out[col]
                if src.dtype == object:
                    first = next((v for v in src if not is_missing(v)), None)
                    if isinstance(first, datetime):
                        vals = [np.nan if is_missing(v) else v.timestamp()
                                for v in src]
                    else:
                        vals = [np.nan if is_missing(v) else float(v)
                                for v in src]
                    src = np.asarray(vals, dtype=np.float64)
                # numpy int/bool cannot represent missing — casting NaN would
                # silently write INT_MIN garbage, so fail loudly instead
                if (not np.issubdtype(dtype, np.floating)
                        and np.issubdtype(src.dtype, np.floating)
                        and np.isnan(src).any()):
                    raise ValueError(
                        f"column {col!r} has missing values; impute "
                        f"(CleanMissingData) before converting to {target}")
                out = out.with_column(col, src.astype(dtype))
        return out

"""SummarizeData — per-column dataset profiling.

Analog of the reference's ``src/summarize-data/`` (reference:
SummarizeData.scala:17-220): one output row per input column with four
toggleable statistic groups — counts (count, unique, missing), basic
(numeric count, mean, stddev, min, max), sample (variance, skewness,
kurtosis), percentiles (0.5/1/5/10/25/50/75/90/95/99/99.5%).

All statistics are exact vectorized NumPy (the reference trades exactness
for approx distinct/quantiles on Spark).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.table import DataTable, is_missing

PERCENTILE_LEVELS = (0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95,
                     0.99, 0.995)


def _numeric_or_none(col: np.ndarray) -> np.ndarray | None:
    """Non-missing numeric values of a column, or None if non-numeric."""
    if col.dtype != object:
        if not np.issubdtype(col.dtype, np.number):
            return None
        vals = col.astype(np.float64)
        return vals[~np.isnan(vals)]
    out = []
    for v in col:
        if is_missing(v):
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float, np.number)):
            return None
        out.append(float(v))
    return np.asarray(out, dtype=np.float64)


class SummarizeData(Transformer):
    """Dataset profiling: counts, basic stats, sample stats, and percentiles
    per column (reference: summarize-data/src/main/scala/SummarizeData.scala:17-130)."""

    counts = Param(default=True, doc="compute count statistics", type_=bool)
    basic = Param(default=True, doc="compute basic statistics", type_=bool)
    sample = Param(default=True, doc="compute sample statistics", type_=bool)
    percentiles = Param(default=True, doc="compute percentiles", type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        rows: list[dict[str, Any]] = []
        n = len(table)
        for name in table.columns:
            col = table[name]
            row: dict[str, Any] = {"Feature": name}
            nums = _numeric_or_none(col)
            if self.counts:
                if col.dtype == object:
                    missing = sum(1 for v in col if is_missing(v))
                    hashable = all(
                        not isinstance(v, (dict, list, np.ndarray))
                        for v in col)
                    # distinct over non-missing values only (countDistinct
                    # semantics, matching the float branch below)
                    uniq = (len({v for v in col if not is_missing(v)})
                            if hashable else None)
                elif np.issubdtype(col.dtype, np.floating):
                    missing = int(np.isnan(col).sum())
                    uniq = len(np.unique(col[~np.isnan(col)]))
                else:
                    missing = 0
                    uniq = len(np.unique(col))
                row["count"] = n
                row["unique_value_count"] = uniq
                row["missing_value_count"] = missing
            if self.basic:
                has = nums is not None and len(nums) > 0
                row["numeric_count"] = len(nums) if nums is not None else 0
                row["mean"] = float(np.mean(nums)) if has else None
                row["stddev"] = (float(np.std(nums, ddof=1))
                                 if has and len(nums) > 1 else None)
                row["min"] = float(np.min(nums)) if has else None
                row["max"] = float(np.max(nums)) if has else None
            if self.sample:
                has = nums is not None and len(nums) > 1
                if has:
                    mean = np.mean(nums)
                    sd = np.std(nums)
                    var = float(np.var(nums, ddof=1))
                    if sd > 0:
                        z = (nums - mean) / sd
                        skew = float(np.mean(z ** 3))
                        kurt = float(np.mean(z ** 4) - 3.0)
                    else:
                        skew = kurt = 0.0
                    row["sample_variance"] = var
                    row["sample_skewness"] = skew
                    row["sample_kurtosis"] = kurt
                else:
                    row["sample_variance"] = None
                    row["sample_skewness"] = None
                    row["sample_kurtosis"] = None
            if self.percentiles:
                has = nums is not None and len(nums) > 0
                for p in PERCENTILE_LEVELS:
                    key = f"quantile_{p}"
                    row[key] = (float(np.quantile(nums, p)) if has else None)
            rows.append(row)
        return DataTable.from_rows(rows)

"""PartitionSample — head / random sample / assign-to-partition.

Analog of the reference's ``src/partition-sample/`` (reference:
PartitionSample.scala:13-180): three modes —

* ``Head``: first ``count`` rows,
* ``RandomSample``: seeded random subset, absolute ``count`` or ``percent``,
* ``AssignToPartition``: adds a seeded random partition-id column.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.table import DataTable

MODE_HEAD = "Head"
MODE_RS = "RandomSample"
MODE_ATP = "AssignToPartition"
RS_ABSOLUTE = "Absolute"
RS_PERCENT = "Percentage"


class PartitionSample(Transformer):
    """Sampling/partition assignment: Head, RandomSample (absolute or
    percentage), or AssignToPartition (reference:
    partition-sample/src/main/scala/PartitionSample.scala:13-120)."""

    mode = Param(default=MODE_RS, doc="sampling mode", type_=str,
                 validator=Param.one_of(MODE_HEAD, MODE_RS, MODE_ATP))
    rs_mode = Param(default=RS_PERCENT, doc="random-sample submode",
                    type_=str, validator=Param.one_of(RS_ABSOLUTE, RS_PERCENT))
    seed = Param(default=-1, doc="seed for random ops (-1 = nondeterministic)",
                 type_=int)
    percent = Param(default=0.01, doc="fraction of rows to keep", type_=float,
                    validator=Param.in_range(0.0, 1.0))
    count = Param(default=1000, doc="number of rows (Head / Absolute)",
                  type_=int, validator=Param.ge(0))
    new_col_name = Param(default="Partition", doc="partition-id column name",
                         type_=str)
    num_parts = Param(default=10, doc="number of partitions for "
                      "AssignToPartition", type_=int, validator=Param.gt(0))

    def _rng(self) -> np.random.Generator:
        seed = self.seed
        return np.random.default_rng(None if seed < 0 else seed)

    def transform(self, table: DataTable) -> DataTable:
        mode = self.mode
        if mode == MODE_HEAD:
            return table.head(self.count)
        if mode == MODE_RS:
            n = len(table)
            if self.rs_mode == RS_ABSOLUTE:
                k = min(self.count, n)
            else:
                k = int(round(self.percent * n))
            idx = np.sort(self._rng().choice(n, size=k, replace=False))
            return table.take(idx)
        # AssignToPartition
        parts = self._rng().integers(0, self.num_parts, size=len(table))
        return table.with_column(self.new_col_name, parts.astype(np.int32))

    def infer_schema(self, schema):
        if self.mode == MODE_ATP:
            from mmlspark_tpu.analysis.info import ColumnInfo
            out = schema.copy()
            out.columns[self.new_col_name] = ColumnInfo.scalar("int32")
            return out
        return schema.copy()

    def infer_rows(self, n, schema):
        if n is None or self.mode == MODE_ATP:
            return n
        if self.mode == MODE_HEAD:
            return min(self.count, n)
        if self.rs_mode == RS_ABSOLUTE:
            return min(self.count, n)
        return int(round(self.percent * n))

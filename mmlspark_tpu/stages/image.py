"""Image pipeline stages: ImageTransformer (op-list), UnrollImage,
ImageSetAugmenter.

Analog of the reference's ``src/image-transformer/`` (reference:
ImageTransformer.scala:21-360, UnrollImage.scala:18-42,
image-featurizer ImageSetAugmenter.scala:38-61). The reference applies
OpenCV ``Mat`` ops row-by-row in executor UDFs; here ops run on decoded
HWC uint8 arrays via the native C++ extension (resize/unroll) or OpenCV,
threaded across rows — and the unroll/normalize hot path also has a batched
device-side variant used by ImageFeaturizer.

Supported ops match the reference stage list: resize, crop, color_format,
flip, blur, threshold, gaussian_kernel.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from mmlspark_tpu.core import config
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import (
    is_image_column, make_image, mark_image_column,
)
from mmlspark_tpu.core.stage import (
    ArrayMeta, DeviceOp, DeviceStage, HasInputCol, HasOutputCol, Transformer,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.native import imgops


# ---- op implementations: (array HWC uint8, params) -> array ----

def _op_resize(img: np.ndarray, p: Mapping) -> np.ndarray:
    return imgops.resize(img, int(p["height"]), int(p["width"]))


def _op_crop(img: np.ndarray, p: Mapping) -> np.ndarray:
    x, y = int(p.get("x", 0)), int(p.get("y", 0))
    h, w = int(p["height"]), int(p["width"])
    if y + h > img.shape[0] or x + w > img.shape[1]:
        raise ValueError(
            f"crop ({y}:{y+h}, {x}:{x+w}) outside image {img.shape[:2]}")
    return img[y:y + h, x:x + w]


# format name → cv2 conversion-code attribute; the name set is shared with
# schema inference so bad formats are rejected pre-flight, not per-row
_COLOR_FORMAT_CODES = {
    "gray": "COLOR_BGR2GRAY", "grey": "COLOR_BGR2GRAY",
    "rgb": "COLOR_BGR2RGB", "hsv": "COLOR_BGR2HSV",
    "luv": "COLOR_BGR2LUV", "lab": "COLOR_BGR2LAB",
    "yuv": "COLOR_BGR2YUV",
}


def _op_color_format(img: np.ndarray, p: Mapping) -> np.ndarray:
    import cv2
    fmt = p["format"]
    if fmt not in _COLOR_FORMAT_CODES:
        raise ValueError(f"unknown color format {fmt!r}; "
                         f"one of {sorted(_COLOR_FORMAT_CODES)}")
    out = cv2.cvtColor(img, getattr(cv2, _COLOR_FORMAT_CODES[fmt]))
    return out if out.ndim == 3 else out[:, :, None]


def _op_flip(img: np.ndarray, p: Mapping) -> np.ndarray:
    # flip_code semantics match OpenCV: 1 = horizontal (left-right),
    # 0 = vertical (up-down), -1 = both
    code = int(p.get("flip_code", 1))
    if code == 1:
        return img[:, ::-1]
    if code == 0:
        return img[::-1]
    return img[::-1, ::-1]


def _op_blur(img: np.ndarray, p: Mapping) -> np.ndarray:
    import cv2
    return cv2.blur(img, (int(p["height"]), int(p["width"])))


def _op_threshold(img: np.ndarray, p: Mapping) -> np.ndarray:
    import cv2
    _, out = cv2.threshold(img, float(p["threshold"]), float(p["max_val"]),
                           getattr(cv2, "THRESH_" +
                                   p.get("type", "binary").upper()))
    return out if out.ndim == 3 else out[:, :, None]


def _op_gaussian_kernel(img: np.ndarray, p: Mapping) -> np.ndarray:
    import cv2
    k = int(p["aperture_size"])
    return cv2.GaussianBlur(img, (k, k), float(p.get("sigma", 0)))


OPS: dict[str, Callable[[np.ndarray, Mapping], np.ndarray]] = {
    "resize": _op_resize,
    "crop": _op_crop,
    "color_format": _op_color_format,
    "flip": _op_flip,
    "blur": _op_blur,
    "threshold": _op_threshold,
    "gaussian_kernel": _op_gaussian_kernel,
}


# ---- device-side op builders (the DeviceStage path): each mirrors the
#      host op's math exactly so fused output matches the per-row path ----

# ops with a device implementation; any other op in the list declines
# device_fn and the whole stage runs on host
DEVICE_OPS = frozenset({"resize", "crop", "flip"})


def _device_resize_step(h: int, w: int, oh: int, ow: int):
    """Batched align-corners bilinear resize matching imgops.cpp
    ``img_resize_bilinear`` tap-for-tap: same f32 coordinate math, same
    left-associated blend order, same +0.5 truncating uint8 round — so
    device output tracks the native host path to within ±1 count (the only
    slack is compiler fma/rounding on knife-edge halves)."""
    # f32/f32 division, matching the C++'s float arithmetic exactly
    # (a python-double division rounded to f32 can differ by one ulp)
    sy = (np.float32(h - 1) / np.float32(oh - 1)) if oh > 1 else np.float32(0)
    sx = (np.float32(w - 1) / np.float32(ow - 1)) if ow > 1 else np.float32(0)
    fy = np.arange(oh, dtype=np.float32) * sy
    fx = np.arange(ow, dtype=np.float32) * sx
    y0 = fy.astype(np.int32)
    x0 = fx.astype(np.int32)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (fy - y0).reshape(1, oh, 1, 1)
    wx = (fx - x0).reshape(1, 1, ow, 1)

    def step(img):
        import jax.numpy as jnp
        rows0 = jnp.take(img, y0, axis=1)
        rows1 = jnp.take(img, y1, axis=1)
        v00 = jnp.take(rows0, x0, axis=2).astype(jnp.float32)
        v01 = jnp.take(rows0, x1, axis=2).astype(jnp.float32)
        v10 = jnp.take(rows1, x0, axis=2).astype(jnp.float32)
        v11 = jnp.take(rows1, x1, axis=2).astype(jnp.float32)
        v = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
             + v10 * wy * (1 - wx) + v11 * wy * wx)
        return (v + np.float32(0.5)).astype(jnp.uint8)

    return step


def _device_flip_step(code: int):
    def step(img):
        if code == 1:
            return img[:, :, ::-1]
        if code == 0:
            return img[:, ::-1]
        return img[:, ::-1, ::-1]

    return step


def _device_crop_step(x: int, y: int, ch: int, cw: int):
    def step(img):
        return img[:, y:y + ch, x:x + cw]

    return step


class ImageTransformer(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """Applies an ordered list of image ops per row.

    Ops are dicts: ``{"op": "resize", "height": 32, "width": 32}``.
    Accepts image-struct columns or raw encoded bytes (decode-if-binary,
    reference: ImageTransformer.scala:233-250).
    """

    input_col = Param(default="image", doc="input image column", type_=str)
    output_col = Param(default="image", doc="output image column", type_=str)
    ops = Param(default=None, doc="ordered list of image op dicts",
                type_=(list, tuple))

    # chainable builders (mirror of the reference's setter DSL)
    def _add(self, **op: Any) -> "ImageTransformer":
        self.set(ops=(list(self.ops or []) + [op]))
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(op="crop", x=x, y=y, height=height, width=width)

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add(op="color_format", format=format)

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add(op="flip", flip_code=flip_code)

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="blur", height=height, width=width)

    def threshold(self, threshold: float, max_val: float,
                  type: str = "binary") -> "ImageTransformer":
        return self._add(op="threshold", threshold=threshold,
                         max_val=max_val, type=type)

    def gaussian_kernel(self, aperture_size: int,
                        sigma: float = 0.0) -> "ImageTransformer":
        return self._add(op="gaussian_kernel", aperture_size=aperture_size,
                         sigma=sigma)

    def _process_one(self, value: Any) -> dict | None:
        if value is None:
            return None
        if isinstance(value, dict):
            img = np.asarray(value["data"])
            path = value.get("path", "")
        elif isinstance(value, (bytes, bytearray)):
            from mmlspark_tpu.data.readers import decode_image
            img = decode_image(bytes(value))
            path = ""
            if img is None:
                return None
        else:
            img = np.asarray(value, dtype=np.uint8)
            path = ""
        for op in self.ops or []:
            img = OPS[op["op"]](img, op)
        return make_image(path, img)

    def transform(self, table: DataTable) -> DataTable:
        for op in self.ops or []:
            if op.get("op") not in OPS:
                raise ValueError(f"unknown image op {op.get('op')!r}; "
                                 f"available: {sorted(OPS)}")
        col = table[self.input_col]
        # the native/OpenCV ops release the GIL, so a thread pool gives real
        # host parallelism — the Spark-partition-parallelism analog the
        # per-row loop was missing (reference gets this free from executors,
        # ImageTransformer.scala:329-360)
        threads = int(config.get("image_threads"))
        if len(col) > 1 and threads > 1:
            with ThreadPoolExecutor(max_workers=threads) as pool:
                out = list(pool.map(self._process_one, col))
        else:
            out = [self._process_one(v) for v in col]
        table = table.with_column(self.output_col, out)
        return mark_image_column(table, self.output_col)

    # ---- static schema inference ----

    def infer_schema(self, schema: Any) -> Any:
        """Replay the op list over the abstract image geometry: resize and
        crop rewrite (h, w), color_format rewrites channels, and an
        out-of-bounds crop or unknown op is rejected here instead of as a
        per-row error mid-transform."""
        from mmlspark_tpu.analysis.info import (
            KIND_IMAGE, ColumnInfo, SchemaError, require_image_input,
        )
        out = schema.copy()
        info = require_image_input(out, self.input_col, "ImageTransformer")
        shape = info.shape if info.kind == KIND_IMAGE and info.shape else \
            (None, None, None)
        h, w, c = (tuple(shape) + (None,) * 3)[:3]
        for op in self.ops or []:
            kind = op.get("op")
            if kind not in OPS:
                raise SchemaError(
                    "unknown-image-op",
                    f"unknown image op {kind!r}; available: {sorted(OPS)}")
            if kind == "resize":
                h, w = int(op["height"]), int(op["width"])
            elif kind == "crop":
                x, y = int(op.get("x", 0)), int(op.get("y", 0))
                ch, cw = int(op["height"]), int(op["width"])
                if (h is not None and y + ch > h) or \
                        (w is not None and x + cw > w):
                    raise SchemaError(
                        "crop-out-of-bounds",
                        f"crop ({y}:{y + ch}, {x}:{x + cw}) falls outside "
                        f"the incoming image geometry ({h}x{w})")
                h, w = ch, cw
            elif kind == "color_format":
                fmt = op.get("format")
                if fmt not in _COLOR_FORMAT_CODES:
                    raise SchemaError(
                        "unknown-color-format",
                        f"unknown color format {fmt!r}; one of "
                        f"{sorted(_COLOR_FORMAT_CODES)}")
                c = 1 if fmt in ("gray", "grey") else c
        out.columns[self.output_col] = ColumnInfo.image(
            h, w, c, has_missing=info.has_missing)
        return out

    # ---- DeviceStage protocol ----

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        """Batched device variant of the op list. Only uint8 HWC stacks and
        the pure-indexing/arithmetic ops (resize/crop/flip) qualify — the
        OpenCV-backed ops (color_format/blur/threshold/gaussian) keep the
        host path. A crop outside the image also declines, so the host path
        raises its canonical per-row error."""
        if not meta.is_image or meta.dtype != "uint8" or len(meta.shape) != 3:
            return None
        h, w, c = meta.shape
        steps = []
        for op in self.ops or []:
            kind = op.get("op")
            if kind not in DEVICE_OPS:
                return None
            if kind == "resize":
                oh, ow = int(op["height"]), int(op["width"])
                steps.append(_device_resize_step(h, w, oh, ow))
                h, w = oh, ow
            elif kind == "crop":
                x, y = int(op.get("x", 0)), int(op.get("y", 0))
                ch, cw = int(op["height"]), int(op["width"])
                if y + ch > h or x + cw > w:
                    return None
                steps.append(_device_crop_step(x, y, ch, cw))
                h, w = ch, cw
            else:  # flip
                steps.append(_device_flip_step(int(op.get("flip_code", 1))))

        def fn(params, img):
            for step in steps:
                img = step(img)
            return img

        return DeviceOp(fn, ArrayMeta((h, w, c), "uint8", is_image=True))

    def device_emit(self, table: DataTable, values: Any, meta: ArrayMeta,
                    ctx: dict) -> DataTable:
        paths = ctx.get("paths") or [""] * len(values)
        out = [make_image(p, v) for p, v in zip(paths, values)]
        table = table.with_column(self.output_col, out)
        return mark_image_column(table, self.output_col)


class UnrollImage(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """Image struct → flat CHW float vector (native C++ pack).

    Reference: UnrollImage.scala:18-42 loops per pixel in Scala to build a
    CHW Double DenseVector; here it's one native (or vectorized) pass per
    image emitting float32.
    """

    input_col = Param(default="image", doc="input image column", type_=str)
    output_col = Param(default="features", doc="output vector column",
                       type_=str)
    scale = Param(default=1.0, doc="multiply pixels by this", type_=float)
    offset = Param(default=0.0, doc="then add this", type_=float)
    to_rgb = Param(default=False, doc="swap BGR→RGB while unrolling",
                   type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        col = table[self.input_col]
        datas = [None if v is None else np.asarray(v["data"]) for v in col]
        # grayscale (H,W) rows get the channel axis here, exactly as
        # imgops.unroll does per row
        datas = [d[:, :, None] if d is not None and d.ndim == 2 else d
                 for d in datas]
        shapes = {d.shape for d in datas if d is not None}
        if len(shapes) == 1 and all(d is not None for d in datas):
            # uniform-shape fast path: ONE native pass over the whole stack
            # ([N,H,W,C] uint8 → [N,C,H,W] f32) instead of N python calls
            out = imgops.unroll_batch(np.stack(datas), to_rgb=self.to_rgb,
                                      scale=self.scale, offset=self.offset)
            vecs: list = list(out.reshape(len(datas), -1))
        else:
            def one(d):
                if d is None:
                    return None
                return imgops.unroll(d, to_rgb=self.to_rgb, scale=self.scale,
                                     offset=self.offset).reshape(-1)
            threads = int(config.get("image_threads"))
            if len(datas) > 1 and threads > 1:
                with ThreadPoolExecutor(max_workers=threads) as pool:
                    vecs = list(pool.map(one, datas))
            else:
                vecs = [one(d) for d in datas]
        return table.with_column(self.output_col, vecs)

    # ---- static schema inference ----

    def infer_schema(self, schema: Any) -> Any:
        from mmlspark_tpu.analysis.info import (
            KIND_IMAGE, ColumnInfo, require_image_input,
        )
        out = schema.copy()
        info = require_image_input(out, self.input_col, "UnrollImage")
        size = None
        if info.kind == KIND_IMAGE:
            s = info.concrete_shape
            if s is not None:
                size = int(np.prod(s))
        out.columns[self.output_col] = ColumnInfo.vector(
            size, "float32", has_missing=info.has_missing)
        return out

    # ---- DeviceStage protocol ----

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        """Device unroll: the exact per-pixel ``float(px) * scale + offset``
        of imgops.cpp ``img_unroll`` on the transposed CHW view, batched."""
        if not meta.is_image or len(meta.shape) != 3:
            return None
        h, w, c = meta.shape
        scale = np.float32(self.scale)
        offset = np.float32(self.offset)
        to_rgb = bool(self.to_rgb) and c == 3

        def fn(params, x):
            import jax.numpy as jnp
            xf = x.astype(jnp.float32)
            if to_rgb:
                xf = xf[..., ::-1]
            chw = jnp.transpose(xf, (0, 3, 1, 2))
            return (chw * scale + offset).reshape(x.shape[0], c * h * w)

        return DeviceOp(fn, ArrayMeta((c * h * w,), "float32"))


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by unioning flipped copies.

    Reference: ImageSetAugmenter.scala:38-61 — emits the original rows plus
    left-right (and optionally up-down) flipped copies. For training loops
    prefer :func:`mmlspark_tpu.ops.augment_batch` — the same augmentations
    applied INSIDE the compiled step on device, with per-sample randomness
    and no dataset copies.
    """

    input_col = Param(default="image", doc="input image column", type_=str)
    output_col = Param(default="image", doc="output image column", type_=str)
    flip_left_right = Param(default=True, doc="add LR-flipped copies",
                            type_=bool)
    flip_up_down = Param(default=False, doc="add UD-flipped copies",
                         type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        base = table.with_column(self.output_col, table[self.input_col])
        base = mark_image_column(base, self.output_col)
        result = base
        flips = []
        if self.flip_left_right:
            flips.append(1)
        if self.flip_up_down:
            flips.append(0)
        for code in flips:
            t = ImageTransformer(input_col=self.input_col,
                                 output_col=self.output_col).flip(code)
            result = result.concat(t.transform(table))
        return result

    def infer_schema(self, schema: Any) -> Any:
        from mmlspark_tpu.analysis.info import require_image_input
        out = schema.copy()
        info = require_image_input(out, self.input_col, "ImageSetAugmenter")
        aug = info.copy()
        from mmlspark_tpu.core.schema import SchemaConstants
        aug.meta[SchemaConstants.K_IMAGE] = True
        out.columns[self.output_col] = aug
        return out

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        if n is None:
            return None
        copies = 1 + int(bool(self.flip_left_right)) \
            + int(bool(self.flip_up_down))
        return n * copies

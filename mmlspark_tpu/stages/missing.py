"""CleanMissingData — imputation estimator (Mean / Median / Custom).

Analog of the reference's ``src/clean-missing-data/`` (reference:
CleanMissingData.scala:14-160): per-column replacement values are computed at
fit time; Mean/Median support numeric columns only, Custom additionally
supports strings/bools. Missing = None or NaN.

Replacements are computed with vectorized ``np.nanmean``/``np.nanmedian``
(the reference uses Spark aggregate jobs / approx quantiles).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import (
    Estimator, HasInputCols, HasOutputCols, Transformer,
)
from mmlspark_tpu.data.table import DataTable, is_missing

MEAN = "Mean"
MEDIAN = "Median"
CUSTOM = "Custom"
MODES = (MEAN, MEDIAN, CUSTOM)


def _numeric_view(col: np.ndarray) -> np.ndarray:
    """Column as float64 with missing → NaN; raises for non-numeric."""
    if col.dtype != object:
        if not np.issubdtype(col.dtype, np.number):
            raise TypeError("only numeric types supported for numeric "
                            f"imputation, got {col.dtype}")
        return col.astype(np.float64)
    out = np.empty(len(col), dtype=np.float64)
    for i, v in enumerate(col):
        if is_missing(v):
            out[i] = np.nan
        elif isinstance(v, (int, float, np.number)) and not isinstance(v, bool):
            out[i] = float(v)
        else:
            raise TypeError("only numeric types supported for numeric "
                            f"imputation, got {type(v).__name__}")
    return out


class CleanMissingData(Estimator, HasInputCols, HasOutputCols):
    """Imputation estimator: Mean/Median/Custom replacement per column
    (reference: clean-missing-data/src/main/scala/CleanMissingData.scala:14-80)."""

    cleaning_mode = Param(default=MEAN, doc="imputation mode",
                          type_=str, validator=Param.one_of(*MODES))
    custom_value = Param(default=None, doc="replacement value for Custom mode")

    def fit(self, table: DataTable) -> "CleanMissingDataModel":
        in_cols = list(self.input_cols or [])
        out_cols = list(self.output_cols or in_cols)
        if len(in_cols) != len(out_cols):
            raise ValueError("input_cols and output_cols length mismatch")
        mode = self.cleaning_mode
        repl: dict[str, Any] = {}
        for col in in_cols:
            if mode == CUSTOM:
                if self.custom_value is None:
                    raise ValueError("Custom mode requires custom_value")
                v = self.custom_value
                # numeric columns get the value coerced (reference stores
                # customValue as string and casts to the column type)
                arr = table[col]
                if arr.dtype != object and np.issubdtype(arr.dtype, np.number):
                    v = float(v)
                repl[col] = v
            else:
                vals = _numeric_view(table[col])
                if np.all(np.isnan(vals)):
                    raise ValueError(f"column {col!r} has no non-missing "
                                     "values to impute from")
                repl[col] = float(np.nanmean(vals) if mode == MEAN
                                  else np.nanmedian(vals))
        return CleanMissingDataModel(
            input_cols=in_cols, output_cols=out_cols,
            replacement_values=repl)


class CleanMissingDataModel(Transformer, HasInputCols, HasOutputCols):
    """Fitted :class:`CleanMissingData`: fills missing values with the
    per-column replacements computed at fit time."""

    replacement_values = Param(default=None,
                               doc="per-input-column replacement value",
                               type_=dict)

    def transform(self, table: DataTable) -> DataTable:
        out = table
        for in_col, out_col in zip(self.input_cols, self.output_cols):
            col = table[in_col]
            repl = self.replacement_values[in_col]
            if col.dtype == object:
                filled = [repl if is_missing(v) else v for v in col]
                out = out.with_column(out_col, filled)
            elif np.issubdtype(col.dtype, np.floating):
                out = out.with_column(
                    out_col, np.where(np.isnan(col), repl, col))
            else:  # integer/bool columns cannot hold NaN — copy through
                out = out.with_column(out_col, col.copy())
        return out

"""ValueIndexer / IndexToValue — categorical indexing for any value type.

Analog of the reference's ``src/value-indexer/`` (reference:
ValueIndexer.scala:63-120, IndexToValue.scala:26-46): a StringIndexer
generalized to int/long/double/string/bool columns, whose fitted levels are
stored in the column's sidecar metadata (the Spark column-metadata analog,
see :mod:`mmlspark_tpu.core.schema`), with an inverse transform reading the
levels back from metadata.

TPU-first notes: indexing is a vectorized ``np.searchsorted`` over sorted
levels (O(n log k) with no per-row Python), and the produced int32 codes are
directly usable as embedding/one-hot indices in device batches.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import SchemaConstants, set_categorical_levels
from mmlspark_tpu.core.stage import (
    Estimator, HasInputCol, HasOutputCol, Transformer,
)
from mmlspark_tpu.data.table import DataTable, is_missing, to_py_scalar


def sorted_levels(values: np.ndarray) -> list:
    """Distinct values sorted ascending, None/NaN first (NullOrdering analog,
    reference: ValueIndexer.scala:37-48)."""
    has_null = False
    distinct: set = set()
    for v in values:
        if is_missing(v):
            has_null = True
        else:
            distinct.add(to_py_scalar(v))
    out = sorted(distinct)
    return ([None] + out) if has_null else out


def index_values(values: np.ndarray, levels: list) -> np.ndarray:
    """Vectorized value→code lookup; unseen values map to -1."""
    null_offset = 1 if (levels and levels[0] is None) else 0
    core = levels[null_offset:]
    n = len(values)
    codes = np.full(n, -1, dtype=np.int32)
    null_mask = np.fromiter(
        (is_missing(v) for v in values), dtype=bool, count=n)
    if null_offset:
        codes[null_mask] = 0
    if core:
        arr = np.asarray([v for v, m in zip(values, null_mask) if not m])
        if len(arr):
            key = np.asarray(core)
            pos = np.searchsorted(key, arr)
            pos = np.clip(pos, 0, len(core) - 1)
            found = key[pos] == arr
            filled = np.where(found, pos + null_offset, -1).astype(np.int32)
            codes[~null_mask] = filled
    return codes


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fits the sorted dictionary of distinct values of the input column.

    The model converts the column to int32 categorical codes and stamps the
    levels into the output column's metadata.
    """

    def fit(self, table: DataTable) -> "ValueIndexerModel":
        levels = sorted_levels(table[self.input_col])
        return ValueIndexerModel(
            input_col=self.input_col, output_col=self.output_col,
            levels=levels)

    def infer_schema(self, schema: Any) -> Any:
        schema = super().infer_schema(schema)
        from mmlspark_tpu.analysis.info import ColumnInfo
        # levels are a fit-time artifact; the output is provably int32
        # categorical codes either way
        info = ColumnInfo.scalar("int32")
        info.meta[SchemaConstants.K_IS_CATEGORICAL] = True
        schema.columns[self.output_col] = info
        return schema


class ValueIndexerModel(Transformer, HasInputCol, HasOutputCol):
    """Fitted :class:`ValueIndexer`: maps values to level codes and stamps
    categorical-levels column metadata (reference:
    value-indexer/src/main/scala/ValueIndexer.scala)."""

    levels = Param(default=None, doc="sorted categorical levels",
                   type_=(list, tuple))

    def transform(self, table: DataTable) -> DataTable:
        codes = index_values(table[self.input_col], list(self.levels))
        out = table.with_column(self.output_col, codes)
        return set_categorical_levels(out, self.output_col, list(self.levels))

    def infer_schema(self, schema: Any) -> Any:
        schema = super().infer_schema(schema)
        from mmlspark_tpu.analysis.info import ColumnInfo
        info = ColumnInfo.scalar("int32")
        info.meta[SchemaConstants.K_IS_CATEGORICAL] = True
        info.meta[SchemaConstants.K_CATEGORICAL_LEVELS] = list(
            self.levels or [])
        schema.columns[self.output_col] = info
        return schema


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse of ValueIndexerModel: codes → original values, levels read
    from the input column's metadata (reference: IndexToValue.scala:26-46)."""

    def transform(self, table: DataTable) -> DataTable:
        meta = table.column_meta(self.input_col)
        levels = meta.get(SchemaConstants.K_CATEGORICAL_LEVELS)
        if levels is None:
            raise ValueError(
                f"column {self.input_col!r} carries no categorical levels; "
                "run ValueIndexer first")
        codes = np.asarray(table[self.input_col], dtype=np.int64)
        values = [levels[c] if 0 <= c < len(levels) else None for c in codes]
        return table.with_column(self.output_col, values)

    def infer_schema(self, schema: Any) -> Any:
        from mmlspark_tpu.analysis.info import ColumnInfo, SchemaError
        out = schema.copy()
        info = out.get(self.input_col)
        if info is None:
            if schema.exact:
                raise SchemaError(
                    "missing-input-column",
                    f"IndexToValue reads missing column "
                    f"{self.input_col!r}; available: {list(schema)}")
            info = ColumnInfo.unknown()
        levels = info.meta.get(SchemaConstants.K_CATEGORICAL_LEVELS)
        if (levels is None and info.kind != "unknown"
                and not info.meta.get(SchemaConstants.K_IS_CATEGORICAL)):
            # flagged-categorical without levels is fine: an unfitted
            # ValueIndexer upstream stamps the flag, the levels are a
            # fit-time artifact
            raise SchemaError(
                "categorical-levels-missing",
                f"column {self.input_col!r} carries no categorical levels "
                "in its metadata; run ValueIndexer first")
        out.columns[self.output_col] = ColumnInfo.unknown()
        return out

"""Featurize / AssembleFeatures — automatic featurization of mixed-type
tables into a single dense feature-vector column.

Analog of the reference's ``src/featurize/`` (reference:
Featurize.scala:82-98, AssembleFeatures.scala:152-459): per-column type
dispatch at fit time —

* numeric → float64 (rows with missing values dropped at transform, matching
  the reference's ``na.drop`` at AssembleFeatures.scala:419-420),
* categorical (indexed, levels in metadata) → one-hot (drop-last, Spark
  OneHotEncoder semantics) or raw code,
* string → tokenize + stable-hash term frequencies with **count-based slot
  selection**: only hash slots that were non-zero on the fit data are kept
  (the BitSet-reduce analog, AssembleFeatures.scala:232-258) — this is also
  what makes the output *dense-friendly for the MXU*: a 2^18 hash space
  collapses to the observed vocabulary size,
* date/datetime → [epoch_ms, year, day-of-week, month, day(, hour, minute,
  second)] (AssembleFeatures.scala:371-400),
* vector columns → appended as-is,
* image columns → [height, width, CHW pixel values] when ``allow_images``
  (AssembleFeatures.scala:401-410).

Column order in the assembled vector puts categoricals first (the
FastVectorAssembler contract, reference:
core/spark/src/main/scala/FastVectorAssembler.scala:23-40).

The assembled column is a 2-D float32 matrix ready for
``DataTable.column_matrix`` → one contiguous host→device transfer.
"""

from __future__ import annotations

from datetime import datetime
from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.pipeline import Pipeline, PipelineModel
from mmlspark_tpu.core.schema import (
    SchemaConstants, get_categorical_levels, is_image_column,
)
from mmlspark_tpu.core.stage import (
    ArrayMeta, DeviceOp, DeviceStage, Estimator, HasFeaturesCol, Transformer,
)
from mmlspark_tpu.data.table import DataTable, is_missing
from mmlspark_tpu.stages.text import Tokenizer, hash_term

# 2^18 hash slots by default; 2^12 for tree/NN learners
# (reference: Featurize.scala:13-19)
NUM_FEATURES_DEFAULT = 1 << 18
NUM_FEATURES_TREE_OR_NN = 1 << 12

_KIND_NUMERIC = "numeric"
_KIND_CATEGORICAL = "categorical"
_KIND_STRING = "string"
_KIND_DATE = "date"
_KIND_VECTOR = "vector"
_KIND_TOKENS = "tokens"      # pre-tokenized text: list-of-str rows
_KIND_IMAGE = "image"
_KIND_BOOL = "bool"


def _classify_column(table: DataTable, col: str) -> str:
    if get_categorical_levels(table, col) is not None:
        return _KIND_CATEGORICAL
    if is_image_column(table, col):
        return _KIND_IMAGE
    arr = table[col]
    if arr.dtype != object:
        if arr.dtype == np.bool_:
            return _KIND_BOOL
        if np.issubdtype(arr.dtype, np.number):
            return _KIND_NUMERIC
        raise TypeError(f"unsupported dtype for assembly: {arr.dtype}")
    first = next((v for v in arr if not is_missing(v)), None)
    if first is None:
        return _KIND_NUMERIC  # all-missing: treat as numeric NaNs
    if isinstance(first, str):
        return _KIND_STRING
    if isinstance(first, datetime):
        return _KIND_DATE
    if isinstance(first, (np.ndarray, list, tuple)):
        # a sequence of strings is pre-tokenized text, not a numeric vector
        # (the fuzz suite feeds both; misclassifying crashes at transform)
        probe = next((v for v in arr
                      if not is_missing(v) and len(v) > 0), None)
        if probe is not None and isinstance(probe[0], str):
            return _KIND_TOKENS
        return _KIND_VECTOR
    if isinstance(first, dict):
        return _KIND_IMAGE
    if isinstance(first, bool):
        return _KIND_BOOL
    if isinstance(first, (int, float, np.number)):
        return _KIND_NUMERIC
    raise TypeError(f"unsupported type for assembly: {type(first).__name__}")


def _date_features(v: Any) -> np.ndarray:
    if is_missing(v):
        return np.full(8, np.nan)
    ts = v.timestamp() * 1000.0
    return np.array([ts, v.year, v.isoweekday(), v.month, v.day,
                     v.hour, v.minute, v.second], dtype=np.float64)


def _token_lists(values: Any) -> list[list[str]]:
    """Token-list column → clean list-of-str rows (missing → empty)."""
    return [[] if is_missing(v) else [str(t) for t in v] for v in values]


def _hash_rows(token_lists: list[list[str]], num_features: int) -> list[dict[int, float]]:
    """Sparse per-row term-frequency dicts (slot → count)."""
    out = []
    for toks in token_lists:
        d: dict[int, float] = {}
        for t in toks:
            slot = hash_term(t, num_features)
            d[slot] = d.get(slot, 0.0) + 1.0
        out.append(d)
    return out


class AssembleFeatures(Estimator, HasFeaturesCol):
    """Fits the per-column featurization plan and the hashed-slot selection."""

    columns_to_featurize = Param(default=None, doc="input columns",
                                 type_=(list, tuple))
    number_of_features = Param(default=NUM_FEATURES_DEFAULT,
                               doc="hash space for string columns",
                               type_=int, validator=Param.gt(0))
    one_hot_encode_categoricals = Param(default=True,
                                        doc="one-hot categorical columns",
                                        type_=bool)
    allow_images = Param(default=False, doc="allow image featurization",
                         type_=bool)

    def fit(self, table: DataTable) -> "AssembleFeaturesModel":
        cols = list(self.columns_to_featurize or table.columns)
        plan: list[dict[str, Any]] = []
        # categoricals first (FastVectorAssembler contract)
        classified = [(c, _classify_column(table, c)) for c in cols]
        classified.sort(key=lambda ck: 0 if ck[1] == _KIND_CATEGORICAL else 1)
        text_cols = [(c, k) for c, k in classified
                     if k in (_KIND_STRING, _KIND_TOKENS)]

        # count-based slot selection across all string/token columns together
        # (the reference hashes all tokenized string cols into one space and
        # reduces a BitSet of non-zero slots)
        selected_slots: list[int] = []
        if text_cols:
            tokenizer = Tokenizer(input_col="x", output_col="y")
            nonzero: set[int] = set()
            for c, k in text_cols:
                toks = (tokenizer._transform_column(table[c], None)
                        if k == _KIND_STRING else _token_lists(table[c]))
                for d in _hash_rows(toks, self.number_of_features):
                    nonzero.update(d)
            selected_slots = sorted(nonzero)

        for c, kind in classified:
            entry: dict[str, Any] = {"col": c, "kind": kind}
            if kind == _KIND_CATEGORICAL:
                entry["levels"] = get_categorical_levels(table, c)
                entry["one_hot"] = bool(self.one_hot_encode_categoricals)
            elif kind == _KIND_IMAGE and not self.allow_images:
                raise ValueError(
                    "featurization of image columns disabled; set "
                    "allow_images=True")
            elif kind == _KIND_VECTOR:
                first = next((v for v in table[c] if not is_missing(v)), [])
                entry["size"] = int(np.asarray(first).size)
            plan.append(entry)

        return AssembleFeaturesModel(
            features_col=self.features_col, plan=plan,
            number_of_features=self.number_of_features,
            selected_slots=selected_slots)

    def infer_schema(self, schema: Any) -> Any:
        """Pre-fit contract check: every column to featurize must exist and
        image columns need ``allow_images``. The assembled width is
        computed when provable (no text columns — slot selection is a
        fit-time artifact)."""
        from mmlspark_tpu.analysis import info as ai
        out = schema.copy()
        cols = list(self.columns_to_featurize or schema.columns)
        width: int | None = 0
        for c in cols:
            if c not in out.columns:
                if schema.exact:
                    raise ai.SchemaError(
                        "missing-input-column",
                        f"AssembleFeatures featurizes missing column "
                        f"{c!r}; available: {list(schema)}")
                width = None
                continue
            ci = out.columns[c]
            if ci.kind == ai.KIND_IMAGE and not self.allow_images:
                raise ai.SchemaError(
                    "images-not-allowed",
                    f"column {c!r} is an image column but allow_images is "
                    "False — this assembly is vector-only; set "
                    "allow_images=True or unroll/featurize the images "
                    "first")
            w = _abstract_block_width(ci, bool(
                self.one_hot_encode_categoricals))
            width = None if (width is None or w is None) else width + w
        out.columns[self.features_col] = ai.ColumnInfo.vector(
            width, "float32")
        if width is not None:
            out.columns[self.features_col].meta[
                SchemaConstants.K_VECTOR_SIZE] = int(width)
        return out


def _abstract_block_width(ci: Any, one_hot: bool) -> int | None:
    """Width one column contributes to the assembled vector, from its
    abstract info; None when not statically provable."""
    from mmlspark_tpu.analysis import info as ai
    if ci.meta.get(SchemaConstants.K_IS_CATEGORICAL):
        levels = ci.meta.get(SchemaConstants.K_CATEGORICAL_LEVELS)
        if levels is None:
            # categorical with fit-time levels (an unfitted ValueIndexer
            # upstream): the one-hot width is not provable yet
            return None
        return (len(levels) - 1) if one_hot else 1
    if ci.kind == ai.KIND_SCALAR:
        return 1
    if ci.kind == ai.KIND_DATE:
        return 8
    if ci.kind == ai.KIND_VECTOR:
        return ci.row_size
    if ci.kind == ai.KIND_IMAGE:
        s = ci.concrete_shape
        return None if s is None else 2 + int(np.prod(s))
    return None  # text/tokens (fit-time slots), object, unknown


class AssembleFeaturesModel(Transformer, DeviceStage, HasFeaturesCol):
    """Fitted :class:`AssembleFeatures`: applies the per-column featurization
    plan and assembles one features vector (reference:
    featurize/src/main/scala/AssembleFeatures.scala:338-459)."""

    plan = Param(default=None, doc="per-column featurization plan",
                 is_complex=True)
    number_of_features = Param(default=NUM_FEATURES_DEFAULT,
                               doc="hash space for string columns", type_=int)
    selected_slots = Param(default=None, doc="kept hash slots (sorted)",
                           is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        n = len(table)
        blocks: list[np.ndarray] = []
        clean_mask = np.ones(n, dtype=bool)  # rows to keep (na.drop analog)
        text_cols: list[tuple[str, str]] = []

        for entry in self.plan:
            c, kind = entry["col"], entry["kind"]
            if kind == _KIND_CATEGORICAL:
                codes = np.asarray(table[c], dtype=np.int64)
                levels = entry["levels"]
                k = len(levels)
                if entry.get("one_hot", True):
                    # Spark OneHotEncoder drops the last category; a
                    # single-level column contributes zero slots
                    width = k - 1
                    block = np.zeros((n, width), dtype=np.float64)
                    valid = (codes >= 0) & (codes < width)
                    block[np.arange(n)[valid], codes[valid]] = 1.0
                else:
                    block = codes.astype(np.float64)[:, None]
                blocks.append(block)
            elif kind in (_KIND_NUMERIC, _KIND_BOOL):
                arr = table[c]
                if arr.dtype == object:
                    vals = np.array(
                        [np.nan if is_missing(v) else float(v)
                         for v in arr], dtype=np.float64)
                else:
                    vals = arr.astype(np.float64)
                clean_mask &= ~np.isnan(vals)
                blocks.append(vals[:, None])
            elif kind == _KIND_DATE:
                mat = np.stack([_date_features(v) for v in table[c]])
                clean_mask &= ~np.isnan(mat).any(axis=1)
                blocks.append(mat)
            elif kind == _KIND_VECTOR:
                size = entry.get("size", 0)
                mat = np.full((n, size), np.nan)
                for i, v in enumerate(table[c]):
                    if not is_missing(v):
                        mat[i] = np.asarray(v, dtype=np.float64).reshape(-1)
                clean_mask &= ~np.isnan(mat).any(axis=1)
                blocks.append(mat)
            elif kind == _KIND_IMAGE:
                rows: list[np.ndarray | None] = []
                width = None
                for i, v in enumerate(table[c]):
                    if is_missing(v):
                        clean_mask[i] = False
                        rows.append(None)
                        continue
                    img = np.asarray(v["data"], dtype=np.float64)
                    row = np.concatenate([[float(v["height"]),
                                           float(v["width"])],
                                          img.reshape(-1)])
                    if width is None:
                        width = len(row)
                    elif len(row) != width:
                        raise ValueError(
                            f"image column {c!r} row {i} unrolls to "
                            f"{len(row)} values, expected {width}; resize "
                            "images to a common shape first")
                    rows.append(row)
                mat = np.zeros((n, width or 0), dtype=np.float64)
                for i, row in enumerate(rows):
                    if row is not None:
                        mat[i] = row
                blocks.append(mat)
            elif kind in (_KIND_STRING, _KIND_TOKENS):
                text_cols.append((c, kind))
            else:
                raise TypeError(f"unknown plan kind {kind!r}")

        if text_cols:
            slots = list(self.selected_slots or [])
            slot_pos = {s: i for i, s in enumerate(slots)}
            tf = np.zeros((n, len(slots)), dtype=np.float64)
            tokenizer = Tokenizer(input_col="x", output_col="y")
            for c, kind in text_cols:
                toks = (tokenizer._transform_column(table[c], None)
                        if kind == _KIND_STRING else _token_lists(table[c]))
                for i, d in enumerate(_hash_rows(toks,
                                                 self.number_of_features)):
                    for s, cnt in d.items():
                        pos = slot_pos.get(s)
                        if pos is not None:
                            tf[i, pos] += cnt
            blocks.append(tf)

        features = (np.concatenate(blocks, axis=1) if blocks
                    else np.zeros((n, 0)))
        features = features.astype(np.float32)
        out = table
        if not clean_mask.all():
            out = out.take(clean_mask)
            features = features[clean_mask]
        out = out.with_column(self.features_col, features)
        return out.with_meta(
            self.features_col,
            **{SchemaConstants.K_VECTOR_SIZE: int(features.shape[1])})

    # ---- static schema inference ----

    def infer_schema(self, schema: Any) -> Any:
        """Check the fitted plan still matches the incoming schema: every
        planned column present and of the planned kind (an image column
        reaching a numeric/vector slot is the image-vs-vector confusion),
        categorical levels unchanged since fit (silent mis-encoding
        otherwise), and the assembled width computed exactly."""
        from mmlspark_tpu.analysis import info as ai
        out = schema.copy()
        width: int | None = 0
        text_counted = False
        for entry in self.plan or []:
            c, kind = entry["col"], entry["kind"]
            ci = out.get(c)
            if ci is None:
                if schema.exact:
                    raise ai.SchemaError(
                        "missing-input-column",
                        f"featurization plan reads missing column {c!r}; "
                        f"available: {list(schema)}")
                width = None
                continue
            w: int | None
            if kind == _KIND_CATEGORICAL:
                levels = entry.get("levels") or []
                seen = ci.meta.get(SchemaConstants.K_CATEGORICAL_LEVELS)
                if seen is not None and list(seen) != list(levels):
                    out.warn(
                        "categorical-level-drift",
                        f"column {c!r} was fitted with levels "
                        f"{levels!r:.80} but now carries {seen!r:.80}; "
                        "codes will be mis-encoded silently")
                w = (len(levels) - 1) if entry.get("one_hot", True) else 1
            elif kind == _KIND_IMAGE:
                if ci.kind not in (ai.KIND_IMAGE, ai.KIND_OBJECT,
                                   ai.KIND_UNKNOWN):
                    raise ai.SchemaError(
                        "plan-schema-mismatch",
                        f"featurization plan expects column {c!r} to be an "
                        f"image column but it is now {ci.kind}")
                s = ci.concrete_shape
                w = None if s is None else 2 + int(np.prod(s))
            elif kind in (_KIND_STRING, _KIND_TOKENS):
                # every text column hashes into ONE shared slot block
                w = 0 if text_counted else len(self.selected_slots or [])
                text_counted = True
            elif kind == _KIND_DATE:
                w = 8
            elif kind == _KIND_VECTOR:
                if ci.kind == ai.KIND_IMAGE:
                    raise ai.SchemaError(
                        "plan-schema-mismatch",
                        f"featurization plan expects column {c!r} as a "
                        "numeric vector but it is now an image column — "
                        "vector-only assembly cannot consume images")
                w = entry.get("size")
            else:  # numeric / bool
                if ci.kind == ai.KIND_IMAGE:
                    raise ai.SchemaError(
                        "plan-schema-mismatch",
                        f"featurization plan expects column {c!r} as "
                        f"{kind} but it is now an image column — "
                        "vector-only assembly cannot consume images")
                w = 1
            width = None if (width is None or w is None) else width + w
        info = ai.ColumnInfo.vector(width, "float32")
        if width is not None:
            info.meta[SchemaConstants.K_VECTOR_SIZE] = int(width)
        out.columns[self.features_col] = info
        return out

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        # the na.drop analog removes rows with missing values in any
        # featurized column; when missing rows are possible the output
        # count is unknowable statically
        if n is None:
            return None
        for entry in self.plan or []:
            ci = schema.get(entry["col"])
            if ci is not None and ci.has_missing:
                return None
        return n

    # ---- DeviceStage protocol: the numeric image assembly as a fused op.
    #      Only the single-image-column plan qualifies — it is the one
    #      assembly whose math is integer-exact (uint8 pixels represent
    #      exactly in f32) and whose na.drop mask is statically empty (the
    #      planner's entry coercion already rejects missing rows), so the
    #      fused output is bit-for-bit the host output. Mixed plans (NaN
    #      row-dropping, hashing, one-hot) keep the host path. ----

    def device_input_col(self) -> str | None:
        plan = self.plan or []
        if len(plan) == 1 and plan[0]["kind"] == _KIND_IMAGE:
            return plan[0]["col"]
        return None

    def device_output_col(self) -> str | None:
        return self.features_col

    def device_cache_token(self) -> Any:
        return (id(self.plan), id(self.selected_slots),
                self.number_of_features, self.features_col)

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        if self.device_input_col() is None or not meta.is_image \
                or len(meta.shape) != 3:
            return None
        h, w, c = meta.shape

        def fn(params, x):
            import jax.numpy as jnp
            # [height, width, HWC pixel values] — the transform() image
            # row layout, batched (f64→f32 of uint8 is exact, so computing
            # in f32 directly matches the host's f64-then-f32 cast)
            flat = x.astype(jnp.float32).reshape(x.shape[0], h * w * c)
            hw = jnp.broadcast_to(
                jnp.asarray([float(h), float(w)], jnp.float32),
                (x.shape[0], 2))
            return jnp.concatenate([hw, flat], axis=1)

        return DeviceOp(fn, ArrayMeta((2 + h * w * c,), "float32"))

    def device_emit(self, table: DataTable, values: Any, meta: ArrayMeta,
                    ctx: dict) -> DataTable:
        out = table.with_column(self.features_col, values)
        return out.with_meta(
            self.features_col,
            **{SchemaConstants.K_VECTOR_SIZE: int(values.shape[1])})


class Featurize(Estimator):
    """One estimator per output feature column, each assembling a set of
    input columns (reference: Featurize.scala:82-98)."""

    feature_columns = Param(default=None,
                            doc="output column → list of input columns",
                            type_=dict)
    number_of_features = Param(default=NUM_FEATURES_DEFAULT,
                               doc="hash space for string columns", type_=int)
    one_hot_encode_categoricals = Param(default=True,
                                        doc="one-hot categoricals",
                                        type_=bool)
    allow_images = Param(default=False, doc="allow image featurization",
                         type_=bool)

    def fit(self, table: DataTable) -> PipelineModel:
        fc = self.feature_columns or {"features": list(table.columns)}
        stages = [
            AssembleFeatures(
                features_col=out_col,
                columns_to_featurize=list(in_cols),
                number_of_features=self.number_of_features,
                one_hot_encode_categoricals=self.one_hot_encode_categoricals,
                allow_images=self.allow_images)
            for out_col, in_cols in fc.items()]
        return Pipeline(stages).fit(table)

"""Word2Vec — skip-gram embeddings trained on-device.

Analog of Spark ML's ``Word2Vec`` as the reference uses it (notebook
``202 - Amazon Book Reviews - Word2Vec``; spec'd by the reference's own
Word2VecSpec, core/ml/src/test/scala/Word2VecSpec.scala): fit learns one
vector per vocabulary word from token lists; transform averages a row's
word vectors into a single feature vector; ``find_synonyms`` returns
cosine neighbors.

TPU-first redesign (Spark trains Hogwild-style on partitioned skip-grams):

* training is skip-gram with negative sampling as ONE jit-compiled step —
  embedding gathers, batched dot products, and the sigmoid losses all fuse
  on device; fixed-shape batches (padded tail with a 0-weight mask) mean
  exactly one compiled program,
* negatives are drawn inside the step from a per-step folded PRNG key (no
  host RNG in the hot loop),
* the (center, context) pair walk is built host-side once, vectorized.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger, timed
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, HasInputCol, HasOutputCol, \
    Transformer
from mmlspark_tpu.data.table import DataTable, is_missing

_log = get_logger(__name__)


def _build_vocab(rows: Sequence, min_count: int,
                 max_vocab: int | None) -> tuple[list[str], np.ndarray]:
    """Vocabulary (frequent-first) plus index-aligned corpus counts."""
    counts: dict[str, int] = {}
    for toks in rows:
        if is_missing(toks):
            continue
        for t in toks:
            counts[t] = counts.get(t, 0) + 1
    vocab = [w for w, c in counts.items() if c >= min_count]
    vocab.sort(key=lambda w: (-counts[w], w))  # frequent first, stable
    if max_vocab is not None:
        vocab = vocab[:max_vocab]
    return vocab, np.asarray([counts[w] for w in vocab], np.float64)


def _skipgram_pairs(rows: Sequence, index: dict[str, int], window: int,
                    seed: int) -> np.ndarray:
    """All (center, context) id pairs within the window, as int32 [N, 2]."""
    rng = np.random.default_rng(seed)
    centers, contexts = [], []
    for toks in rows:
        if is_missing(toks):
            continue
        ids = np.asarray([index[t] for t in toks if t in index],
                         dtype=np.int32)
        n = len(ids)
        if n < 2:
            continue
        # per-center random effective window (word2vec's distance weighting)
        for off in range(1, window + 1):
            keep = rng.random(max(n - off, 0)) < (1.0 - (off - 1) / window)
            a, b = ids[:-off][keep], ids[off:][keep]
            centers.append(a)
            contexts.append(b)
            centers.append(b)  # symmetric
            contexts.append(a)
    if not centers:
        return np.zeros((0, 2), np.int32)
    return np.stack([np.concatenate(centers),
                     np.concatenate(contexts)], axis=1)


class Word2Vec(Estimator, HasInputCol, HasOutputCol):
    """Learns word embeddings from a token-list column (skip-gram + negative
    sampling, jit-compiled); the fitted model averages word vectors per row
    (Spark ``Word2Vec`` semantics, reference notebook 202)."""

    input_col = Param(default="tokens", doc="token-list input column",
                      type_=str)
    output_col = Param(default="features", doc="mean-vector output column",
                       type_=str)
    vector_size = Param(default=64, doc="embedding dimension", type_=int,
                        validator=Param.gt(0))
    window = Param(default=5, doc="max context window", type_=int,
                   validator=Param.gt(0))
    min_count = Param(default=2, doc="minimum token frequency", type_=int)
    max_vocab = Param(default=None, doc="cap on vocabulary size", type_=int,
                      validator=Param.gt(0))
    negatives = Param(default=5, doc="negative samples per pair", type_=int,
                      validator=Param.gt(0))
    epochs = Param(default=5, doc="passes over the skip-gram pairs",
                   type_=int, validator=Param.gt(0))
    batch_size = Param(default=2048, doc="pairs per device step", type_=int,
                       validator=Param.gt(0))
    learning_rate = Param(default=0.025, doc="adam learning rate",
                          type_=float)
    seed = Param(default=42, doc="seed", type_=int)

    def fit(self, table: DataTable) -> "Word2VecModel":
        import jax
        import jax.numpy as jnp
        import optax

        rows = table[self.input_col]
        vocab, counts = _build_vocab(rows, self.min_count, self.max_vocab)
        if not vocab:
            raise ValueError(
                f"Word2Vec: no token appears >= min_count={self.min_count} "
                f"times in column {self.input_col!r}")
        index = {w: i for i, w in enumerate(vocab)}
        pairs = _skipgram_pairs(rows, index, self.window, self.seed)
        v, d = len(vocab), self.vector_size

        key = jax.random.PRNGKey(self.seed)
        k_in, k_train = jax.random.split(key)
        params = {
            "in": jax.random.uniform(k_in, (v, d), jnp.float32,
                                     -0.5 / d, 0.5 / d),
            "out": jnp.zeros((v, d), jnp.float32),
        }
        tx = optax.adam(self.learning_rate)
        opt = tx.init(params)
        neg = self.negatives
        # negatives follow the unigram^0.75 distribution (word2vec's noise
        # distribution, same as Spark's Word2Vec) — host-built CDF once,
        # device-sampled via searchsorted on uniform draws
        noise = counts ** 0.75
        noise_cdf = jnp.asarray(np.cumsum(noise) / noise.sum(), jnp.float32)

        def step(params, opt, centers, contexts, w, key):
            def loss_fn(p):
                ci = p["in"][centers]                    # [B, D]
                co = p["out"][contexts]                  # [B, D]
                pos = jax.nn.log_sigmoid(
                    jnp.sum(ci * co, axis=-1))           # [B]
                u = jax.random.uniform(key, (centers.shape[0], neg))
                nids = jnp.searchsorted(noise_cdf, u).astype(jnp.int32)
                nids = jnp.minimum(nids, v - 1)
                nv = p["out"][nids]                      # [B, neg, D]
                # a negative that collides with the true context would push
                # the pair apart with one hand while pos pulls it together
                # with the other — zero those terms out
                ok = (nids != contexts[:, None]).astype(jnp.float32)
                negl = (jax.nn.log_sigmoid(
                    -jnp.einsum("bd,bnd->bn", ci, nv)) * ok).sum(axis=-1)
                per = -(pos + negl)
                return (per * w).sum() / jnp.maximum(w.sum(), 1.0)

            l, g = jax.value_and_grad(loss_fn)(params)
            up, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, up), opt, l

        jstep = jax.jit(step, donate_argnums=(0, 1))

        if len(pairs) == 0:
            # degenerate corpus (e.g. one-word sentences): nothing to
            # train on; the init vectors still give a valid, loadable model
            _log.warning("Word2Vec: no skip-gram pairs (window=%d) — "
                         "returning untrained vectors", self.window)
            return Word2VecModel(
                input_col=self.input_col, output_col=self.output_col,
                vocab=list(vocab),
                vectors=np.asarray(params["in"], np.float32))

        bs = min(self.batch_size, len(pairs))
        rng = np.random.default_rng(self.seed)
        losses = []
        with timed(f"Word2Vec[{v} words, {len(pairs)} pairs]", _log,
                   len(table)):
            step_i = 0
            for epoch in range(self.epochs):
                order = rng.permutation(len(pairs))
                for s in range(0, len(pairs), bs):
                    idx = order[s:s + bs]
                    cen = pairs[idx, 0]
                    ctx = pairs[idx, 1]
                    w = np.ones(bs, np.float32)
                    if len(idx) < bs:   # pad tail to the fixed shape
                        pad = bs - len(idx)
                        cen = np.concatenate([cen, np.zeros(pad, np.int32)])
                        ctx = np.concatenate([ctx, np.zeros(pad, np.int32)])
                        w[len(idx):] = 0.0
                    params, opt, l = jstep(
                        params, opt, jnp.asarray(cen), jnp.asarray(ctx),
                        jnp.asarray(w),
                        jax.random.fold_in(k_train, step_i))
                    step_i += 1
                # device scalar, resolved after training: an inline
                # float() here is a host sync every epoch (JX105)
                losses.append(l)
        losses = [float(l_) for l_ in losses]
        _log.info("Word2Vec loss %.4f -> %.4f over %d epochs",
                  losses[0], losses[-1], self.epochs)
        vectors = np.asarray(params["in"], np.float32)
        return Word2VecModel(input_col=self.input_col,
                             output_col=self.output_col,
                             vocab=list(vocab), vectors=vectors)


class Word2VecModel(Transformer, HasInputCol, HasOutputCol):
    """Fitted :class:`Word2Vec`: averages a row's word vectors (rows with
    no in-vocabulary token get the zero vector, matching Spark), plus
    cosine ``find_synonyms``."""

    input_col = Param(default="tokens", doc="token-list input column",
                      type_=str)
    output_col = Param(default="features", doc="mean-vector output column",
                       type_=str)
    vocab = Param(default=None, doc="vocabulary, index-aligned to vectors",
                  type_=(list, tuple))
    vectors = Param(default=None, doc="embedding matrix [V, D]",
                    is_complex=True)

    def _index(self) -> dict[str, int]:
        # cache keyed on vocab identity: set()/copy() replacing the vocab
        # must not serve the old word→row map against new vectors
        vocab = self.vocab
        cached = getattr(self, "_index_cache", None)
        if cached is None or cached[0] is not vocab:
            cached = (vocab, {w: i for i, w in enumerate(vocab)})
            self._index_cache = cached
        return cached[1]

    def transform(self, table: DataTable) -> DataTable:
        index = self._index()
        vecs = np.asarray(self.vectors, np.float32)
        d = vecs.shape[1]
        out = []
        for toks in table[self.input_col]:
            if is_missing(toks):
                out.append(np.zeros(d, np.float32))
                continue
            ids = [index[t] for t in toks if t in index]
            out.append(vecs[ids].mean(axis=0) if ids
                       else np.zeros(d, np.float32))
        return table.with_column(self.output_col, out)

    def find_synonyms(self, word: str, k: int = 5) -> list[tuple[str, float]]:
        index = self._index()
        if word not in index:
            raise KeyError(f"{word!r} not in the Word2Vec vocabulary")
        vecs = np.asarray(self.vectors, np.float32)
        q = vecs[index[word]]
        norms = np.linalg.norm(vecs, axis=1) * (np.linalg.norm(q) + 1e-12)
        sims = vecs @ q / np.maximum(norms, 1e-12)
        sims[index[word]] = -np.inf
        top = np.argsort(-sims)[:k]
        return [(self.vocab[i], float(sims[i])) for i in top]

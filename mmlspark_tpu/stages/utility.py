"""Small utility pipeline stages.

Analog of the reference's ``src/pipeline-stages/`` + ``src/checkpoint-data/``
(reference: SelectColumns.scala:21-45, DropColumns.scala, Repartition.scala:18-63,
Cacher.scala:12-38, ClassBalancer.scala:16-60, Timer.scala:54-123,
CheckpointData.scala:47-113) and ``src/multi-column-adapter/``
(MultiColumnAdapter.scala:17-134).

Spark-specific semantics (persist storage levels, shuffle repartition) map to
their host-memory analogs: caching is a memoized snapshot, checkpointing an
explicit on-disk parquet round-trip; repartition sets the partition hint used
by host-parallel stages.
"""

from __future__ import annotations

import copy
import os
import time
import weakref
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import (
    Estimator, HasInputCol, HasLabelCol, HasOutputCol, PipelineStage,
    Transformer,
)
from mmlspark_tpu.data.table import DataTable, to_py_scalar
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span

_log = get_logger("stages.utility")


class SelectColumns(Transformer):
    """Keeps only the listed columns (reference:
    pipeline-stages/src/main/scala/SelectColumns.scala:21-45)."""

    cols = Param(default=None, doc="columns to keep", type_=(list, tuple))

    def transform(self, table: DataTable) -> DataTable:
        return table.select(*(self.cols or []))


class DropColumns(Transformer):
    """Drops the listed columns (reference: pipeline-stages DropColumns)."""

    cols = Param(default=None, doc="columns to drop", type_=(list, tuple))

    def transform(self, table: DataTable) -> DataTable:
        return table.drop(*(self.cols or []))


class RenameColumns(Transformer):
    """Renames columns via an old-name → new-name map."""

    mapping = Param(default=None, doc="old-name → new-name map", type_=dict)

    def transform(self, table: DataTable) -> DataTable:
        return table.rename(self.mapping or {})


class Repartition(Transformer):
    """Sets the table's partition hint (consumed by host-parallel stages and
    the sharded input pipeline). ``disable`` passes through untouched."""

    n = Param(default=Param.REQUIRED, doc="number of partitions", type_=int,
              validator=Param.gt(0))
    disable = Param(default=False, doc="pass through unchanged", type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        if self.disable:
            return table
        return table.repartition(self.n)


class Cacher(Transformer):
    """Memoizing materialization point (reference: Cacher.scala:12-38,
    ``dataset.cache()``).

    Columnar tables are host-resident, so the observable cache semantics
    here are *memoization*: the first transform snapshots the table (a
    defensive column copy — later in-place mutation of the input cannot
    leak through the cache, exactly like Spark's materialized storage),
    and repeated transforms of the SAME upstream table return the
    identical cached object without re-copying — the re-execution
    shield a pipeline puts above an expensive featurization."""

    disable = Param(default=False, doc="pass through unchanged", type_=bool)

    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_cache", None)  # runtime memo, not part of the stage state
        return d

    def transform(self, table: DataTable) -> DataTable:
        if self.disable:
            return table
        cached = self.__dict__.get("_cache")
        # weakref key: the cache must not PIN the upstream table alive
        # (that would hold two full copies for the stage's lifetime); a
        # dead referent can't collide with a new table's identity either
        if cached is not None and cached[0]() is table:
            return cached[1]

        def snap_col(col):
            # object columns (image dicts, row vectors) hold references —
            # a shallow np.copy would let in-place row mutation leak
            # through the cache
            return (copy.deepcopy(col) if col.dtype == object
                    else np.copy(col))

        snap = DataTable({k: snap_col(table[k]) for k in table.columns},
                         meta=table.meta)
        snap.num_partitions = table.num_partitions
        self.__dict__["_cache"] = (weakref.ref(table), snap)
        return snap


class CheckpointData(Transformer):
    """Persist the table to disk (parquet via Arrow) and reload — the analog
    of persist/unpersist with a Hive writer. ``remove_checkpoint`` deletes
    the file after reload. Note: vector cells stored as ndarrays come back
    as Python lists (the Arrow round-trip loses the NumPy wrapper; numeric
    consumers go through ``column_matrix`` which accepts both)."""

    path = Param(default=None, doc="checkpoint file path (.parquet)",
                 type_=str)
    remove_checkpoint = Param(default=False,
                              doc="delete the file after reload", type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        if not self.path:
            return table
        import pyarrow.parquet as pq
        pq.write_table(table.to_arrow(), self.path)
        out = DataTable.from_arrow(pq.read_table(self.path), table.meta)
        out.num_partitions = table.num_partitions
        if self.remove_checkpoint:
            os.unlink(self.path)
        return out


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Weights each class by inverse frequency: weight = max_count / count
    (reference: ClassBalancer.scala:16-60, broadcast-join semantics)."""

    def fit(self, table: DataTable) -> "ClassBalancerModel":
        col = table[self.input_col]
        values, counts = np.unique(col, return_counts=True)
        top = counts.max() if len(counts) else 1
        weights = {to_py_scalar(v): float(top) / float(c)
                   for v, c in zip(values, counts)}
        return ClassBalancerModel(
            input_col=self.input_col, output_col=self.output_col,
            weights=weights)


class ClassBalancerModel(Transformer, HasInputCol, HasOutputCol):
    """Fitted :class:`ClassBalancer`: adds the inverse-frequency weight
    column computed at fit time."""

    # complex: JSON would stringify non-string class keys (int/float labels)
    weights = Param(default=None, doc="class value → weight", type_=dict,
                    is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        col = table[self.input_col]
        try:
            w = np.asarray([self.weights[to_py_scalar(v)] for v in col],
                           dtype=np.float64)
        except KeyError as e:
            raise ValueError(
                f"column {self.input_col!r} contains class value {e.args[0]!r}"
                " not seen when ClassBalancer was fit; known classes: "
                f"{sorted(map(str, self.weights))}") from None
        return table.with_column(self.output_col, w)


class Timer(Estimator):
    """Wraps a stage and logs wall-time of its fit/transform
    (reference: Timer.scala:54-123).

    Routed through obs when tracing is on: fit/transform become spans and
    land in per-stage ``stage_fit_s``/``stage_transform_s`` histograms in
    the shared registry. Log lines are identical whether or not the
    tracer is enabled."""

    stage = Param(default=None, doc="the wrapped stage", is_complex=True)
    log_to_console = Param(default=True, doc="print timing lines", type_=bool)
    disable = Param(default=False, doc="bypass timing", type_=bool)

    def _log(self, msg: str) -> None:
        if self.log_to_console:
            _log.info(msg)

    def fit(self, table: DataTable) -> Transformer:
        stage = self.stage
        if self.disable:
            return stage.fit(table) if isinstance(stage, Estimator) else stage
        t0 = time.perf_counter()
        if isinstance(stage, Estimator):
            name = type(stage).__name__
            on = _obs_rt._enabled
            with _obs_span(f"Timer[{name}].fit" if on else "", "timed",
                           {"rows": len(table)} if on else None):
                model = stage.fit(table)
            elapsed = time.perf_counter() - t0
            if _obs_rt._enabled:
                _obs_registry().histogram("stage_fit_s",
                                          stage=name).observe(elapsed)
            self._log(f"fit {name} on {len(table)} rows took "
                      f"{elapsed:.3f}s")
        else:
            model = stage
        return TimerModel(stage=model, log_to_console=self.log_to_console,
                          disable=self.disable)


class TimerModel(Transformer):
    """Fitted :class:`Timer`: times the wrapped transformer's transform
    calls (reference: pipeline-stages/src/main/scala/Timer.scala:54-123)."""

    stage = Param(default=None, doc="the wrapped transformer",
                  is_complex=True)
    log_to_console = Param(default=True, doc="print timing lines", type_=bool)
    disable = Param(default=False, doc="bypass timing", type_=bool)

    def transform(self, table: DataTable) -> DataTable:
        if self.disable:
            return self.stage.transform(table)
        name = type(self.stage).__name__
        t0 = time.perf_counter()
        on = _obs_rt._enabled
        with _obs_span(f"Timer[{name}].transform" if on else "", "timed",
                       {"rows": len(table)} if on else None):
            out = self.stage.transform(table)
        elapsed = time.perf_counter() - t0
        if _obs_rt._enabled:
            _obs_registry().histogram("stage_transform_s",
                                      stage=name).observe(elapsed)
        if self.log_to_console:
            _log.info(
                f"transform {name} on {len(table)} rows "
                f"took {elapsed:.3f}s")
        return out


class MultiColumnAdapter(Estimator):
    """Applies a unary stage to N (input, output) column pairs
    (reference: MultiColumnAdapter.scala:17-134). The base stage must expose
    ``input_col``/``output_col`` params; it is copied per pair."""

    base_stage = Param(default=None, doc="unary stage to replicate",
                       is_complex=True)
    input_cols = Param(default=None, doc="input column names",
                       type_=(list, tuple))
    output_cols = Param(default=None, doc="output column names",
                        type_=(list, tuple))

    def _pairs(self) -> list[tuple[str, str]]:
        ins, outs = list(self.input_cols or []), list(self.output_cols or [])
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols length mismatch")
        return list(zip(ins, outs))

    def fit(self, table: DataTable) -> Transformer:
        from mmlspark_tpu.core.pipeline import PipelineModel
        base = self.base_stage
        if base is None:
            raise ValueError("base_stage not set")
        fitted: list[Transformer] = []
        current = table
        for in_col, out_col in self._pairs():
            stage = base.copy(input_col=in_col, output_col=out_col)
            if isinstance(stage, Estimator):
                model = stage.fit(current)
            else:
                model = stage
            current = model.transform(current)
            fitted.append(model)
        return PipelineModel(stages=fitted)

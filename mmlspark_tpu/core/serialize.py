"""Stage/model persistence: type-driven serializer registry.

Every stage saves to a directory: ``metadata.json`` holds the class path and
the JSON-representable params; each *complex* param (models, pytrees, arrays,
nested stages…) is written under ``complex/<name>/`` by a serializer chosen
by value type. This is the analog of the reference's ``Serializer``
type-dispatch plus constructor serialization (reference:
core/serialize/src/main/scala/Serializer.scala:51-133,
ConstructorWriter.scala:22-90) — but since Python classes are constructed
from kwargs, "constructor serialization" degenerates to: save all set params,
reinstantiate the class, restore them.

Numeric pytrees go through ``flax.serialization`` msgpack so fitted JAX
models round-trip; arbitrary host objects fall back to pickle (same trust
model as Java serialization in the reference).
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import shutil
from typing import Any

import numpy as np


_FORMAT_VERSION = 1


def _json_default(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return {"__bytes_hex__": v.hex()}
    raise TypeError(f"not JSON-serializable: {type(v)}")


def _json_object_hook(d: dict) -> Any:
    if "__bytes_hex__" in d and len(d) == 1:
        return bytes.fromhex(d["__bytes_hex__"])
    return d


def class_path(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


def load_class(path: str) -> type:
    module, _, name = path.rpartition(".")
    obj: Any = importlib.import_module(module)
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


# ---- complex-value serializers (Serializer.typeToSerializer analog) ----

def _is_pytree_of_arrays(v: Any) -> bool:
    import jax
    try:
        leaves = jax.tree_util.tree_leaves(v)
    except Exception:
        return False
    if not leaves:
        return isinstance(v, (dict, list, tuple))
    def is_array(l: Any) -> bool:
        return (isinstance(l, (np.ndarray, np.generic))
                or type(l).__module__.startswith("jax"))
    # require at least one real array leaf: containers of plain Python
    # scalars round-trip exactly via pickle, whereas msgpack restore would
    # turn every scalar leaf into an ndarray
    return any(is_array(l) for l in leaves) and all(
        is_array(l) or isinstance(l, (int, float, bool)) for l in leaves)


def save_value(value: Any, directory: str) -> None:
    """Write one complex value into ``directory`` with a ``kind`` tag."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.table import DataTable

    os.makedirs(directory, exist_ok=True)

    def tag(kind: str, extra: dict | None = None) -> None:
        with open(os.path.join(directory, "kind.json"), "w") as f:
            json.dump({"kind": kind, **(extra or {})}, f,
                      default=_json_default)

    if isinstance(value, PipelineStage):
        tag("stage")
        value.save(os.path.join(directory, "stage"))
    elif isinstance(value, (list, tuple)) and value and all(
            isinstance(s, PipelineStage) for s in value):
        tag("stage_list", {"n": len(value),
                           "tuple": isinstance(value, tuple)})
        for i, s in enumerate(value):
            s.save(os.path.join(directory, f"stage_{i}"))
    elif isinstance(value, np.ndarray):
        tag("ndarray")
        np.save(os.path.join(directory, "value.npy"), value,
                allow_pickle=value.dtype == object)
    elif isinstance(value, DataTable):
        tag("datatable", {"meta": value.meta})
        with open(os.path.join(directory, "table.pkl"), "wb") as f:
            pickle.dump({k: value[k] for k in value.columns}, f)
    elif _is_pytree_of_arrays(value):
        import jax
        from flax import serialization
        tag("pytree")
        host = jax.tree_util.tree_map(np.asarray, value)
        with open(os.path.join(directory, "tree.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(host))
        with open(os.path.join(directory, "treedef.pkl"), "wb") as f:
            pickle.dump(jax.tree_util.tree_structure(value), f)
    else:
        tag("pickle")
        with open(os.path.join(directory, "value.pkl"), "wb") as f:
            pickle.dump(value, f)


def load_value(directory: str) -> Any:
    from mmlspark_tpu.data.table import DataTable

    with open(os.path.join(directory, "kind.json")) as f:
        info = json.load(f)
    kind = info["kind"]
    if kind == "stage":
        return load_stage(os.path.join(directory, "stage"))
    if kind == "stage_list":
        out = [load_stage(os.path.join(directory, f"stage_{i}"))
               for i in range(info["n"])]
        return tuple(out) if info.get("tuple") else out
    if kind == "ndarray":
        return np.load(os.path.join(directory, "value.npy"),
                       allow_pickle=True)
    if kind == "datatable":
        with open(os.path.join(directory, "table.pkl"), "rb") as f:
            cols = pickle.load(f)
        return DataTable(cols, info.get("meta"))
    if kind == "pytree":
        import jax
        from flax import serialization
        with open(os.path.join(directory, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        # build a skeleton with the right structure, then restore bytes
        skeleton = jax.tree_util.tree_unflatten(
            treedef, [0] * treedef.num_leaves)
        with open(os.path.join(directory, "tree.msgpack"), "rb") as f:
            return serialization.from_bytes(skeleton, f.read())
    if kind == "pickle":
        with open(os.path.join(directory, "value.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown serialized kind {kind!r} in {directory}")


# ---- stage save/load entry points ----

def save_stage(stage: Any, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path, exist_ok=True)
    simple = stage._simple_param_values()
    meta = {
        "format_version": _FORMAT_VERSION,
        "class": class_path(type(stage)),
        "params": simple,
        "uid": stage.uid,
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, default=_json_default, indent=1)
    complex_vals = stage._complex_param_values()
    for name, value in complex_vals.items():
        save_value(value, os.path.join(path, "complex", name))
    extra_dir = os.path.join(path, "extra")
    stage._save_extra(extra_dir)


def load_stage(path: str) -> Any:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f, object_hook=_json_object_hook)
    cls = load_class(meta["class"])
    stage = cls.__new__(cls)
    stage._values = {}
    stage._uid = meta.get("uid")
    # JSON round-trips tuples to lists; params validate/coerce on set
    params = {}
    declared = cls.params()
    for k, v in meta["params"].items():
        if k in declared:
            if isinstance(v, list) and isinstance(declared[k].type_, type) \
                    and declared[k].type_ is tuple:
                v = tuple(v)
            params[k] = v
    stage._post_init()
    stage.set(**params)
    cdir = os.path.join(path, "complex")
    if os.path.isdir(cdir):
        for name in os.listdir(cdir):
            if name in declared:
                stage._values[name] = load_value(os.path.join(cdir, name))
    stage._load_extra(os.path.join(path, "extra"))
    return stage

"""Typed parameter DSL for pipeline stages.

Every knob on every stage is a :class:`Param` descriptor with a default, a
doc string, an optional domain/validator, and an optional type. Params are
introspectable at the class level, which powers the auto-generated API docs,
the stage registry, the fuzzing suite, and JSON persistence — the analog of
the reference's ``MMLParams``/``Wrappable`` DSL whose introspection powers
PySpark codegen (reference: core/contracts/src/main/scala/Params.scala:10-110,
codegen/src/main/scala/PySparkWrapperGenerator.scala:34-81).

Unlike the reference there is no JVM/py4j boundary, so "codegen" degenerates
to doc/stub generation; the single source of truth is the descriptor.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Iterable, Mapping, Sequence


class ParamValidationError(ValueError):
    """Raised when a param value fails its domain/validator check."""


class Param:
    """A typed, validated, documented parameter declared on a stage class.

    Use class-level declaration::

        class MyStage(Transformer):
            input_col = Param(default="input", doc="name of the input column")
            n = Param(default=8, doc="batch size", type_=int,
                      validator=Param.gt(0))
    """

    __slots__ = ("name", "default", "doc", "type_", "validator", "is_complex",
                 "owner")

    # sentinel: a param with no default that must be set before use
    REQUIRED = object()

    def __init__(
        self,
        default: Any = None,
        doc: str = "",
        type_: type | tuple[type, ...] | None = None,
        validator: Callable[[Any], bool] | None = None,
        is_complex: bool = False,
    ):
        self.name: str | None = None  # filled by __set_name__
        self.default = default
        self.doc = doc
        self.type_ = type_
        self.validator = validator
        # complex params hold values not representable as JSON (models,
        # pytrees, nested stages); they are persisted by the serializer
        # registry instead (analog of ComplexParam,
        # reference: core/serialize/src/main/scala/ComplexParam.scala:10-31)
        self.is_complex = is_complex
        self.owner: type | None = None

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name
        self.owner = owner

    def __get__(self, obj: Any, objtype: type | None = None) -> Any:
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj: Any, value: Any) -> None:
        obj.set(**{self.name: value})

    def validate(self, value: Any) -> Any:
        """Validate (and lightly coerce) a candidate value; return it."""
        if value is None or value is Param.REQUIRED:
            return value
        if self.type_ is not None:
            # int is acceptable where float is declared
            if self.type_ is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)
            if self.type_ is int and isinstance(value, bool):
                raise ParamValidationError(
                    f"param {self.name!r}: got bool where int expected")
            if not isinstance(value, self.type_):
                raise ParamValidationError(
                    f"param {self.name!r}: expected {self.type_}, "
                    f"got {type(value).__name__} ({value!r})")
        if self.validator is not None and not self.validator(value):
            raise ParamValidationError(
                f"param {self.name!r}: value {value!r} outside domain "
                f"({getattr(self.validator, '_doc', 'validator failed')})")
        return value

    def __repr__(self) -> str:
        return (f"Param({self.name!r}, default={self.default!r}, "
                f"doc={self.doc!r})")

    # ---- domain combinators (analog of ParamDomain factories,
    # reference: core/contracts/src/main/scala/Params.scala:38-108) ----

    @staticmethod
    def _mk(fn: Callable[[Any], bool], doc: str) -> Callable[[Any], bool]:
        fn._doc = doc  # type: ignore[attr-defined]
        return fn

    @staticmethod
    def gt(lo: float) -> Callable[[Any], bool]:
        return Param._mk(lambda v: v > lo, f"> {lo}")

    @staticmethod
    def ge(lo: float) -> Callable[[Any], bool]:
        return Param._mk(lambda v: v >= lo, f">= {lo}")

    @staticmethod
    def lt(hi: float) -> Callable[[Any], bool]:
        return Param._mk(lambda v: v < hi, f"< {hi}")

    @staticmethod
    def le(hi: float) -> Callable[[Any], bool]:
        return Param._mk(lambda v: v <= hi, f"<= {hi}")

    @staticmethod
    def in_range(lo: float, hi: float) -> Callable[[Any], bool]:
        return Param._mk(lambda v: lo <= v <= hi, f"in [{lo}, {hi}]")

    @staticmethod
    def one_of(*choices: Any) -> Callable[[Any], bool]:
        cs = set(choices)
        return Param._mk(lambda v: v in cs, f"one of {sorted(map(str, cs))}")

    @staticmethod
    def nonempty() -> Callable[[Any], bool]:
        return Param._mk(lambda v: len(v) > 0, "non-empty")


class Params:
    """Base class giving a stage its param store and introspection surface.

    Values live in ``self._values``; unset params fall back to the class-level
    default. ``params()`` exposes the full descriptor map in declaration
    order (MRO-aware) for docs/fuzzing/persistence.
    """

    def __init__(self, **kwargs: Any):
        self._values: dict[str, Any] = {}
        self.set(**kwargs)

    # -- introspection --

    @classmethod
    def params(cls) -> dict[str, Param]:
        # per-class cache; params are declared statically so no invalidation
        cached = cls.__dict__.get("_params_cache")
        if cached is not None:
            return cached
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        cls._params_cache = out
        return out

    @classmethod
    def param(cls, name: str) -> Param:
        p = cls.params().get(name)
        if p is None:
            raise KeyError(f"{cls.__name__} has no param {name!r}")
        return p

    # -- get/set --

    def get(self, name: str) -> Any:
        p = type(self).param(name)
        if name in self._values:
            return self._values[name]
        if p.default is Param.REQUIRED:
            raise ParamValidationError(
                f"required param {name!r} of {type(self).__name__} not set")
        return p.default

    def is_set(self, name: str) -> bool:
        return name in self._values

    def set(self, **kwargs: Any) -> "Params":
        """Set params by keyword; validates each. Returns self (chainable)."""
        declared = type(self).params()
        for name, value in kwargs.items():
            p = declared.get(name)
            if p is None:
                raise KeyError(
                    f"{type(self).__name__} has no param {name!r}; "
                    f"available: {sorted(declared)}")
            self._values[name] = p.validate(value)
        return self

    def get_all(self, include_defaults: bool = True) -> dict[str, Any]:
        """Current param map (explicitly-set values over defaults)."""
        out = {}
        for name, p in type(self).params().items():
            if name in self._values:
                out[name] = self._values[name]
            elif include_defaults and p.default is not Param.REQUIRED:
                out[name] = p.default
        return out

    def explain_params(self) -> str:
        """Human-readable param documentation (doc-gen building block)."""
        lines = []
        for name, p in type(self).params().items():
            cur = self._values.get(name, p.default)
            dom = getattr(p.validator, "_doc", None)
            extra = f", domain: {dom}" if dom else ""
            lines.append(f"{name}: {p.doc} (default: {p.default!r}{extra}, "
                         f"current: {cur!r})")
        return "\n".join(lines)

    def copy(self, **overrides: Any) -> "Params":
        """Deep copy of this stage with optional param overrides."""
        other = _copy.deepcopy(self)
        other.set(**overrides)
        return other

    def _simple_param_values(self) -> dict[str, Any]:
        """Explicitly-set, JSON-representable params (for persistence)."""
        declared = type(self).params()
        return {k: v for k, v in self._values.items()
                if not declared[k].is_complex}

    def _complex_param_values(self) -> dict[str, Any]:
        declared = type(self).params()
        return {k: v for k, v in self._values.items()
                if declared[k].is_complex}

"""Filesystem abstraction: local paths, in-memory URIs, object stores.

The core/hadoop analog (reference: core/hadoop/src/main/scala/
HadoopUtils.scala + the HDFS-backed model repository
downloader/src/main/scala/ModelDownloader.scala:39-104 ``HDFSRepo``). The
reference reaches distributed storage through the Hadoop FileSystem API;
here a scheme registry routes paths:

* plain paths / ``file://`` → the local filesystem,
* ``memory://`` → a process-local in-memory store (the test/HDFS stand-in,
  and the unit-test double for object stores),
* ``gs://`` / ``s3://`` / ``hdfs://`` / ``abfs://`` → fsspec, when
  installed (TPU deployments read shards and write checkpoints to GCS).

Consumers (model downloader/publisher, bundle save/load, readers) call the
module-level helpers; new schemes only need a ``FileSystem`` registration.
"""

from __future__ import annotations

import io
import os
import posixpath
import threading
from glob import glob as _glob
from typing import Any, Iterator

_FSSPEC_SCHEMES = ("gs", "s3", "hdfs", "abfs", "az", "gcs")


def split_scheme(path: str) -> tuple[str, str]:
    """('memory', 'a/b') for 'memory://a/b'; ('', path) for local paths.

    Windows drive letters and bare paths have no scheme.
    """
    if "://" in path:
        scheme, rest = path.split("://", 1)
        if len(scheme) > 1:  # not a drive letter
            return scheme.lower(), rest
    return "", path


class FileSystem:
    """Minimal FS contract needed by the framework's IO paths."""

    def open(self, path: str, mode: str = "rb") -> Any:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def remove(self, path: str) -> None:
        raise NotImplementedError

    def list(self, path: str, recursive: bool = False) -> list[str]:
        """Files under a directory/prefix (full paths, sorted)."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError


class LocalFS(FileSystem):
    def open(self, path: str, mode: str = "rb") -> Any:
        if "w" in mode or "a" in mode:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        os.remove(path)

    def list(self, path: str, recursive: bool = False) -> list[str]:
        if os.path.isdir(path):
            pattern = os.path.join(path, "**" if recursive else "*")
            files = _glob(pattern, recursive=recursive)
        else:
            files = _glob(path, recursive=recursive)
        return sorted(f for f in files if os.path.isfile(f))

    def size(self, path: str) -> int:
        return os.path.getsize(path)


class MemoryFS(FileSystem):
    """Process-local in-memory store — deterministic object-store double."""

    def __init__(self) -> None:
        self._files: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def _norm(self, path: str) -> str:
        return posixpath.normpath(path).lstrip("/")

    def open(self, path: str, mode: str = "rb") -> Any:
        key = self._norm(path)
        if "r" in mode and "w" not in mode:
            with self._lock:
                if key not in self._files:
                    raise FileNotFoundError(f"memory://{key}")
                data = self._files[key]
            return io.BytesIO(data) if "b" in mode else io.StringIO(
                data.decode())
        fs = self

        class _Writer(io.BytesIO):
            def close(self) -> None:
                with fs._lock:
                    fs._files[key] = self.getvalue()
                super().close()

        class _TextWriter(io.StringIO):
            def close(self) -> None:
                with fs._lock:
                    fs._files[key] = self.getvalue().encode()
                super().close()

        return _Writer() if "b" in mode else _TextWriter()

    def exists(self, path: str) -> bool:
        key = self._norm(path)
        with self._lock:
            return (key in self._files
                    or any(k.startswith(key + "/") for k in self._files))

    def makedirs(self, path: str) -> None:
        pass  # directories are implicit

    def remove(self, path: str) -> None:
        key = self._norm(path)
        with self._lock:
            if key not in self._files:
                raise FileNotFoundError(f"memory://{key}")
            del self._files[key]

    def list(self, path: str, recursive: bool = False) -> list[str]:
        prefix = self._norm(path)
        out = []
        with self._lock:
            for k in self._files:
                if prefix in ("", "."):
                    rel = k
                elif k.startswith(prefix + "/"):
                    rel = k[len(prefix) + 1:]
                elif k == prefix:
                    rel = ""
                else:
                    continue
                if not recursive and "/" in rel:
                    continue
                out.append("memory://" + k)
        return sorted(out)

    def size(self, path: str) -> int:
        key = self._norm(path)
        with self._lock:
            return len(self._files[key])

    def clear(self) -> None:
        with self._lock:
            self._files.clear()


class FsspecFS(FileSystem):
    """Object stores through fsspec (gs://, s3://, hdfs://, …)."""

    def __init__(self, scheme: str):
        try:
            import fsspec
        except ImportError as e:
            raise ImportError(
                f"paths with scheme {scheme}:// need fsspec (and the "
                f"matching backend, e.g. gcsfs for gs://)") from e
        self._fs = fsspec.filesystem(scheme)
        self._scheme = scheme

    def _full(self, path: str) -> str:
        return f"{self._scheme}://{path}"

    def open(self, path: str, mode: str = "rb") -> Any:
        return self._fs.open(self._full(path), mode)

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._full(path))

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(self._full(path), exist_ok=True)

    def remove(self, path: str) -> None:
        self._fs.rm(self._full(path))

    def list(self, path: str, recursive: bool = False) -> list[str]:
        if recursive:
            names = self._fs.find(self._full(path))  # find is files-only
        else:
            names = [e["name"] for e in
                     self._fs.ls(self._full(path), detail=True)
                     if e.get("type") == "file"]
        return sorted(f"{self._scheme}://{n.split('://', 1)[-1]}"
                      for n in names)

    def size(self, path: str) -> int:
        return self._fs.size(self._full(path))


_memory_fs = MemoryFS()
_local_fs = LocalFS()
_fsspec_cache: dict[str, FsspecFS] = {}


def get_fs(path: str) -> tuple[FileSystem, str]:
    """Resolve a path/URI to (filesystem, fs-local path)."""
    scheme, rest = split_scheme(path)
    if scheme in ("", "file"):
        return _local_fs, rest
    if scheme == "memory":
        return _memory_fs, rest
    if scheme in _FSSPEC_SCHEMES:
        if scheme not in _fsspec_cache:
            _fsspec_cache[scheme] = FsspecFS(scheme)
        return _fsspec_cache[scheme], rest
    raise ValueError(f"unknown filesystem scheme {scheme!r} in {path!r}")


# ---- module-level helpers (what consumers actually call) ----

def open_file(path: str, mode: str = "rb") -> Any:
    fs, p = get_fs(path)
    return fs.open(p, mode)


def exists(path: str) -> bool:
    fs, p = get_fs(path)
    return fs.exists(p)


def makedirs(path: str) -> None:
    fs, p = get_fs(path)
    fs.makedirs(p)


def remove(path: str) -> None:
    fs, p = get_fs(path)
    fs.remove(p)


def list_files(path: str, recursive: bool = False) -> list[str]:
    fs, p = get_fs(path)
    return fs.list(p, recursive=recursive)


def size(path: str) -> int:
    fs, p = get_fs(path)
    return fs.size(p)


def read_bytes(path: str) -> bytes:
    with open_file(path, "rb") as f:
        return f.read()


def write_bytes(path: str, data: bytes) -> None:
    with open_file(path, "wb") as f:
        f.write(data)


def join(base: str, *parts: str) -> str:
    """Scheme-aware path join (posix separators for URIs)."""
    scheme, rest = split_scheme(base)
    if not scheme:
        return os.path.join(base, *parts)
    return f"{scheme}://" + posixpath.join(rest, *parts)


def iter_chunks(path: str, chunk: int = 1 << 20) -> Iterator[bytes]:
    with open_file(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return
            yield b

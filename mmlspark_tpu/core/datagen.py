"""Random table generation for the generic fuzz suite.

Analog of the reference's ``core/test/datagen`` (reference:
core/test/datagen/src/main/scala/GenerateDataset.scala:36-59,
GenerateDataType.scala): seeded random DataTables over a randomized schema of
mixed column types — numerics with missing values, strings with empties and
None, categoricals, token lists, vectors, booleans, dates, and image structs
— so every pipeline stage can be fuzzed against inputs it did not expect.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from mmlspark_tpu.data.table import DataTable


def _numeric(rng: np.random.Generator, n: int, missing: float) -> np.ndarray:
    vals = rng.normal(scale=rng.uniform(0.5, 100.0), size=n)
    if missing:
        vals[rng.random(n) < missing] = np.nan
    return vals


def _integer(rng: np.random.Generator, n: int, missing: float) -> np.ndarray:
    return rng.integers(-1000, 1000, size=n)


def _boolean(rng: np.random.Generator, n: int, missing: float) -> np.ndarray:
    return rng.random(n) > 0.5


def _string(rng: np.random.Generator, n: int, missing: float) -> list:
    words = ["alpha", "beta", "gamma", "", "δelta", "a b c", "x,y"]
    out: list[Any] = [words[i] for i in rng.integers(0, len(words), size=n)]
    if missing:
        for i in np.nonzero(rng.random(n) < missing)[0]:
            out[int(i)] = None
    return out


def _categorical(rng: np.random.Generator, n: int, missing: float) -> list:
    k = int(rng.integers(1, 5))  # k=1: singleton category edge case
    levels = [f"lvl{j}" for j in range(k)]
    return [levels[i] for i in rng.integers(0, k, size=n)]


def _tokens(rng: np.random.Generator, n: int, missing: float) -> list:
    vocab = ["tok%d" % j for j in range(9)]
    return [[vocab[i] for i in rng.integers(0, 9, size=rng.integers(0, 6))]
            for _ in range(n)]


def _vector(rng: np.random.Generator, n: int, missing: float) -> list:
    d = int(rng.integers(2, 9))
    return [rng.normal(size=d).astype(np.float32) for _ in range(n)]


def _date_string(rng: np.random.Generator, n: int, missing: float) -> list:
    return [f"20{rng.integers(10, 30):02d}-0{rng.integers(1, 10)}-"
            f"{rng.integers(10, 28)} 0{rng.integers(0, 10)}:30:00"
            for _ in range(n)]


def _image(rng: np.random.Generator, n: int, missing: float) -> list:
    from mmlspark_tpu.core.schema import make_image
    h, w = int(rng.integers(4, 12)), int(rng.integers(4, 12))
    return [make_image(f"img{i}", rng.integers(0, 255, (h, w, 3)))
            for i in range(n)]


GENERATORS: dict[str, Callable] = {
    "numeric": _numeric,
    "integer": _integer,
    "boolean": _boolean,
    "string": _string,
    "categorical": _categorical,
    "tokens": _tokens,
    "vector": _vector,
    "date": _date_string,
    "image": _image,
}


def random_table(seed: int = 0, n_rows: int = 24,
                 kinds: tuple[str, ...] | None = None,
                 missing: float = 0.1) -> DataTable:
    """A table with one column of every requested kind (default: a random
    subset of at least 4 kinds), deterministic in ``seed``."""
    rng = np.random.default_rng(seed)
    if kinds is None:
        pool = list(GENERATORS)
        k = int(rng.integers(4, len(pool) + 1))
        kinds = tuple(pool[i] for i in
                      rng.choice(len(pool), size=k, replace=False))
    cols: dict[str, Any] = {}
    for kind in kinds:
        cols[kind] = GENERATORS[kind](rng, n_rows, missing)
    t = DataTable(cols)
    if "image" in cols:
        from mmlspark_tpu.core.schema import mark_image_column
        t = mark_image_column(t, "image")
    return t


def labeled_table(seed: int = 0, n_rows: int = 48,
                  classification: bool = True) -> DataTable:
    """Mixed-type table with a learnable label column (for Train* fuzzing)."""
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n_rows)
    x2 = rng.normal(size=n_rows)
    cat = [["u", "v"][i] for i in rng.integers(0, 2, size=n_rows)]
    signal = x1 + 0.5 * x2 + np.asarray([0.5 if c == "u" else -0.5
                                         for c in cat])
    if classification:
        label = (signal > 0).astype(np.int64)
    else:
        label = signal + rng.normal(scale=0.1, size=n_rows)
    return DataTable({"x1": x1, "x2": x2, "cat": cat, "label": label})

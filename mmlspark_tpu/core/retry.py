"""Typed retry policy — bounded attempts with jittered exponential backoff.

Transient faults (a flaky network fetch during a model-zoo pull, a worker
process dying under a preemption storm) should cost a bounded, observable
retry loop, not an aborted run. :class:`RetryPolicy` is the ONE place the
backoff arithmetic lives: the model downloader retries fetches through it
(``data/downloader.py``) and the training service supervisor paces worker
restarts with the same schedule (``train/service.py``) — one policy type,
two very different fault domains.

Jitter is full-range (each delay is drawn uniformly from
``[delay * (1 - jitter), delay]``), the standard decorrelation against
thundering-herd retries (many workers hitting the same recovered endpoint
in lockstep). The draw comes from a caller-suppliable ``random.Random`` so
tests pin the schedule without patching the module.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Iterator


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How many times to retry, how long to wait, and what is retryable.

    ``max_attempts`` counts TOTAL tries (1 = no retry). Delays grow
    ``base_delay_s * multiplier**k`` capped at ``max_delay_s``; ``jitter``
    is the fraction of each delay randomized away (0 = deterministic,
    0.5 = drawn from ``[0.5d, d]``). ``retry_on`` is the exception tuple
    a failure must match to be retried — anything else propagates
    immediately (a typed validation error is not a transient fault).
    ``retry_if`` (optional) refines the type match with a predicate:
    the exception retries only when ``retry_if(exc)`` is true — how a
    caller distinguishes a transient HTTP 503 from a permanent 404 when
    both are ``OSError`` subclasses.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.2
    max_delay_s: float = 10.0
    multiplier: float = 2.0
    jitter: float = 0.5
    retry_on: tuple[type[BaseException], ...] = (OSError,)
    retry_if: Callable[[BaseException], bool] | None = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if self.multiplier < 1.0:
            raise ValueError(
                f"multiplier must be >= 1, got {self.multiplier} (a "
                "shrinking backoff retries faster under sustained failure)")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delays(self, rng: random.Random | None = None) -> Iterator[float]:
        """The backoff schedule: one delay per RETRY (``max_attempts - 1``
        values), jittered."""
        rng = rng or random
        for k in range(self.max_attempts - 1):
            d = min(self.base_delay_s * self.multiplier ** k,
                    self.max_delay_s)
            if self.jitter:
                d *= 1.0 - self.jitter * rng.random()
            yield d


def call_with_retry(fn: Callable[[], Any], policy: RetryPolicy,
                    on_retry: Callable[[int, BaseException, float], None]
                    | None = None,
                    sleep: Callable[[float], None] = time.sleep,
                    rng: random.Random | None = None) -> Any:
    """Call ``fn`` under ``policy``; returns its value or raises the LAST
    failure once attempts are exhausted.

    ``on_retry(attempt, exc, delay_s)`` fires before each backoff sleep —
    the hook call sites use to log and bump their retry counters (e.g.
    the downloader's ``data.fetch_retries``). A failure not matching
    ``policy.retry_on`` propagates without consuming attempts.

    A failure may carry a server-provided hint in a ``retry_after_s``
    attribute (the serving plane stamps it on ``Overloaded`` /
    ``ServerClosed`` from the same config that feeds the HTTP
    ``Retry-After`` header). The hint is a FLOOR on the backoff delay,
    never a cap: retrying sooner than the server asked just burns an
    attempt on a rejection the server already promised, while a policy
    that wants to wait longer still may.
    """
    delays = policy.delays(rng)
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except policy.retry_on as e:
            if policy.retry_if is not None and not policy.retry_if(e):
                raise  # type matched but the predicate says permanent
            delay = next(delays, None)
            if delay is None:  # attempts exhausted — the caller sees the
                raise          # real failure, not a retry wrapper
            hint = getattr(e, "retry_after_s", None)
            if hint is not None:
                delay = max(delay, float(hint))
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover

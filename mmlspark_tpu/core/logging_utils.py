"""Logging + timing utilities.

Analog of the reference's ``Logging`` (core/env/src/main/scala/Logging.scala:14-23)
and the ``Timer`` wrapper stage's measurement core
(pipeline-stages/src/main/scala/Timer.scala:54-123). The pipeline-visible
``TimerStage`` lives in ``mmlspark_tpu.stages.misc``; this module provides
the timing primitive and the logger factory.
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Iterator

from mmlspark_tpu.core import config


def get_logger(name: str = "mmlspark_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(config.get("log_level"))
        logger.propagate = False
    return logger


@contextmanager
def timed(label: str, logger: logging.Logger | None = None,
          rows: int | None = None) -> Iterator[dict]:
    """Context manager measuring wall time; yields a dict that receives
    ``elapsed_s`` on exit. Logs when the ``timings`` config flag is on."""
    record: dict = {"label": label}
    t0 = time.perf_counter()
    try:
        yield record
    finally:
        record["elapsed_s"] = time.perf_counter() - t0
        if config.get("timings") and logger is not None:
            extra = f" ({rows} rows)" if rows is not None else ""
            logger.info("%s took %.3fs%s", label, record["elapsed_s"], extra)

"""Logging + timing utilities.

Analog of the reference's ``Logging`` (core/env/src/main/scala/Logging.scala:14-23)
and the ``Timer`` wrapper stage's measurement core
(pipeline-stages/src/main/scala/Timer.scala:54-123). The pipeline-visible
``TimerStage`` lives in ``mmlspark_tpu.stages.misc``; this module provides
the timing primitive and the logger factory.

When the obs tracer is enabled (docs/observability.md), :func:`timed`
additionally records a span and a per-label duration histogram into the
shared registry — log output is byte-identical either way, so enabling
observability never changes what operators grep for.
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Iterator

from mmlspark_tpu.core import config

# loggers this factory configured, re-leveled when config changes (a
# logger created at import time must honor a later
# ``config.set("log_level", ...)`` — the level is a live setting, not a
# first-call snapshot)
_configured: set[str] = set()


def _apply_log_level(changed: str) -> None:
    if changed not in ("log_level", "*"):
        return
    level = config.get("log_level")
    for name in list(_configured):
        logging.getLogger(name).setLevel(level)


config.subscribe(_apply_log_level)


def get_logger(name: str = "mmlspark_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(handler)
        logger.setLevel(config.get("log_level"))
        logger.propagate = False
    _configured.add(name)
    return logger


@contextmanager
def timed(label: str, logger: logging.Logger | None = None,
          rows: int | None = None) -> Iterator[dict]:
    """Context manager measuring wall time; yields a dict that receives
    ``elapsed_s`` on exit. Logs when the ``timings`` config flag is on.

    Routed through obs when tracing is enabled: the block becomes a span
    (category ``timed``) and the duration lands in the shared
    ``timed_s{label=...}`` histogram, so every pre-obs `timed` call site
    (fused segments, trainer epochs, bridge chunks) shows up on the
    exported timeline without re-instrumentation."""
    from mmlspark_tpu.obs import runtime as _obs_rt
    from mmlspark_tpu.obs.metrics import registry as _obs_registry
    from mmlspark_tpu.obs.spans import span as _obs_span

    record: dict = {"label": label}
    t0 = time.perf_counter()
    obs_span = _obs_span(label, "timed",
                         None if rows is None else {"rows": rows})
    obs_span.__enter__()
    try:
        yield record
    finally:
        record["elapsed_s"] = time.perf_counter() - t0
        obs_span.__exit__(None, None, None)
        if _obs_rt._enabled:
            _obs_registry().histogram(
                "timed_s", label=label).observe(record["elapsed_s"])
        if config.get("timings") and logger is not None:
            extra = f" ({rows} rows)" if rows is not None else ""
            logger.info("%s took %.3fs%s", label, record["elapsed_s"], extra)

"""Pipeline / PipelineModel — ordered stage composition with persistence.

The analog of SparkML's ``Pipeline`` as the reference uses it everywhere
(e.g. featurize/src/main/scala/Featurize.scala:82-98 returns a fitted
Pipeline). ``fit`` walks the stages: estimators are fitted on the running
table and replaced by their models; transformers pass through.
"""

from __future__ import annotations

from typing import Any, Sequence

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, PipelineStage, Transformer
from mmlspark_tpu.data.table import DataTable


def _fold_state(stages: Sequence[PipelineStage] | None, schema: Any,
                n: int | None) -> tuple[Any, int | None]:
    """Chain the stages' (schema, rows) inference in ONE pass (the SparkML
    transformSchema fold) — shared by Pipeline and PipelineModel so fitted
    and unfitted analysis cannot diverge, and so each inner stage's
    inference (including UDF probes) runs exactly once per walk."""
    for stage in stages or []:
        schema, n = stage._infer_state(schema, n)
    return schema, n


class Pipeline(Estimator):
    """Ordered composition of stages fit as one estimator.

    Estimator stages are fit in sequence on the progressively transformed
    table; the result is a :class:`PipelineModel` of fitted transformers
    (SparkML ``Pipeline`` semantics as used throughout the reference)."""

    stages = Param(default=None, doc="ordered list of pipeline stages",
                   is_complex=True)

    def __init__(self, stages: Sequence[PipelineStage] | None = None,
                 **kwargs: Any):
        super().__init__(**kwargs)
        if stages is not None:
            self.set(stages=list(stages))

    def fit(self, table: DataTable) -> "PipelineModel":
        fitted: list[Transformer] = []
        current = table
        stages = self.stages or []
        # pre-flight: reject mis-wired stage lists with every offending
        # index/type up front (the analyzer's check), not a bare TypeError
        # from whichever stage happens to break first
        from mmlspark_tpu.analysis.analyzer import check_stage_kinds
        bad = check_stage_kinds(stages)
        if bad:
            raise TypeError(
                "Pipeline has invalid stages:\n  "
                + "\n  ".join(d.message for d in bad))
        last_est = max((i for i, s in enumerate(stages)
                        if isinstance(s, Estimator)), default=-1)
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(current)
            else:
                model = stage
            # only transform while a later estimator still needs the table
            if i < last_est:
                current = model.transform(current)
            fitted.append(model)
        return PipelineModel(stages=fitted)

    def infer_schema(self, schema: Any) -> Any:
        return _fold_state(self.stages, schema, None)[0]

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        return _fold_state(self.stages, schema, n)[1]

    def _infer_state(self, schema: Any, n: int | None
                     ) -> tuple[Any, int | None]:
        return _fold_state(self.stages, schema, n)


class PipelineModel(Transformer):
    """A fitted :class:`Pipeline`: applies each transformer in order.

    Execution goes through the pipeline planner
    (:mod:`mmlspark_tpu.core.plan`): maximal runs of device-capable stages
    (``DeviceStage``) fuse into one jitted program with a single H2D upload
    and one async-windowed D2H fetch per minibatch; everything else runs
    its host ``transform`` exactly as before. The compiled-segment cache
    lives on this instance, so streaming callers (the Arrow bridge) pay
    compile + param upload once across chunks.
    """

    stages = Param(default=None, doc="ordered list of fitted transformers",
                   is_complex=True)

    def __init__(self, stages: Sequence[Transformer] | None = None,
                 **kwargs: Any):
        super().__init__(**kwargs)
        if stages is not None:
            self.set(stages=list(stages))

    def __getstate__(self):
        # compiled fused segments (jitted closures, device arrays, locks)
        # don't pickle; drop on serialize — rebuilt on first transform
        d = self.__dict__.copy()
        d.pop("_plan_cache", None)
        d.pop("_plan_lock", None)
        return d

    def transform(self, table: DataTable) -> DataTable:
        from mmlspark_tpu.core import plan
        return plan.execute_stages(list(self.stages or []), table,
                                   cache_host=self)

    def infer_schema(self, schema: Any) -> Any:
        return _fold_state(self.stages, schema, None)[0]

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        return _fold_state(self.stages, schema, n)[1]

    def _infer_state(self, schema: Any, n: int | None
                     ) -> tuple[Any, int | None]:
        return _fold_state(self.stages, schema, n)

"""The schema/metadata protocol linking scorers to evaluators.

The reference encodes two kinds of information in Spark column metadata under
an ``mml`` tag: (a) score-column *roles* — which column holds scores /
scored labels / scored probabilities for a given model, and what kind of
score it is (classification vs regression) — and (b) *categorical levels* for
indexed columns (reference: core/schema/src/main/scala/SparkSchema.scala:23-227,
SchemaConstants.scala:7-43, Categoricals.scala:21-90). Evaluators like
``ComputeModelStatistics`` read these instead of taking column names as
params.

Here the same contract rides the :class:`~mmlspark_tpu.data.table.DataTable`
sidecar ``meta`` dict. Helper functions below are the single point of
truth for key names so scorers and evaluators cannot drift apart.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

# the image-struct contract's canonical definitions live next to the Arrow
# wire format in data.table; re-exported here as the schema-facing names
from mmlspark_tpu.data.table import (  # noqa: F401
    DataTable, IMAGE_FIELDS, K_IMAGE as _K_IMAGE, is_missing as _is_missing,
)


class SchemaConstants:
    """Metadata keys and well-known column names/kinds.

    Analog of reference SchemaConstants.scala:7-43.
    """

    MML_TAG = "mml"

    # column purposes
    SCORES_COLUMN = "scores"
    SCORED_LABELS_COLUMN = "scored_labels"
    SCORED_PROBABILITIES_COLUMN = "scored_probabilities"
    LABEL_COLUMN = "label"
    FEATURES_COLUMN = "features"

    # score-value kinds
    CLASSIFICATION_KIND = "Classification"
    REGRESSION_KIND = "Regression"

    # metadata keys
    K_COLUMN_PURPOSE = "column_purpose"
    K_MODEL_UID = "model_uid"
    K_SCORE_VALUE_KIND = "score_value_kind"
    K_CATEGORICAL_LEVELS = "categorical_levels"
    K_IS_CATEGORICAL = "is_categorical"
    K_IMAGE = _K_IMAGE  # canonical literal lives in data.table
    K_VECTOR_SIZE = "vector_size"


def set_score_column(
    table: DataTable,
    model_uid: str,
    column: str,
    purpose: str,
    kind: str,
) -> DataTable:
    """Stamp a column as a score output of a model (SparkSchema.setScoresColumnName analog)."""
    return table.with_meta(
        column,
        **{SchemaConstants.K_COLUMN_PURPOSE: purpose,
           SchemaConstants.K_MODEL_UID: model_uid,
           SchemaConstants.K_SCORE_VALUE_KIND: kind})


def set_label_column(table: DataTable, model_uid: str, column: str,
                     kind: str) -> DataTable:
    return table.with_meta(
        column,
        **{SchemaConstants.K_COLUMN_PURPOSE: SchemaConstants.LABEL_COLUMN,
           SchemaConstants.K_MODEL_UID: model_uid,
           SchemaConstants.K_SCORE_VALUE_KIND: kind})


def find_score_column(
    table: DataTable,
    purpose: str,
    model_uid: str | None = None,
) -> str | None:
    """Locate the column stamped with a given purpose (optionally per model)."""
    for col in table.columns:
        m = table.column_meta(col)
        if m.get(SchemaConstants.K_COLUMN_PURPOSE) != purpose:
            continue
        if model_uid is not None and m.get(SchemaConstants.K_MODEL_UID) != model_uid:
            continue
        return col
    return None


def get_score_value_kind(table: DataTable, column: str) -> str | None:
    return table.column_meta(column).get(SchemaConstants.K_SCORE_VALUE_KIND)


# ---- categorical levels (Categoricals.scala analog) ----

def set_categorical_levels(
    table: DataTable, column: str, levels: Sequence[Any]
) -> DataTable:
    return table.with_meta(
        column,
        **{SchemaConstants.K_IS_CATEGORICAL: True,
           SchemaConstants.K_CATEGORICAL_LEVELS: list(levels)})


def get_categorical_levels(table: DataTable, column: str) -> list[Any] | None:
    m = table.column_meta(column)
    if not m.get(SchemaConstants.K_IS_CATEGORICAL):
        return None
    return m.get(SchemaConstants.K_CATEGORICAL_LEVELS)


def is_categorical(table: DataTable, column: str) -> bool:
    return bool(table.column_meta(column).get(SchemaConstants.K_IS_CATEGORICAL))


# ---- image columns (ImageSchema analog) ----
# An image cell is a dict with the IMAGE_FIELDS keys (canonical definition
# in data.table, next to the Arrow wire format): decoded HWC uint8 BGR
# bytes in ``data`` (reference: core/schema/src/main/scala/
# ImageSchema.scala:12-17 uses (path, height, width, type, bytes)).


def make_image(path: str, array_hwc: np.ndarray) -> dict[str, Any]:
    a = np.ascontiguousarray(array_hwc, dtype=np.uint8)
    if a.ndim == 2:
        a = a[:, :, None]
    return {"path": path, "height": a.shape[0], "width": a.shape[1],
            "channels": a.shape[2], "data": a}


def is_image_column(table: DataTable, column: str) -> bool:
    if table.column_meta(column).get(SchemaConstants.K_IMAGE):
        return True
    col = table[column]
    if col.dtype != object:
        return False
    # probe the first NON-MISSING cell: a leading None/NaN (a failed
    # decode, a missing row) must not hide an otherwise-image column
    for v in col:
        if _is_missing(v):
            continue
        if isinstance(v, dict):
            return set(IMAGE_FIELDS).issubset(v.keys())
        return False
    return False


def mark_image_column(table: DataTable, column: str) -> DataTable:
    return table.with_meta(column, **{SchemaConstants.K_IMAGE: True})


def find_unused_column_name(table: DataTable, base: str) -> str:
    """DatasetExtensions.findUnusedColumnName analog."""
    name = base
    i = 1
    while name in table:
        name = f"{base}_{i}"
        i += 1
    return name

"""Pipeline execution planner — fuse adjacent device stages into one program.

The host pipeline walks stages one at a time, so a device-heavy chain
(image transform → featurize → score) pays a host↔device round-trip per
stage — and through the driver's tunnel each crossing costs a ~50–110 ms
RTT plus the ~45–53 MB/s incompressible-upload floor (PERF_NOTES), making
crossings the dominant cost. The planner partitions a stage list into
maximal runs of :class:`~mmlspark_tpu.core.stage.DeviceStage`-capable
stages and compiles each run into ONE jitted composite: a single H2D
upload per minibatch, one fused XLA program, and one async-windowed D2H
fetch round (the ``copy_to_host_async``/``max_inflight`` software pipeline
lifted out of ``JaxModel.transform`` into :func:`pipeline_minibatches`).

Fallback rules (also documented in docs/device_stages.md):

* a stage that is not a ``DeviceStage``, or whose ``device_fn`` declines
  the incoming :class:`~mmlspark_tpu.core.stage.ArrayMeta`, runs on host;
* a segment needs ≥ 2 consecutive device-capable stages — a lone device
  stage keeps its own (already-optimized) ``transform`` path;
* entry coercion is strict: rows must be non-missing and share one
  shape/dtype, else the whole segment falls back to the host path;
* every column a fused run writes is materialized from the same composite
  program (tuple outputs, fetched in the same async window), so the fused
  table is column-for-column identical to the stage-by-stage result.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Iterator

import numpy as np

from mmlspark_tpu.core import config
from mmlspark_tpu.core.logging_utils import get_logger, timed
from mmlspark_tpu.core.schema import is_image_column
from mmlspark_tpu.core.stage import ArrayMeta, DeviceOp, DeviceStage
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.obs import device as _obs_dev
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span

_log = get_logger(__name__)


# ---- fixed-shape minibatching (moved here from models.jax_model so the
#      bridge, JaxModel, and fused segments share one definition) ----

def minibatches(batch: np.ndarray, size: int
                ) -> Iterator[tuple[np.ndarray, int]]:
    """Yield fixed-shape minibatches; the tail is zero-padded to ``size``.

    Fixed shapes mean XLA compiles one program total — the analog of the
    reference's re-batching iterator (CNTKModel.scala:51-88) designed for
    the compilation model instead of JNI marshalling.
    """
    n = len(batch)
    for start in range(0, n, size):
        chunk = batch[start:start + size]
        valid = len(chunk)
        if valid < size:
            pad = np.zeros((size - valid,) + chunk.shape[1:], chunk.dtype)
            chunk = np.concatenate([chunk, pad])
        yield chunk, valid


# ---- the H2D / D2H crossing points. Every device entry and exit of the
#      minibatch pipeline goes through these two functions, so crossing
#      counts are observable: tools/perf_smoke.py monkeypatches them, and
#      the obs registry counts them (plan.h2d_uploads / plan.h2d_bytes /
#      plan.d2h_fetches, plus one plan.h2d_shapes series per distinct
#      upload shape — the recompile observable) when tracing is on ----

def _upload(chunk: np.ndarray, target: Any) -> Any:
    """ONE host→device transfer of one minibatch."""
    import jax
    if _obs_rt._enabled:
        nbytes = int(getattr(chunk, "nbytes", 0))
        shape = getattr(chunk, "shape", None)
        reg = _obs_registry()
        reg.counter("plan.h2d_uploads").add()
        reg.counter("plan.h2d_bytes").add(nbytes)
        if shape is not None:
            reg.counter("plan.h2d_shapes",
                        shape=str(tuple(shape))).add()
        with _obs_span("plan/h2d", "plan", {"bytes": nbytes}):
            return jax.device_put(chunk, target)
    return jax.device_put(chunk, target)


def _issue_fetch(outs: tuple) -> None:
    """ONE async device→host fetch round for one minibatch's outputs."""
    if _obs_rt._enabled:
        _obs_registry().counter("plan.d2h_fetches").add()
    for o in outs:
        o.copy_to_host_async()


def train_commit(chunk: np.ndarray, target: Any) -> Any:
    """ONE train-batch H2D commit, through the planner's upload seam.

    The train input pipeline (``train/loop.py`` commit closures, running
    on the ``DeviceLoader`` worker) routes its transfers here so the
    train path's crossings and bytes land in the SAME observable —
    ``count_crossings`` patches and the obs registry counters — as the
    pipeline executor's. The thin-wire preprocessing gate
    (``tools/perf_smoke.py check_train_device_preprocess``) reads its
    ≥4× byte reduction off exactly this seam."""
    return _upload(chunk, target)


class CrossingCounter:
    """Tally of device crossings observed by :func:`count_crossings`."""

    def __init__(self) -> None:
        self.uploads = 0        # H2D transfers (one per minibatch)
        self.fetches = 0        # D2H fetch rounds (one per minibatch)
        self.upload_bytes = 0   # total H2D payload — fusion ships the
        #                         thinnest (entry) form, e.g. uint8 pixels
        #                         instead of f32 features
        self.upload_shapes: set = set()  # distinct batch shapes entering the
        #                         device — for a fixed program each new shape
        #                         is one XLA compile, so this set is the
        #                         recompile observable (serve's bucket gate)


@contextlib.contextmanager
def count_crossings():
    """Count H2D uploads and D2H fetch rounds issued by the minibatch
    pipeline — the observability hook behind tools/perf_smoke.py and the
    bench's crossing metrics. Patches this module's ``_upload`` /
    ``_issue_fetch`` seams, so it sees JaxModel's own path and fused
    segments alike. Not thread-safe; use from single-threaded callers."""
    global _upload, _issue_fetch
    counter = CrossingCounter()
    orig_upload, orig_fetch = _upload, _issue_fetch

    def counting_upload(chunk, target):
        counter.uploads += 1
        counter.upload_bytes += int(getattr(chunk, "nbytes", 0))
        shape = getattr(chunk, "shape", None)
        if shape is not None:
            counter.upload_shapes.add(tuple(shape))
        return orig_upload(chunk, target)

    def counting_fetch(outs):
        counter.fetches += 1
        return orig_fetch(outs)

    _upload, _issue_fetch = counting_upload, counting_fetch
    try:
        yield counter
    finally:
        _upload, _issue_fetch = orig_upload, orig_fetch


def _windowed_dispatch(fn: Callable, dev_params: Any, batch: np.ndarray,
                       size: int, target: Any, max_inflight: int,
                       label: str | None = None
                       ) -> tuple[list, list, Callable[[], None]]:
    """The ONE definition of the upload → call → async-fetch → bounded-
    window discipline, shared by batch execution
    (:func:`pipeline_minibatches`) and the serving dispatch entry
    (:func:`dispatch_segment`). Dispatches every minibatch, draining
    device-resident outputs to ``max_inflight`` as it goes; returns
    ``(pieces, shapes, drain_rest)`` where ``pieces`` accumulates one
    ``[trimmed host array per output]`` list per drained chunk (in chunk
    order), ``shapes`` is the observed upload shapes, and ``drain_rest()``
    blocks until the window is empty — callers choose when to pay it.
    ``label`` names the segment for device attribution
    (:mod:`mmlspark_tpu.obs.device`) when that pillar is enabled."""
    window: deque = deque()
    pieces: list[list[np.ndarray]] = []
    shapes: list[tuple] = []
    inflight = max(2, int(max_inflight))

    def drain_one() -> None:
        outs, valid = window.popleft()
        with _obs_span("plan/d2h", "plan"):
            host = [np.asarray(o)[:valid] for o in outs]
        if _obs_rt._enabled:
            _obs_registry().counter("plan.d2h_bytes").add(
                sum(int(h.nbytes) for h in host))
        pieces.append(host)

    for chunk, valid in minibatches(batch, size):
        shapes.append(tuple(chunk.shape))
        # labels built only when tracing: the disabled path allocates
        # nothing beyond the span() call itself (perf_smoke's < 2% gate)
        attrib = _obs_rt._enabled and _obs_dev._enabled
        labels = ({"shape": str(tuple(chunk.shape))}
                  if _obs_rt._enabled else None)
        with _obs_span("plan/dispatch", "plan", labels):
            committed = _upload(chunk, target)
            if attrib:
                # device attribution: detect a fresh XLA compile via
                # compile-cache growth around the call and attribute
                # its time + cost/memory analyses (obs/device.py)
                cache_before = _obs_rt.jit_cache_size(fn)
                t_call = time.perf_counter()
            outs = fn(dev_params, committed)
            if attrib:
                dur_call = time.perf_counter() - t_call
            if not isinstance(outs, tuple):
                outs = (outs,)
            _issue_fetch(outs)
        if attrib:
            # outside the dispatch span: cost capture AOT-recompiles the
            # program once per entry shape, and that second compile must
            # not inflate the compute side of device_time_split()
            _obs_dev.note_dispatch(fn, dev_params, chunk, label,
                                   cache_before, dur_call)
        window.append((outs, valid))
        # drain to inflight-1 so at most max_inflight minibatch outputs are
        # ever device-resident (the documented HBM bound)
        while len(window) >= inflight:
            drain_one()

    def drain_rest() -> None:
        while window:
            drain_one()

    return pieces, shapes, drain_rest


def _assemble_outputs(pieces: list) -> list[np.ndarray]:
    """Per-chunk ``pieces`` → one concatenated host array per output."""
    if not pieces:
        return []
    return [np.concatenate([p[k] for p in pieces])
            if len(pieces) > 1 else pieces[0][k]
            for k in range(len(pieces[0]))]


def pipeline_minibatches(fn: Callable, dev_params: Any, batch: np.ndarray,
                         size: int, target: Any, max_inflight: int,
                         label: str | None = None) -> list[np.ndarray]:
    """Run ``fn(dev_params, minibatch)`` over ``batch`` with the three-stage
    software pipeline: upload of batch i+1 and device→host copy of batch
    i-1 both overlap compute of batch i (async dispatch +
    ``copy_to_host_async``), so wall clock ≈ max(H2D, compute, D2H), not
    their sum. The deque caps device-resident outputs at ``max_inflight``
    minibatches, bounding HBM on very large tables.

    ``fn`` may return one array or a tuple (a fused segment materializes
    every column its stages write). Returns one trimmed, concatenated host
    array per output.
    """
    pieces, _shapes, drain_rest = _windowed_dispatch(
        fn, dev_params, batch, size, target, max_inflight, label=label)
    drain_rest()
    return _assemble_outputs(pieces)


# ---- segment entry: host column → one stacked device-ready array ----

def stack_image_column(col: np.ndarray
                       ) -> tuple[np.ndarray, list[str]] | None:
    """Stack an image-struct column into one ``[N,H,W,C]`` uint8 batch via a
    single bulk copy; returns ``(batch, paths)`` or None when rows are
    missing, ragged, or not uint8 (host fallback)."""
    datas, paths = [], []
    for v in col:
        if not isinstance(v, dict):
            return None
        d = np.asarray(v["data"])
        if d.ndim == 2:
            d = d[:, :, None]
        datas.append(d)
        paths.append(v.get("path", ""))
    if not datas:
        return None
    shape, dtype = datas[0].shape, datas[0].dtype
    if dtype != np.uint8 or any(
            d.shape != shape or d.dtype != dtype for d in datas):
        return None
    return np.stack(datas), paths


def _entry_meta(table: DataTable, col: str) -> ArrayMeta | None:
    """Cheap first-row probe used at planning time; the full (validated)
    coercion happens in :func:`_coerce_entry` at execution time."""
    if col not in table or len(table) == 0:
        return None
    if is_image_column(table, col):
        v = table[col][0]
        if not isinstance(v, dict):
            return None
        d = np.asarray(v["data"])
        if d.dtype != np.uint8:
            return None
        shape = d.shape if d.ndim == 3 else d.shape + (1,)
        return ArrayMeta(tuple(shape), "uint8", is_image=True)
    arr = table[col]
    if arr.dtype == object:
        first = arr[0]
        if first is None:
            return None
        f = np.asarray(first)
        if not np.issubdtype(f.dtype, np.number):
            return None
        dt = "uint8" if f.dtype == np.uint8 else "float32"
        return ArrayMeta((int(f.size),), dt)
    if not np.issubdtype(arr.dtype, np.number):
        return None
    return ArrayMeta((1,), "float32")


def _coerce_entry(table: DataTable, col: str, meta: ArrayMeta
                  ) -> tuple[np.ndarray, dict] | None:
    """Materialize the segment's entry column as one contiguous array
    matching ``meta``; None on any mismatch (segment falls back to host)."""
    if meta.is_image:
        stacked = stack_image_column(table[col])
        if stacked is None:
            return None
        batch, paths = stacked
        if batch.shape[1:] != tuple(meta.shape):
            return None
        return batch, {"paths": paths}
    try:
        batch = table.column_matrix(col, dtype=np.dtype(meta.dtype))
    except (TypeError, ValueError):
        return None
    if batch.shape[1:] != tuple(meta.shape):
        return None
    return batch, {}


# ---- planning: greedy maximal runs of device-capable stages ----

# device_fn results memoized per stage (a WeakKeyDictionary so nothing
# lands in stage __dict__s, keeping pickling untouched): planning runs on
# every transform call, and a model stage's device_fn traces the forward
# with jax.eval_shape — per-chunk streaming must not re-trace when the
# stage config and incoming meta are unchanged
_DEVICE_FN_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _stage_device_fn(s: DeviceStage, meta: ArrayMeta,
                     mesh: Any = None) -> DeviceOp | None:
    """The stage's device op for ``meta``, memoized.

    A stage whose computation depends on the concrete mesh (e.g. a
    pipeline-parallel stage wrapping
    :func:`~mmlspark_tpu.parallel.pipeline.pipeline_apply` — its
    collectives name mesh axes over specific devices) implements the
    optional ``device_fn_mesh(meta, mesh)`` hook; the planner calls it
    with the segment's resolved mesh at compile/verify time and falls
    back to the plain ``device_fn`` during mesh-less planning probes
    (shape inference only — the op's metas must match either way)."""
    fn_mesh = getattr(s, "device_fn_mesh", None)
    key = (s.device_cache_token(), meta,
           None if mesh is None or fn_mesh is None else _mesh_key(mesh))
    hit = _DEVICE_FN_MEMO.get(s)
    if hit is not None and hit[0] == key:
        return hit[1]
    if fn_mesh is not None and mesh is not None:
        op = fn_mesh(meta, mesh)
    else:
        op = s.device_fn(meta)
    _DEVICE_FN_MEMO[s] = (key, op)
    return op

class _Segment:
    """A maximal run of device-capable stages rooted at ``stages[start]``."""

    def __init__(self, start: int, stages: list, entry_col: str,
                 entry_meta: ArrayMeta, metas_in: list[ArrayMeta],
                 out_cols: list[str], emitters: dict[str, int],
                 out_metas: dict[str, ArrayMeta], mesh: Any = None,
                 shard_params: Callable | None = None,
                 precision: Any = None):
        self.start = start
        self.stages = stages
        self.entry_col = entry_col
        self.entry_meta = entry_meta
        self.metas_in = metas_in          # per-stage input meta
        self.out_cols = out_cols          # first-write order
        self.emitters = emitters          # out col → index of last writer
        self.out_metas = out_metas        # out col → final meta
        self.mesh = mesh                  # explicit mesh override (sharded
        #                                   serving: a replica's sub-mesh)
        self.shard_params = shard_params  # (mesh, params_tuple) → shardings
        self.precision = precision        # PrecisionPolicy | None (serve
        #                                   low-precision pass; None = f32)

    @property
    def end(self) -> int:
        return self.start + len(self.stages)


def collect_segment(stages: list, i: int,
                    meta_of: Callable[[str], ArrayMeta | None],
                    explain: list | None = None,
                    min_stages: int = 2, mesh: Any = None,
                    shard_params: Callable | None = None,
                    precision: Any = None) -> _Segment | None:
    """Root a maximal device segment at ``stages[i]``, resolving the entry
    column's layout through ``meta_of`` (a concrete-table probe at execution
    time; an abstract :class:`~mmlspark_tpu.analysis.info.TableSchema`
    lookup when the pre-flight analyzer replays this exact logic with no
    data). ``explain``, when given, collects human-readable reasons the
    segment broke or never formed — the device-plan audit's trace.

    ``min_stages`` defaults to 2 (a lone device stage keeps its own
    already-optimized ``transform`` path in batch execution); the serving
    entry (:func:`dispatch_segment` via :func:`transform_async`) passes 1,
    because there the win is the *asynchronous single-batch dispatch*, which
    a lone model stage benefits from just as much as a fused run.

    ``mesh`` overrides the segment's inference mesh — the sharded-serving
    entry passes a replica's sub-mesh (DP-replica fan-out) or a
    model-parallel tp/pp mesh here instead of the stage-declared/default
    layout. ``shard_params`` optionally overrides param placement:
    ``(mesh, params_tuple) → shardings pytree`` (default: the generic
    :func:`mmlspark_tpu.parallel.mesh.param_shardings` rules plus any
    per-stage ``device_param_rules``). ``precision`` pins the segment's
    :class:`~mmlspark_tpu.core.precision.PrecisionPolicy` (bf16
    activations / int8 weight-only — the serve low-precision pass,
    applied by :func:`segment_composite`); None keeps the f32 plan."""

    def note(msg: str) -> None:
        if explain is not None:
            explain.append(msg)

    s0 = stages[i]
    if not isinstance(s0, DeviceStage):
        note(f"stage {i} ({type(s0).__name__}) is not a DeviceStage")
        return None
    entry_col = s0.device_input_col()
    if entry_col is None:
        note(f"stage {i} ({type(s0).__name__}) declines device execution "
             "for its current configuration (no device input column)")
        return None
    entry_meta = meta_of(entry_col)
    if entry_meta is None:
        note(f"stage {i} ({type(s0).__name__}): entry column "
             f"{entry_col!r} has no device-coercible layout "
             "(missing, ragged, non-numeric, or unknown shape)")
        return None
    env: dict[str, ArrayMeta] = {entry_col: entry_meta}
    seg_stages: list = []
    metas_in: list[ArrayMeta] = []
    out_cols: list[str] = []
    emitters: dict[str, int] = {}
    out_metas: dict[str, ArrayMeta] = {}
    j = i
    while j < len(stages):
        s = stages[j]
        if not isinstance(s, DeviceStage):
            note(f"segment breaks at stage {j}: {type(s).__name__} "
                 "is not a DeviceStage")
            break
        in_col = s.device_input_col()
        out_col = s.device_output_col()
        if in_col is None or out_col is None:
            note(f"segment breaks at stage {j}: {type(s).__name__} "
                 "declines device execution (no device input/output column)")
            break
        if in_col not in env:
            note(f"segment breaks at stage {j}: input column {in_col!r} "
                 "is not device-resident (host-produced columns are never "
                 "re-uploaded mid-run)")
            break
        op = _stage_device_fn(s, env[in_col])
        if op is None:
            note(f"segment breaks at stage {j}: "
                 f"{type(s).__name__}.device_fn declined the incoming "
                 f"layout {env[in_col]}")
            break
        metas_in.append(env[in_col])
        seg_stages.append(s)
        env[out_col] = op.out_meta
        if out_col not in emitters:
            out_cols.append(out_col)
        emitters[out_col] = j - i
        out_metas[out_col] = op.out_meta
        j += 1
    if len(seg_stages) < max(1, int(min_stages)):
        if len(seg_stages) == 1:
            note(f"stage {i} ({type(s0).__name__}) is a lone device stage "
                 "(a segment needs >= 2): it keeps its own transform path")
        return None
    return _Segment(i, seg_stages, entry_col, entry_meta, metas_in,
                    out_cols, emitters, out_metas, mesh=mesh,
                    shard_params=shard_params, precision=precision)


def _collect_segment(stages: list, i: int, table: DataTable
                     ) -> _Segment | None:
    return collect_segment(stages, i, lambda col: _entry_meta(table, col))


def describe_plan(stages: list, table: DataTable) -> list[tuple[str, list]]:
    """The segment structure the executor would use on ``table``:
    ``[("device"|"host", [stage, ...]), ...]``. Purely for introspection
    (tests, bench reporting) — segments whose entry depends on a not-yet-run
    host stage show as host here but may still fuse at execution time."""
    out: list[tuple[str, list]] = []
    i = 0
    while i < len(stages):
        seg = _collect_segment(stages, i, table)
        if seg is None:
            out.append(("host", [stages[i]]))
            i += 1
        else:
            out.append(("device", list(seg.stages)))
            i = seg.end
    return out


# ---- compilation + execution ----

def _segment_tokens(seg: _Segment) -> tuple:
    return tuple(s.device_cache_token() for s in seg.stages)


def _segment_mesh(seg: _Segment):
    """The fused run's inference mesh: an explicit per-segment override
    (sharded serving pins each replica's sub-mesh here) wins, then the
    first explicit ``mesh_spec`` among the segment's stages, else DP over
    every local device — the same default JaxModel uses standalone, so
    routing a pipeline through the planner never narrows its data
    parallelism."""
    import jax

    from mmlspark_tpu.parallel import mesh as mesh_lib

    if seg.mesh is not None:
        return seg.mesh
    spec = next((s.mesh_spec for s in seg.stages
                 if getattr(s, "mesh_spec", None)), None)
    return mesh_lib.make_mesh(spec or mesh_lib.MeshSpec(dp=-1),
                              jax.local_devices())


def _mesh_key(mesh: Any) -> tuple:
    """Hashable identity of a mesh for the compiled-segment cache: axis
    layout plus the concrete device assignment (two replicas' sub-meshes
    must never share one compiled entry — each owns its own device-
    resident params)."""
    return (tuple(sorted(mesh.shape.items())),
            tuple(getattr(d, "id", i)
                  for i, d in enumerate(mesh.devices.flat)))


def _segment_param_shardings(seg: _Segment, mesh, params_tuple):
    """Param placement for a fused run on ``mesh``: the segment's explicit
    ``shard_params`` override wins; otherwise the generic
    :func:`~mmlspark_tpu.parallel.mesh.param_shardings` rules (tp
    column-sharding, fsdp zero-sharding, replicate elsewhere — a pure-dp
    mesh replicates everything, the pre-sharded-serving behavior) with any
    per-stage ``device_param_rules(path, leaf)`` hook consulted first.
    ``params_tuple`` has one entry per segment stage, so rule paths are
    ``<stage-idx>/<leaf path>``."""
    from mmlspark_tpu.parallel import mesh as mesh_lib

    if seg.shard_params is not None:
        return seg.shard_params(mesh, params_tuple)
    stage_rules = [getattr(s, "device_param_rules", None)
                   for s in seg.stages]
    if not any(stage_rules):
        return mesh_lib.param_shardings(mesh, params_tuple)

    def rules(path: str, leaf):
        head, _, rest = path.partition("/")
        # tuple indices render as "[k]" (SequenceKey), dict keys as "k"
        fn = stage_rules[int(head.strip("[]"))]
        return fn(rest, leaf) if fn is not None else None

    return mesh_lib.param_shardings(mesh, params_tuple, rules)


def _compile_segment(seg: _Segment) -> tuple:
    """(jitted composite, device params, transfer target, dp extent). The
    composite threads the entry array through every stage op and returns a
    tuple with one array per materialized column, so fusion never changes
    which columns exist — only how many device crossings they cost.
    Params upload once (replicated over the mesh) and live
    device-resident; minibatches commit batch-sharded over the data axes
    (single-device meshes take the plain-placement fast path — sharded
    transfers cost a round-trip per shard through remote-device
    tunnels, PERF_NOTES round 2)."""
    if _obs_rt._enabled:
        names = "→".join(type(s).__name__ for s in seg.stages)
        _obs_registry().counter("plan.segment_compiles").add()
        with _obs_span("plan/compile_segment", "plan", {"stages": names}):
            return _compile_segment_inner(seg)
    return _compile_segment_inner(seg)


def segment_composite(seg: "_Segment", mesh: Any) -> tuple:
    """(composite fn, params tuple) for a fused segment on ``mesh`` —
    the ONE builder of the function this module jits. The SPMD audit
    (``analysis.spmd.plan_segment_composite``) traces the same object,
    so the verified program can never drift from the dispatched one —
    including the low-precision pass: when ``seg.precision`` is an
    active :class:`~mmlspark_tpu.core.precision.PrecisionPolicy`, the
    returned params tuple is the quantized STORAGE form (int8 weights /
    bf16 leaves — what uploads), and the composite dequantizes inside
    the trace, casts float activations to bf16 at every stage boundary,
    and restores each output column to its declared ``ArrayMeta`` dtype
    so ``device_emit`` sees the layout the f32 plan declared."""
    ops: list[DeviceOp] = []
    for s, meta_in in zip(seg.stages, seg.metas_in):
        op = _stage_device_fn(s, meta_in, mesh)
        if op is None:  # config changed between planning and compile
            raise RuntimeError(
                f"{type(s).__name__}.device_fn declined at compile time")
        ops.append(op)

    in_cols = [s.device_input_col() for s in seg.stages]
    out_cols_per_stage = [s.device_output_col() for s in seg.stages]
    policy = seg.precision
    if policy is not None and not policy.active:
        policy = None

    if policy is None:
        def composite(all_params: tuple, x: Any) -> tuple:
            vals = {seg.entry_col: x}
            for k, op in enumerate(ops):
                vals[out_cols_per_stage[k]] = op.fn(all_params[k],
                                                    vals[in_cols[k]])
            return tuple(vals[c] for c in seg.out_cols)

        return composite, tuple(op.params for op in ops)

    from mmlspark_tpu.core import precision as prec

    stored = tuple(prec.quantize_params(op.params, policy) for op in ops)

    def composite(all_params: tuple, x: Any) -> tuple:
        vals = {seg.entry_col: prec.cast_activation(x, policy)}
        for k, op in enumerate(ops):
            p = prec.materialize(all_params[k], policy)
            vals[out_cols_per_stage[k]] = prec.cast_activation(
                op.fn(p, vals[in_cols[k]]), policy)
        return tuple(prec.cast_output(vals[c], seg.out_metas[c].dtype)
                     for c in seg.out_cols)

    return composite, stored


def _maybe_cache_jit(jitted: Any, seg: "_Segment", mesh: Any) -> Any:
    """Wrap a segment's jitted composite in the persistent AOT compile
    cache (core/compile_cache.py) when a cache is installed and every
    stage in the segment fingerprints stably. Programs then load from
    disk per concrete dispatch shape instead of re-compiling; an
    unfingerprintable segment (or no cache) compiles exactly as
    before."""
    from mmlspark_tpu.core import compile_cache as _cc
    cache = _cc.active()
    if cache is None:
        return jitted
    fp = _cc.plan_fingerprint(seg.stages, seg.entry_meta, mesh=mesh,
                              precision=seg.precision)
    if fp is None:
        return jitted
    return _cc.CachedJit(jitted, fp, cache)


def _compile_segment_inner(seg: "_Segment") -> tuple:
    import jax

    from mmlspark_tpu.parallel import mesh as mesh_lib

    mesh = _segment_mesh(seg)
    composite, params_tuple = segment_composite(seg, mesh)
    if mesh.devices.size == 1:
        target = mesh.devices.reshape(-1)[0]
        dev_params = jax.device_put(params_tuple, target)
        fn = _maybe_cache_jit(jax.jit(composite), seg, mesh)
        return fn, dev_params, target, 1

    data = mesh_lib.batch_sharding(mesh)
    # params place by the sharding rules (replicated on a pure-dp mesh —
    # the historical behavior; tp/pp/fsdp serve meshes shard them)
    param_shards = _segment_param_shardings(seg, mesh, params_tuple)
    dev_params = jax.device_put(params_tuple, param_shards)
    fn = jax.jit(composite, in_shardings=(param_shards, data),
                 out_shardings=data)
    fn = _maybe_cache_jit(fn, seg, mesh)
    return fn, dev_params, data, mesh_dp(mesh)


def _segment_minibatch(seg: _Segment) -> tuple[int, int]:
    """(minibatch size, max_inflight) for a fused run: the smallest explicit
    stage setting wins (it is a memory bound), else the config default."""
    sizes = [int(s.minibatch_size) for s in seg.stages
             if getattr(s, "minibatch_size", None)]
    size = min(sizes) if sizes else int(config.get("default_minibatch_size"))
    inflights = [int(s.max_inflight) for s in seg.stages
                 if getattr(s, "max_inflight", None)]
    return size, (min(inflights) if inflights else 8)


def mesh_dp(mesh: Any) -> int:
    """The data extent minibatches must divide over: 1 on a single-device
    mesh (the plain-placement fast path), else the dp×fsdp product. The
    ONE definition shared by the executor and the pre-flight predictors."""
    if mesh.devices.size == 1:
        return 1
    return mesh.shape["dp"] * mesh.shape["fsdp"]


def dp_rounded_minibatch(size: int, dp: int, n_rows: int) -> int:
    """The executor's minibatch sizing: cap at the row count, then round UP
    to a dp multiple (padding covers the excess) so every chip gets rows.
    Shared with the pre-flight crossing predictors so predictions cannot
    drift from execution."""
    return -(-min(int(size), n_rows) // dp) * dp


def predict_segment_minibatches(seg: _Segment, n_rows: int) -> int:
    """How many fixed-shape minibatches a fused run of ``seg`` over
    ``n_rows`` rows costs — one H2D upload and one async D2H fetch round
    each. Same sizing arithmetic as :func:`_run_segment` via the shared
    helpers, without compiling or transferring anything. Note: reading the
    segment's mesh initializes the jax backend (device *enumeration*, not
    execution) — pre-flight callers on shared hosts should pin
    ``JAX_PLATFORMS=cpu``."""
    if n_rows <= 0:
        return 0
    size, _ = _segment_minibatch(seg)
    size = dp_rounded_minibatch(size, mesh_dp(_segment_mesh(seg)), n_rows)
    return -(-n_rows // size)


# compiled segments kept per cache_host; LRU-capped so streaming sources
# with many distinct entry shapes cannot pin an unbounded number of
# device-resident param copies (each evicted entry releases its device
# tree — the bound _compiled_apply enforces by refreshing in place)
_PLAN_CACHE_MAX = 8


def _cached_segment(seg: _Segment, cache_host: Any) -> tuple:
    """(jitted composite, device params, target, dp) for ``seg``, through
    ``cache_host``'s LRU-capped compiled-segment cache when one is given.
    Shared by batch execution (:func:`_run_segment`) and the serving
    dispatch entry (:func:`dispatch_segment`), so an online server and
    offline ``transform`` calls on the same model reuse ONE jitted
    composite and one device-resident param upload."""
    if cache_host is None:
        return _compile_segment(seg)
    key = (tuple(id(s) for s in seg.stages), seg.entry_col, seg.entry_meta,
           None if seg.mesh is None else _mesh_key(seg.mesh),
           None if seg.shard_params is None else id(seg.shard_params),
           # precision is program identity: an f32 and an int8w serving
           # of one model never share a compiled entry or device params
           None if seg.precision is None or not seg.precision.active
           else seg.precision.cache_token)
    lock = cache_host.__dict__.setdefault("_plan_lock", threading.Lock())
    with lock:
        store = cache_host.__dict__.setdefault("_plan_cache", {})
        entry = store.get(key)
        tokens = _segment_tokens(seg)
        if entry is not None and entry[0] != tokens:
            entry = None  # stage config changed: recompile
        if entry is None:
            # pin the stage objects (and the shard_params override) so
            # their id()-based key components cannot be reused
            entry = (tokens, _compile_segment(seg),
                     (tuple(seg.stages), seg.shard_params))
        else:
            del store[key]  # re-insert: LRU order = insertion order
        store[key] = entry
        while len(store) > _PLAN_CACHE_MAX:
            store.pop(next(iter(store)))
    return entry[1]


def _run_segment(seg: _Segment, table: DataTable,
                 cache_host: Any) -> DataTable | None:
    """Execute a fused segment; None if entry coercion fails (host path)."""
    coerced = _coerce_entry(table, seg.entry_col, seg.entry_meta)
    if coerced is None:
        return None
    batch, ctx = coerced
    size, max_inflight = _segment_minibatch(seg)
    fn, dev_params, target, dp = _cached_segment(seg, cache_host)

    # minibatch must divide over the data axes (shared sizing helper)
    size = dp_rounded_minibatch(size, dp, len(batch))

    names = "→".join(type(s).__name__ for s in seg.stages)
    with timed(f"FusedSegment[{names}]", _log, len(table)):
        outs = pipeline_minibatches(fn, dev_params, batch, size, target,
                                    max_inflight, label=names)
    for col, values in zip(seg.out_cols, outs):
        emitter = seg.stages[seg.emitters[col]]
        table = emitter.device_emit(table, values, seg.out_metas[col], ctx)
    return table


# ---- single-batch serving entry (the online model server's dispatch) ----

class PendingTable:
    """Handle for an asynchronously dispatched transform.

    ``result()`` blocks on the device→host fetch, emits the output columns,
    and returns the finished :class:`DataTable`; it is idempotent. A
    PendingTable built from an already-materialized table (the host
    fallback) returns immediately. ``shapes`` holds the batch shapes
    actually uploaded to the device (empty for the host path) — the
    *observed* recompile surface serving stats report, as opposed to the
    caller's intended bucket. Single-consumer: the serve batcher's
    in-flight window owns each handle."""

    __slots__ = ("_table", "_finish", "shapes")

    def __init__(self, table: DataTable | None = None,
                 finish: Callable[[], DataTable] | None = None,
                 shapes: tuple = ()):
        self._table = table
        self._finish = finish
        self.shapes = tuple(shapes)

    @property
    def dispatched(self) -> bool:
        """True while device work is still outstanding."""
        return self._finish is not None

    def result(self) -> DataTable:
        if self._finish is not None:
            self._table = self._finish()
            self._finish = None
        return self._table


def dispatch_segment(seg: _Segment, table: DataTable,
                     cache_host: Any
                     ) -> tuple[Callable[[], DataTable], tuple] | None:
    """Asynchronously dispatch ``seg`` over one packed (bucket-quantized)
    batch; returns ``(finish, observed upload shapes)``.

    The single-batch segment entry behind the online server. A batch at or
    below the stages' minibatch bound — the common case, since bucket
    ladders are sized to fit — is ONE minibatch: one H2D upload, one
    program call, one async D2H fetch round, and the call returns as soon
    as the device work is *issued* (JAX async dispatch +
    ``copy_to_host_async``), so the serve batcher can pack batch i+1 while
    the device computes batch i. A batch larger than the stages' declared
    ``minibatch_size`` (a memory bound — see :func:`_segment_minibatch`)
    is chunked at that bound with the usual ``max_inflight`` window, so
    serving can never exceed the HBM envelope batch execution honors.
    Because chunk sizes derive only from (bucket, bound, dp), compiled
    shapes stay bounded by the bucket ladder. Returns a ``finish()`` that
    blocks, trims the padding, and emits the output columns; ``None`` when
    entry coercion declines (host path)."""
    coerced = _coerce_entry(table, seg.entry_col, seg.entry_meta)
    if coerced is None:
        return None
    batch, ctx = coerced
    fn, dev_params, target, dp = _cached_segment(seg, cache_host)
    bound, max_inflight = _segment_minibatch(seg)
    size = dp_rounded_minibatch(min(bound, len(batch)), dp, len(batch))
    labels = {"rows": len(batch)} if _obs_rt._enabled else None
    seg_label = ("→".join(type(s).__name__ for s in seg.stages)
                 if _obs_rt._enabled else None)
    with _obs_span("plan/serve_dispatch", "plan", labels):
        pieces, shapes, drain_rest = _windowed_dispatch(
            fn, dev_params, batch, size, target, max_inflight,
            label=seg_label)

    def finish() -> DataTable:
        drain_rest()
        host = _assemble_outputs(pieces)
        out = table
        for k, col in enumerate(seg.out_cols):
            emitter = seg.stages[seg.emitters[col]]
            out = emitter.device_emit(out, host[k], seg.out_metas[col],
                                      ctx)
        return out

    return finish, tuple(shapes)


def transform_async(stages: list, table: DataTable,
                    cache_host: Any = None, mesh: Any = None,
                    shard_params: Callable | None = None,
                    precision: Any = None) -> PendingTable:
    """Run a fitted-transformer list over one packed batch, dispatching the
    *trailing* device segment asynchronously (the serving execution engine).

    Semantics match :func:`execute_stages` exactly — same planning, same
    fallback rules, same compiled-segment cache — except that when the
    stage list *ends* in a device-capable segment (of any length ≥ 1,
    including a lone model stage), that segment is dispatched via
    :func:`dispatch_segment` and the returned :class:`PendingTable` is
    still in flight: host packing of the next batch overlaps this batch's
    device compute, and ``result()`` performs the blocking fetch.

    ``mesh``/``shard_params`` pin the device segments to an explicit mesh
    and param placement (see :func:`collect_segment`) — the sharded
    serving entry: a DP replica's sub-mesh, or a tp/pp model-parallel
    layout for a model too big for one chip. ``precision`` pins every
    device segment's low-precision policy (bf16 activations / int8
    weight-only — :mod:`mmlspark_tpu.core.precision`); the offline
    ``execute_stages`` path never passes one, so batch transforms stay
    f32."""
    stages = list(stages)
    i = 0
    while i < len(stages):
        seg = None
        if len(table):
            seg = collect_segment(stages, i,
                                  lambda col: _entry_meta(table, col),
                                  min_stages=1, mesh=mesh,
                                  shard_params=shard_params,
                                  precision=precision)
        if seg is not None:
            if seg.end == len(stages):
                dispatched = dispatch_segment(seg, table, cache_host)
                if dispatched is not None:
                    finish, shapes = dispatched
                    return PendingTable(finish=finish, shapes=shapes)
            elif len(seg.stages) >= 2:
                fused = _run_segment(seg, table, cache_host)
                if fused is not None:
                    table = fused
                    i = seg.end
                    continue
        table = stages[i].transform(table)
        i += 1
    return PendingTable(table=table)


def execute_stages(stages: list, table: DataTable,
                   cache_host: Any = None) -> DataTable:
    """Run a fitted-transformer list over ``table``, fusing maximal runs of
    device-capable stages (the :class:`PipelineModel` execution engine).

    ``cache_host`` (typically the owning PipelineModel) carries the
    compiled-segment cache across calls, so streaming callers (the Arrow
    bridge, ``transform_stream``) pay compile + param upload once.
    """
    i = 0
    while i < len(stages):
        seg = None
        if len(table):
            seg = _collect_segment(stages, i, table)
        if seg is not None:
            fused = _run_segment(seg, table, cache_host)
            if fused is not None:
                table = fused
                i = seg.end
                continue
            _log.info("fused segment at stage %d fell back to host "
                      "(entry coercion failed)", i)
        table = stages[i].transform(table)
        i += 1
    return table


# ---- stateful segments (device-resident state across dispatches) ----
#
# Everything above treats a compiled segment as a pure function: params
# upload once, every dispatch streams batch in → batch out, and nothing
# survives on the device between calls. Autoregressive decode breaks
# that shape — the KV-cache is device state that every token step reads
# AND rewrites, and re-uploading it per step would cost
# O(slots·layers·T_max·d) H2D per token. A *stateful segment* is the
# minimal extension: a jitted step function whose first argument is a
# device-resident buffer pytree, compiled with ``donate_argnums=(0,)``
# so XLA reuses the input cache's buffers for the output cache (an
# in-place update, no reallocation), with the rebind of the new state
# serialized under a witnessed lock. The jitted step registers in the
# owner's ``_plan_cache`` under a ``("stateful", name)`` key so
# ``obs.runtime.compiled_programs`` counts its programs on the same
# ladder budget as stateless segments.

class SegmentState:
    """Device-resident buffers owned by a stateful segment.

    ``buffers`` is an arbitrary jax pytree living on the device (for the
    serve plane: the slot-major KV-cache pair
    ``[slots, layers, heads, T_max, d]`` of one replica lane). Reads and
    rebinds go through :meth:`swap` under the witnessed lock — after a
    donated dispatch the OLD buffers are deleted by XLA, so a racing
    reader holding a stale reference would fetch a dead buffer.
    """

    __slots__ = ("name", "_buffers", "_lock")

    def __init__(self, name: str, buffers: Any):
        from mmlspark_tpu.obs.lockwitness import named_lock
        self.name = name
        self._buffers = buffers
        self._lock = named_lock("core.plan.SegmentState._lock")

    @property
    def buffers(self) -> Any:
        with self._lock:
            return self._buffers

    def swap(self, fn: Callable[[Any], tuple]) -> Any:
        """Run ``fn(buffers) -> (new_buffers, out)`` under the lock,
        rebind the state to ``new_buffers``, and return ``out``. The ONE
        mutation point: dispatches that donate the old buffers and reads
        that snapshot them serialize here."""
        with self._lock:
            self._buffers, out = fn(self._buffers)
            return out


def allocate_segment_state(name: str, shapes: dict, target: Any = None,
                           dtype: Any = None) -> SegmentState:
    """Allocate zeroed device buffers for a stateful segment.

    ``shapes`` maps buffer name → shape tuple (all sharing ``dtype``,
    default f32); ``target`` is a device or sharding for
    ``jax.device_put`` (default placement when None). Zero is the right
    init for a KV-cache: the active-slot mask keeps unwritten positions
    out of every attention denominator."""
    import jax
    import jax.numpy as jnp

    dt = jnp.float32 if dtype is None else dtype
    bufs = {k: jnp.zeros(s, dt) for k, s in shapes.items()}
    if target is not None:
        bufs = jax.device_put(bufs, target)
    return SegmentState(name, bufs)


def register_stateful_program(cache_host: Any, name: str, jitted: Any,
                              pinned: Any = None) -> Any:
    """Enter a stateful segment's jitted step into ``cache_host``'s
    compiled-segment cache under a ``("stateful", name)`` key.

    This is what keeps the serve plane's program accounting honest:
    ``obs.runtime.compiled_programs(cache_host)`` walks ``_plan_cache``
    and sums each entry's live jit-cache size, so a decode loop that
    silently retraced per batch size would blow the ladder budget the
    tier-1 gate pins. Stateful entries are pinned outside the LRU window
    (state outlives any bucket traffic pattern): the eviction loop in
    ``_cached_segment`` only pops ``while len > max``, so keep the
    stateful program count small. Returns ``jitted`` for chaining."""
    lock = cache_host.__dict__.setdefault("_plan_lock", threading.Lock())
    with lock:
        store = cache_host.__dict__.setdefault("_plan_cache", {})
        store[("stateful", name)] = (("stateful", name), (jitted,),
                                     (pinned,))
    return jitted


class StatefulSegment:
    """A compiled step function owning :class:`SegmentState`.

    ``step_fn(buffers, *args) -> (new_buffers, out)`` is jitted with the
    buffers donated (``donate_argnums=(0,)`` unless ``donate=False``),
    so each :meth:`dispatch` updates the device state in place — no
    per-step reallocation, no H2D re-upload of the cache. Dispatches
    serialize through :meth:`SegmentState.swap`; the jitted program
    registers on ``cache_host`` (when given) for
    ``compiled_programs`` accounting."""

    __slots__ = ("name", "state", "_jitted")

    def __init__(self, name: str, step_fn: Callable, state: SegmentState,
                 cache_host: Any = None, donate: bool = True,
                 static_argnums: tuple = ()):
        import jax

        self.name = name
        self.state = state
        kwargs: dict = {"static_argnums": tuple(
            n + 1 for n in static_argnums)} if static_argnums else {}
        if donate:
            kwargs["donate_argnums"] = (0,)
        self._jitted = jax.jit(step_fn, **kwargs)
        if cache_host is not None:
            register_stateful_program(cache_host, name, self._jitted,
                                      pinned=state)

    @property
    def jitted(self) -> Any:
        """The jitted step — what the SPMD audit traces and
        ``jit_cache_size`` counts."""
        return self._jitted

    def dispatch(self, *args) -> Any:
        """One step: run the donated program over the current buffers,
        rebind the new buffers, return the step outputs (still device
        arrays — async dispatch; the caller owns the fetch policy)."""
        return self.state.swap(
            lambda bufs: self._jitted(bufs, *args))

"""Core runtime: param DSL, stage/pipeline contracts, schema metadata protocol,
serialization, configuration, and logging.

Analog of the reference's ``src/core/{contracts,schema,serialize,env}``
(reference: core/contracts/src/main/scala/Params.scala,
core/schema/src/main/scala/SparkSchema.scala).
"""

"""Registry loading — import every framework module so all stages register.

The reflection-loading analog of the reference's jar scan
(reference: core/utils/src/main/scala/JarLoadingUtils.scala:17-80, which
URL-classloads every built jar so ``Fuzzing.scala`` and codegen can discover
all Transformer/Estimator classes). Here discovery is import-driven:
``PipelineStage.__init_subclass__`` registers each class into
``STAGE_REGISTRY`` at import time, so walking the package imports is the
whole job.
"""

from __future__ import annotations

import importlib
import pkgutil

from mmlspark_tpu.core.stage import STAGE_REGISTRY


def load_all_modules() -> list[str]:
    """Import every ``mmlspark_tpu`` submodule; returns the module names.

    Idempotent (imports are cached). Modules that fail to import raise —
    a stage module that can't import is a packaging bug, not something to
    skip silently.
    """
    import mmlspark_tpu

    names = []
    for info in pkgutil.walk_packages(mmlspark_tpu.__path__,
                                      prefix="mmlspark_tpu."):
        spec = importlib.util.find_spec(info.name)
        origin = getattr(spec, "origin", None) or ""
        if not (info.ispkg or origin.endswith(".py")):
            continue  # shared libraries (e.g. native/libimgops.so)
        importlib.import_module(info.name)
        names.append(info.name)
    return names


def all_stages(prefix: str = "mmlspark_tpu.") -> dict[str, type]:
    """Class path → class for every registered stage, all modules loaded.

    ``prefix`` restricts to framework stages (the default) — user/test
    stages register too but are not part of the documented API surface.
    Pass ``prefix=""`` for everything.
    """
    load_all_modules()
    return {p: c for p, c in STAGE_REGISTRY.items() if p.startswith(prefix)}

"""Persistent AOT compile cache — XLA programs as pipeline artifacts.

Every serving process today pays full XLA compilation for every
(bucket, model, precision) program at startup, even when an identical
process on the same host compiled the identical program seconds ago.
The reference framework's L6 premise is that accelerator programs are
*reusable pipeline artifacts*, not per-process ephemera; this module
makes that literal: compiled executables are serialized
(``jax.experimental.serialize_executable``) into a content-addressed
on-disk cache so a cold process warm-starts by *deserializing* the
ladder in milliseconds instead of re-tracing and re-compiling it.

Identity — the plan fingerprint
-------------------------------
A cached program is only reusable when everything that could change the
compiled artifact is part of the key. :func:`plan_fingerprint` hashes:

* every stage's :meth:`DeviceStage.device_fingerprint` — a *content*
  identity (weights digest, module structure, simple params), unlike
  ``device_cache_token`` whose ``id()``-based tokens are deliberately
  process-local;
* the segment's entry ``ArrayMeta`` (shape/dtype/is_image);
* the mesh spec (axis sizes + device count + platform — not device
  ids, which are process-local);
* the active ``PrecisionPolicy.cache_token``;
* the jax / jaxlib / backend-platform versions (an XLA upgrade must
  never replay stale programs).

A stage without a stable fingerprint (``device_fingerprint()`` returns
``None``) makes the whole segment unfingerprintable — the plan simply
compiles in memory, exactly as before. Per-call *shapes* are keyed
separately (one on-disk entry per concrete dispatch shape), so one
fingerprint holds the whole bucket ladder.

On-disk layout + integrity (the ``ModelRepo`` discipline)
---------------------------------------------------------
::

    <root>/<fp[:2]>/<fp>/<shape-key>/
        ENTRY.json      # versions, nbytes, sha256 per file
        program.bin     # serialized executable payload
        trees.pkl       # pickled (in_tree, out_tree)

Entries are staged in a hidden temp dir and enter the cache via one
``os.replace`` — a reader sees a whole entry or none. ``ENTRY.json``
carries a sha256 per file; :meth:`CompileCache.load` re-verifies before
deserializing anything, so a torn, truncated, or version-mismatched
entry is a typed :class:`CompileCacheError` → counted refusal +
quarantine + in-memory compile, never a silently-wrong served program.
A publish race is benign: the loser's ``os.replace`` fails against the
winner's directory and the loser adopts the winner's entry. The cache
is bounded by an LRU byte budget (entry dirs are mtime-touched on hit;
oldest evicted first).

Wiring
------
:func:`configure` installs the process-wide cache (``ServeConfig
.compile_cache`` / ``tools/serve.py --compile-cache`` /
``MMLSPARK_TPU_COMPILE_CACHE``); ``core/plan._compile_segment_inner``
wraps its jitted composite in :class:`CachedJit` whenever a cache is
active and the segment fingerprints. ``CachedJit`` mimics the jit at
the two seams the repo touches — ``__call__`` and ``_cache_size()``
(the obs compiled-program hook) — so every existing
``programs <= len(buckets)`` gate keeps counting loaded programs.
Counters: ``plan.compile_cache.{hits,misses,puts,bytes,load_ms}``
(obs registry, when enabled) mirrored by a plain ``stats`` dict that is
always live. See docs/serving.md §compile cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
import time
from typing import Any

from mmlspark_tpu.core import config
from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger(__name__)

ENTRY_FILE = "ENTRY.json"
PROGRAM_FILE = "program.bin"
TREES_FILE = "trees.pkl"

#: default LRU byte budget (``compile_cache_bytes`` config)
DEFAULT_MAX_BYTES = 1 << 30


class CompileCacheError(RuntimeError):
    """A cache entry that must not be served: torn, corrupt (digest
    mismatch), or compiled by a different jax/jaxlib/backend. The
    caller falls back to an in-memory compile; the entry is
    quarantined (removed) so the fresh program can be re-published."""


def _faults():
    # lazy: core must not import the serve plane at module level (the
    # models/repo.py direction discipline); the fault seam costs one
    # import-cache lookup only when a put actually runs
    from mmlspark_tpu.serve import faults
    return faults


def _obs_counter(name: str, n: float = 1.0) -> None:
    """Mirror a stat into the obs registry when the pillar is on."""
    try:
        from mmlspark_tpu.obs import runtime as _rt
        if not _rt._enabled:
            return
        from mmlspark_tpu.obs.metrics import registry
        registry().counter(f"plan.compile_cache.{name}").add(n)
    except Exception:  # pragma: no cover - observability is best-effort
        pass


def runtime_versions() -> dict:
    """The toolchain identity baked into every fingerprint and entry:
    a program compiled by a different jax/jaxlib/backend is invalid."""
    import jax
    jaxlib_v = ""
    try:
        import jaxlib
        jaxlib_v = getattr(getattr(jaxlib, "version", None),
                           "__version__", "") or ""
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        pass
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - no devices at all
        backend = "unknown"
    return {"jax": jax.__version__, "jaxlib": jaxlib_v,
            "backend": backend}


def params_digest(params: Any) -> str:
    """Content digest of a params pytree: sha256 over the tree
    structure plus every leaf's shape, dtype, and bytes. This is the
    cross-process identity of a model's weights — the stable
    counterpart of the ``id()``-based in-process cache token."""
    import jax
    import numpy as np
    leaves, treedef = jax.tree_util.tree_flatten(params)
    h = hashlib.sha256(repr(treedef).encode())
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def bundle_digest(bundle: Any) -> str:
    """Content digest of a :class:`ModelBundle` (module structure +
    weights + preprocess + input spec). Memoized on the bundle object —
    bundles are effectively frozen after load, and hashing ResNet50
    weights on every fingerprint would dominate the compile it saves."""
    memo = getattr(bundle, "_content_digest", None)
    if memo is not None:
        return memo
    h = hashlib.sha256()
    h.update(repr((bundle.name, type(bundle.module).__name__,
                   repr(bundle.module), bundle.input_spec,
                   tuple(bundle.output_names),
                   bundle.preprocess)).encode())
    h.update(params_digest(bundle.params).encode())
    digest = h.hexdigest()
    try:
        bundle._content_digest = digest
    except Exception:  # pragma: no cover - frozen/slotted bundle
        pass
    return digest


def plan_fingerprint(stages: Any, entry_meta: Any, mesh: Any = None,
                     precision: Any = None) -> str | None:
    """The cache key for one device segment, or ``None`` when any stage
    lacks a stable content fingerprint (→ in-memory compile, exactly
    the pre-cache behavior). Derivable statically: stages + schema
    entry meta are enough — no data, no devices, no compilation."""
    parts = []
    for s in stages:
        fp_fn = getattr(s, "device_fingerprint", None)
        if fp_fn is None:
            return None
        try:
            fp = fp_fn()
        except Exception:
            _log.warning("compile cache: %s.device_fingerprint() raised"
                         " — segment compiles in memory",
                         type(s).__name__, exc_info=True)
            return None
        if fp is None:
            return None
        parts.append(fp)
    mesh_part = None
    if mesh is not None:
        mesh_part = (tuple(sorted(mesh.shape.items())),
                     int(mesh.devices.size),
                     getattr(mesh.devices.flat[0], "platform", "?"))
    prec = None
    if precision is not None and getattr(precision, "active", False):
        prec = precision.cache_token
    v = runtime_versions()
    blob = repr((tuple(parts),
                 (tuple(entry_meta.shape), str(entry_meta.dtype),
                  bool(entry_meta.is_image)),
                 mesh_part, prec,
                 (v["jax"], v["jaxlib"], v["backend"])))
    return hashlib.sha256(blob.encode()).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CompileCache:
    """The on-disk store: atomic publish, digest-verified load,
    LRU byte budget. All methods are safe under concurrent processes —
    the only cross-process coordination is ``os.replace`` atomicity."""

    def __init__(self, root: str, max_bytes: int | None = None):
        self.root = os.path.abspath(root)
        if max_bytes is None:
            max_bytes = int(config.get("compile_cache_bytes",
                                       DEFAULT_MAX_BYTES))
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._seq = 0
        #: always-live counters (the obs registry mirrors them under
        #: ``plan.compile_cache.*`` when the pillar is enabled):
        #: ``compiles`` counts fresh XLA compiles through CachedJit —
        #: the warm-start gate asserts it stays 0 on a warm process
        self.stats = {"hits": 0, "misses": 0, "puts": 0, "bytes": 0,
                      "refused": 0, "put_races": 0, "evicted": 0,
                      "compiles": 0, "load_ms": 0.0}
        os.makedirs(self.root, exist_ok=True)

    # -- bookkeeping --

    def _bump(self, key: str, n: float = 1) -> None:
        with self._lock:
            self.stats[key] += n
        _obs_counter(key, n)

    def _entry_dir(self, fingerprint: str, shape_key: str) -> str:
        return os.path.join(self.root, fingerprint[:2], fingerprint,
                            shape_key)

    # -- load --

    def load(self, fingerprint: str, shape_key: str) -> Any | None:
        """Deserialize one cached executable. ``None`` on a plain miss;
        :class:`CompileCacheError` (after quarantining the entry) when
        the entry exists but must not be served."""
        d = self._entry_dir(fingerprint, shape_key)
        if not os.path.isdir(d):
            return None
        epath = os.path.join(d, ENTRY_FILE)
        try:
            entry = self._verify(d, epath)
            t0 = time.perf_counter()
            with open(os.path.join(d, PROGRAM_FILE), "rb") as f:
                payload = f.read()
            with open(os.path.join(d, TREES_FILE), "rb") as f:
                in_tree, out_tree = pickle.load(f)
            from jax.experimental import serialize_executable as se
            loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        except CompileCacheError:
            self._quarantine(d)
            raise
        except Exception as e:
            self._quarantine(d)
            raise CompileCacheError(
                f"compile cache entry {fingerprint[:12]}/{shape_key}: "
                f"deserialization failed ({type(e).__name__}: {e})"
            ) from e
        load_ms = (time.perf_counter() - t0) * 1e3
        self._bump("load_ms", load_ms)
        self._bump("bytes", len(payload))
        try:  # LRU touch — eviction orders by entry-dir mtime
            os.utime(d)
        except OSError:  # pragma: no cover - entry racing an eviction
            pass
        return loaded

    def _verify(self, d: str, epath: str) -> dict:
        """ENTRY.json sanity + toolchain match + per-file digests —
        all BEFORE any deserialization touches the payload."""
        if not os.path.exists(epath):
            raise CompileCacheError(
                f"{d}: torn entry ({ENTRY_FILE} missing)")
        try:
            with open(epath, encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, ValueError) as e:
            raise CompileCacheError(f"{epath}: unreadable ({e})") from e
        mine = runtime_versions()
        theirs = entry.get("versions", {})
        for k in ("jax", "jaxlib", "backend"):
            if theirs.get(k) != mine[k]:
                raise CompileCacheError(
                    f"{d}: compiled under {k}={theirs.get(k)!r}, "
                    f"running {k}={mine[k]!r}")
        for rel, want in (entry.get("files") or {}).items():
            path = os.path.join(d, rel)
            if not os.path.exists(path):
                raise CompileCacheError(f"{d}: torn entry ({rel} missing)")
            got = _sha256_file(path)
            if got != want:
                raise CompileCacheError(
                    f"{d}: digest mismatch on {rel} "
                    f"(manifest {want[:12]}…, file {got[:12]}…)")
        return entry

    def _quarantine(self, d: str) -> None:
        self._bump("refused")
        shutil.rmtree(d, ignore_errors=True)
        _log.warning("compile cache: quarantined bad entry %s", d)

    # -- put --

    def put(self, fingerprint: str, shape_key: str, payload: bytes,
            trees: tuple) -> bool:
        """Publish one serialized executable atomically. Returns False
        when the entry already exists or another process won the
        publish race (the loser adopts the winner's entry)."""
        d = self._entry_dir(fingerprint, shape_key)
        if os.path.exists(os.path.join(d, ENTRY_FILE)):
            return False
        parent = os.path.dirname(d)
        os.makedirs(parent, exist_ok=True)
        with self._lock:
            self._seq += 1
            seq = self._seq
        # pid + instance id + seq: unique across processes AND across
        # multiple in-process cache objects staging the same entry
        tmp = os.path.join(
            parent,
            f".staging-{shape_key}-{os.getpid()}-{id(self):x}-{seq}")
        os.makedirs(tmp)
        try:
            with open(os.path.join(tmp, PROGRAM_FILE), "wb") as f:
                f.write(payload)
            with open(os.path.join(tmp, TREES_FILE), "wb") as f:
                pickle.dump(trees, f)
            files = {rel: _sha256_file(os.path.join(tmp, rel))
                     for rel in (PROGRAM_FILE, TREES_FILE)}
            nbytes = sum(os.path.getsize(os.path.join(tmp, rel))
                         for rel in files)
            with open(os.path.join(tmp, ENTRY_FILE), "w",
                      encoding="utf-8") as f:
                json.dump({"fingerprint": fingerprint,
                           "shape_key": shape_key,
                           "versions": runtime_versions(),
                           "nbytes": nbytes,
                           "created": time.time(),
                           "files": files}, f, indent=1)
            # the torn-publish fault point: a crash here leaves the
            # staging dir (invisible to every load path) and no entry —
            # the next process simply compiles in memory
            _faults().hit("compile_cache_torn_put")
            try:
                os.replace(tmp, d)
            except OSError:
                # publish race lost: the winner's directory is already
                # there (non-empty → rename refuses). Adopt it.
                shutil.rmtree(tmp, ignore_errors=True)
                self._bump("put_races")
                return False
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._bump("puts")
        self._bump("bytes", nbytes)
        self._evict_over_budget()
        return True

    # -- LRU byte budget --

    def entries(self) -> list[tuple[float, int, str]]:
        """``[(mtime, nbytes, dir), ...]`` for every published entry."""
        out = []
        for shard in os.listdir(self.root) if os.path.isdir(self.root) \
                else []:
            sdir = os.path.join(self.root, shard)
            if not os.path.isdir(sdir):
                continue
            for fp in os.listdir(sdir):
                fdir = os.path.join(sdir, fp)
                if not os.path.isdir(fdir):
                    continue
                for shape in os.listdir(fdir):
                    d = os.path.join(fdir, shape)
                    if shape.startswith(".") or not os.path.isdir(d):
                        continue
                    try:
                        nbytes = sum(
                            os.path.getsize(os.path.join(d, f))
                            for f in os.listdir(d))
                        out.append((os.path.getmtime(d), nbytes, d))
                    except OSError:  # racing another process's evict
                        continue
        return out

    def size_bytes(self) -> int:
        return sum(n for _t, n, _d in self.entries())

    def _evict_over_budget(self) -> None:
        if self.max_bytes <= 0:
            return
        entries = sorted(self.entries())
        total = sum(n for _t, n, _d in entries)
        for mtime, nbytes, d in entries:
            if total <= self.max_bytes:
                break
            shutil.rmtree(d, ignore_errors=True)
            total -= nbytes
            self._bump("evicted")
            _log.info("compile cache: evicted %s (%d B) over %d B budget",
                      d, nbytes, self.max_bytes)


class CachedJit:
    """Drop-in wrapper over one jitted segment composite that resolves
    every concrete call shape against the disk cache before compiling.

    Mimics the jit at the seams the repo touches: ``__call__(params,
    x)`` dispatches the per-shape program; ``_cache_size()`` reports
    loaded+compiled program count (the ``obs.runtime.jit_cache_size``
    hook, so ``compiled_programs`` gates keep holding); ``lower`` is
    passed through (the obs device cost-capture seam). A cache refusal
    or serialization failure degrades to the wrapped jit's own
    ``lower().compile()`` — the cache can make loads fast, never wrong.
    """

    def __init__(self, jitted: Any, fingerprint: str,
                 cache: CompileCache):
        self._jit = jitted
        self.fingerprint = fingerprint
        self._cache = cache
        self._programs: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _cache_size(self) -> int:
        return len(self._programs)

    def lower(self, *args, **kw):
        return self._jit.lower(*args, **kw)

    @staticmethod
    def shape_key(args: tuple) -> str:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
        blob = repr(tuple(
            (tuple(getattr(a, "shape", ())),
             str(getattr(a, "dtype", type(a).__name__)))
            for a in leaves))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def __call__(self, *args):
        key = self.shape_key(args)
        prog = self._programs.get(key)
        if prog is None:
            with self._lock:
                prog = self._programs.get(key)
                if prog is None:
                    prog = self._resolve(key, args)
                    self._programs[key] = prog
        return prog(*args)

    def _resolve(self, key: str, args: tuple) -> Any:
        cache = self._cache
        try:
            prog = cache.load(self.fingerprint, key)
        except CompileCacheError as e:
            _log.warning("compile cache: %s — compiling in memory", e)
            prog = None
        if prog is not None:
            cache._bump("hits")
            return prog
        cache._bump("misses")
        compiled = self._jit.lower(*args).compile()
        cache._bump("compiles")
        # publishing is best-effort: a full disk / injected crash /
        # unserializable executable must never fail the dispatch that
        # just compiled a perfectly good program
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            cache.put(self.fingerprint, key, payload,
                      (in_tree, out_tree))
        except Exception as e:
            _log.warning("compile cache: publish of %s/%s failed (%s: "
                         "%s) — serving the in-memory program",
                         self.fingerprint[:12], key,
                         type(e).__name__, e)
        return compiled


# -- process-wide cache (ServeConfig.compile_cache / env) --

_active: CompileCache | None = None
_env_checked = False
_state_lock = threading.Lock()


def configure(path: str | None,
              max_bytes: int | None = None) -> CompileCache | None:
    """Install the process-wide cache rooted at ``path`` (``None``/""
    disables). An uncreatable or unwritable path degrades to a one-line
    warning and in-memory compiles — the fleet-dir tolerance rule: a
    bad cache dir must never fail a model load."""
    global _active, _env_checked
    with _state_lock:
        _env_checked = True
        if not path:
            _active = None
            return None
        try:
            cache = CompileCache(path, max_bytes=max_bytes)
            probe = os.path.join(cache.root,
                                 f".probe-{os.getpid()}-{id(cache)}")
            with open(probe, "w") as f:
                f.write("w")
            os.remove(probe)
        except OSError as e:
            _log.warning("compile cache disabled: %r not writable (%s)"
                         " — programs compile in memory", path, e)
            _active = None
            return None
        _active = cache
        _log.info("compile cache: %s (budget %d B)", cache.root,
                  cache.max_bytes)
        return cache


def active() -> CompileCache | None:
    """The installed cache, lazily honoring
    ``MMLSPARK_TPU_COMPILE_CACHE`` (the ``compile_cache`` config) on
    first consult."""
    global _env_checked
    if not _env_checked:
        _env_checked = True
        env = config.get("compile_cache", "")
        if env:
            configure(env)
    return _active


def reset() -> None:
    """Tests: drop the installed cache and re-arm the env check."""
    global _active, _env_checked
    with _state_lock:
        _active = None
        _env_checked = False

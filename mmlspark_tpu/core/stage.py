"""Pipeline-stage contracts: Transformer / Estimator plus column-role mixins.

Analog of SparkML's ``Transformer``/``Estimator`` as used throughout the
reference, with the reference's shared column-role mixins
``HasInputCol/HasOutputCol/HasLabelCol/...`` (reference:
core/contracts/src/main/scala/Params.scala:112-176). Stages are registered
on subclass creation, which powers the fuzz suite and doc generation the way
jar-reflection powers the reference's ``Fuzzing.scala`` and codegen
(reference: core/utils/src/main/scala/JarLoadingUtils.scala:17-80).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core import serialize as _ser
from mmlspark_tpu.data.table import DataTable


_UID_COUNTER = itertools.count()

# global registry: class path → class; drives fuzzing + docgen
STAGE_REGISTRY: dict[str, type] = {}


class PipelineStage(Params):
    """Base of every stage. Named, parameterized, persistable."""

    def __init__(self, **kwargs: Any):
        self._post_init()
        super().__init__(**kwargs)

    def _post_init(self) -> None:
        # split from __init__ so deserialization can bypass param validation
        if not hasattr(self, "_uid") or self._uid is None:
            self._uid = f"{type(self).__name__}_{next(_UID_COUNTER)}"

    def __init_subclass__(cls, **kwargs: Any):
        super().__init_subclass__(**kwargs)
        if not cls.__name__.startswith("_"):
            STAGE_REGISTRY[_ser.class_path(cls)] = cls

    @property
    def uid(self) -> str:
        return self._uid

    # -- persistence contract (every stage is writable/readable,
    #    analog of MLWritable via ComplexParamsWritable) --

    def save(self, path: str, overwrite: bool = True) -> None:
        _ser.save_stage(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        return _ser.load_stage(path)

    def _save_extra(self, directory: str) -> None:
        """Hook for state outside the param store (rare)."""

    def _load_extra(self, directory: str) -> None:
        pass

    def __repr__(self) -> str:
        sets = ", ".join(f"{k}={v!r}" for k, v in
                         self._simple_param_values().items())
        return f"{type(self).__name__}({sets})"


class Transformer(PipelineStage):
    """A stage mapping DataTable → DataTable."""

    def transform(self, table: DataTable) -> DataTable:
        raise NotImplementedError

    def __call__(self, table: DataTable) -> DataTable:
        return self.transform(table)


class Estimator(PipelineStage):
    """A stage that fits on a DataTable and yields a Transformer (model)."""

    def fit(self, table: DataTable) -> Transformer:
        raise NotImplementedError

    def fit_transform(self, table: DataTable) -> DataTable:
        return self.fit(table).transform(table)


# ---- column-role mixins (Params.scala:112-176 analog) ----

class HasInputCol:
    input_col = Param(default="input", doc="name of the input column",
                      type_=str)


class HasOutputCol:
    output_col = Param(default="output", doc="name of the output column",
                       type_=str)


class HasInputCols:
    input_cols = Param(default=None, doc="names of the input columns",
                       type_=(list, tuple))


class HasOutputCols:
    output_cols = Param(default=None, doc="names of the output columns",
                        type_=(list, tuple))


class HasLabelCol:
    label_col = Param(default="label", doc="name of the label column",
                      type_=str)


class HasFeaturesCol:
    features_col = Param(default="features", doc="name of the features column",
                         type_=str)


class UnaryTransformer(Transformer, HasInputCol, HasOutputCol):
    """A transformer producing one output column from one input column."""

    def _transform_column(self, values: Any, table: DataTable) -> Any:
        raise NotImplementedError

    def transform(self, table: DataTable) -> DataTable:
        out = self._transform_column(table[self.input_col], table)
        return table.with_column(self.output_col, out)


class LambdaTransformer(Transformer):
    """Wraps an arbitrary table→table function as a stage (UDFTransformer
    analog). The function is persisted by pickle."""

    fn = Param(default=None, doc="function DataTable -> DataTable",
               is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        return self.fn(table)

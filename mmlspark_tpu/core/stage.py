"""Pipeline-stage contracts: Transformer / Estimator plus column-role mixins.

Analog of SparkML's ``Transformer``/``Estimator`` as used throughout the
reference, with the reference's shared column-role mixins
``HasInputCol/HasOutputCol/HasLabelCol/...`` (reference:
core/contracts/src/main/scala/Params.scala:112-176). Stages are registered
on subclass creation, which powers the fuzz suite and doc generation the way
jar-reflection powers the reference's ``Fuzzing.scala`` and codegen
(reference: core/utils/src/main/scala/JarLoadingUtils.scala:17-80).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable

from mmlspark_tpu.core.params import Param, Params
from mmlspark_tpu.core import serialize as _ser
from mmlspark_tpu.data.table import DataTable


_UID_COUNTER = itertools.count()

# global registry: class path → class; drives fuzzing + docgen
STAGE_REGISTRY: dict[str, type] = {}


class PipelineStage(Params):
    """Base of every stage. Named, parameterized, persistable."""

    def __init__(self, **kwargs: Any):
        self._post_init()
        super().__init__(**kwargs)

    def _post_init(self) -> None:
        # split from __init__ so deserialization can bypass param validation
        if not hasattr(self, "_uid") or self._uid is None:
            self._uid = f"{type(self).__name__}_{next(_UID_COUNTER)}"

    def __init_subclass__(cls, **kwargs: Any):
        super().__init_subclass__(**kwargs)
        if not cls.__name__.startswith("_"):
            STAGE_REGISTRY[_ser.class_path(cls)] = cls

    @property
    def uid(self) -> str:
        return self._uid

    # -- persistence contract (every stage is writable/readable,
    #    analog of MLWritable via ComplexParamsWritable) --

    def save(self, path: str, overwrite: bool = True) -> None:
        _ser.save_stage(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        return _ser.load_stage(path)

    def _save_extra(self, directory: str) -> None:
        """Hook for state outside the param store (rare)."""

    def _load_extra(self, directory: str) -> None:
        pass

    # -- static schema inference (the transformSchema analog) --
    #
    # The pre-flight analyzer (mmlspark_tpu/analysis) walks a pipeline's
    # stages calling infer_schema with NO data and NO device execution.
    # A stage maps the incoming abstract TableSchema to the schema its
    # transform would produce, raising analysis.info.SchemaError on a
    # contract violation (missing column, wrong kind, size mismatch).
    # The default below is derived from the declared column-role params;
    # stages whose output layout is computable (image geometry, model
    # forwards via jax.eval_shape) override it.

    def _declared_input_columns(self) -> list[str]:
        """Column names this stage reads, per its column-role params."""
        declared = type(self).params()
        cols: list[str] = []
        if "input_col" in declared and self.get("input_col"):
            cols.append(self.get("input_col"))
        if "input_cols" in declared and self.get("input_cols"):
            cols.extend(self.get("input_cols"))
        if isinstance(self, Estimator) and "label_col" in declared \
                and self.get("label_col"):
            cols.append(self.get("label_col"))
        return cols

    def _declared_output_columns(self) -> list[str]:
        declared = type(self).params()
        cols: list[str] = []
        if "output_col" in declared and self.get("output_col"):
            cols.append(self.get("output_col"))
        if "output_cols" in declared and self.get("output_cols"):
            cols.extend(self.get("output_cols"))
        return cols

    def infer_schema(self, schema: Any) -> Any:
        """Map an abstract input schema to this stage's output schema.

        Default: require every declared input column to exist and add the
        declared output columns with unknown layout. Override to compute
        real output dtypes/shapes (and to enforce stronger contracts).
        """
        from mmlspark_tpu.analysis.info import ColumnInfo, SchemaError
        missing = [c for c in self._declared_input_columns()
                   if c not in schema]
        out = schema.copy()
        if missing:
            msg = (f"{type(self).__name__} reads missing column(s) "
                   f"{missing}; available: {list(schema)}")
            if schema.exact:
                raise SchemaError("missing-input-column", msg)
            out.warn("missing-input-column", msg + " (schema is inexact: "
                     "an opaque stage may have added them)", "info")
        for c in self._declared_output_columns():
            out.columns[c] = ColumnInfo.unknown()
        return out

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        """Predicted output row count for ``n`` input rows (None =
        unknown). Default: row-preserving; sampling/augmenting/dropping
        stages override."""
        return n

    def _infer_state(self, schema: Any, n: int | None
                     ) -> tuple[Any, int | None]:
        """One-pass (schema, rows) inference — the analyzer's entry point.
        Default composes the two public hooks (rows first: ``infer_rows``
        reads the PRE-stage schema); Pipeline/PipelineModel override to
        fold their stages once, so nested analysis work (UDF probes,
        eval_shape traces) runs a single time per walk."""
        rows = None if n is None else self.infer_rows(n, schema)
        return self.infer_schema(schema), rows

    def __repr__(self) -> str:
        sets = ", ".join(f"{k}={v!r}" for k, v in
                         self._simple_param_values().items())
        return f"{type(self).__name__}({sets})"


class Transformer(PipelineStage):
    """A stage mapping DataTable → DataTable."""

    def transform(self, table: DataTable) -> DataTable:
        raise NotImplementedError

    def __call__(self, table: DataTable) -> DataTable:
        return self.transform(table)


class Estimator(PipelineStage):
    """A stage that fits on a DataTable and yields a Transformer (model)."""

    def fit(self, table: DataTable) -> Transformer:
        raise NotImplementedError

    def fit_transform(self, table: DataTable) -> DataTable:
        return self.fit(table).transform(table)


# ---- column-role mixins (Params.scala:112-176 analog) ----

class HasInputCol:
    input_col = Param(default="input", doc="name of the input column",
                      type_=str)


class HasOutputCol:
    output_col = Param(default="output", doc="name of the output column",
                       type_=str)


class HasInputCols:
    input_cols = Param(default=None, doc="names of the input columns",
                       type_=(list, tuple))


class HasOutputCols:
    output_cols = Param(default=None, doc="names of the output columns",
                        type_=(list, tuple))


class HasLabelCol:
    label_col = Param(default="label", doc="name of the label column",
                      type_=str)


class HasFeaturesCol:
    features_col = Param(default="features", doc="name of the features column",
                         type_=str)


class UnaryTransformer(Transformer, HasInputCol, HasOutputCol):
    """A transformer producing one output column from one input column."""

    def _transform_column(self, values: Any, table: DataTable) -> Any:
        raise NotImplementedError

    def transform(self, table: DataTable) -> DataTable:
        out = self._transform_column(table[self.input_col], table)
        return table.with_column(self.output_col, out)


# ---- device-resident execution capability (the pipeline-fusion protocol) --

@dataclasses.dataclass(frozen=True)
class ArrayMeta:
    """Shape/dtype contract for one column batched as a device array.

    ``shape`` is the per-row shape (the batch axis is implicit), ``dtype``
    a numpy dtype string, and ``is_image`` marks stacked HWC image structs
    (whose host form is a column of image dicts). This is what a
    :class:`DeviceStage` sees when asked whether it can run on device.
    """

    shape: tuple
    dtype: str
    is_image: bool = False


@dataclasses.dataclass
class DeviceOp:
    """A stage's columnwise device computation.

    ``fn(params, x)`` must be a *pure* jax function mapping a
    ``[B, *in_meta.shape]`` array to ``[B, *out_meta.shape]`` — the planner
    composes adjacent ops into ONE jitted program, so fn must not perform
    host transfers, I/O, or Python-side mutation. ``params`` is a pytree of
    host arrays uploaded once per compiled segment and kept device-resident
    (the broadcast-once analog); stateless ops use the default ``()``.
    """

    fn: Callable
    out_meta: ArrayMeta
    params: Any = ()


class DeviceStage:
    """Capability mixin: a stage that can describe its computation as a pure
    columnwise array→array jax function, letting the pipeline planner
    (:mod:`mmlspark_tpu.core.plan`) keep data device-resident across stage
    boundaries instead of paying a host round-trip per stage.

    Opting in is best-effort: ``device_fn`` returning ``None`` (for an
    unsupported op list, dtype, or shape) falls back to the stage's host
    ``transform`` with identical semantics. Implementations must keep the
    device math equivalent to the host path — the parity suite
    (tests/test_plan.py) holds fused output to the documented tolerance.

    Two OPTIONAL hooks extend the protocol for sharded serving
    (docs/serving.md): ``device_fn_mesh(meta, mesh)`` — a mesh-aware
    variant the planner prefers at compile/verify time when the segment's
    concrete mesh is resolved (pipeline-parallel stages whose collectives
    bind mesh axes need it; shape inference still uses the plain
    ``device_fn``) — and ``device_param_rules(path, leaf)`` — per-leaf
    ``PartitionSpec`` placement consulted by
    :func:`mmlspark_tpu.parallel.mesh.param_shardings` when the segment
    compiles on a model-parallel mesh.
    """

    def device_input_col(self) -> str | None:
        """The single column the device computation consumes (None = this
        stage cannot run on device for the current configuration)."""
        return getattr(self, "input_col", None)

    def device_output_col(self) -> str | None:
        """The column the device computation produces."""
        return getattr(self, "output_col", None)

    def device_cache_token(self) -> Any:
        """A cheap fingerprint of the configuration the device computation
        depends on; a changed token invalidates the planner's compiled
        segment. The default covers stages fully described by their simple
        params; stages with complex params (models, fitted plans) must
        override to include their identity."""
        vals = self._simple_param_values() if hasattr(
            self, "_simple_param_values") else {}
        return tuple(sorted((k, repr(v)) for k, v in vals.items()))

    def device_fingerprint(self) -> Any:
        """A STABLE content identity for the persistent AOT compile
        cache (core/compile_cache.py), or ``None`` to opt the segment
        out of cross-process caching. Unlike ``device_cache_token`` —
        which may (and for model stages does) lean on ``id()`` because
        it only guards the in-process compiled-segment cache — a
        fingerprint must hash *content*: two processes loading the same
        artifact must produce the same fingerprint, and any change that
        could alter the compiled program must change it. The default
        covers stages fully described by their simple params; stages
        with complex params (models) must override with a weights
        digest or return ``None``."""
        if hasattr(self, "_complex_param_values") and \
                any(v is not None
                    for v in self._complex_param_values().values()):
            return None  # complex params: content unknown by default
        vals = self._simple_param_values() if hasattr(
            self, "_simple_param_values") else {}
        return (f"{type(self).__module__}.{type(self).__qualname__}",
                tuple(sorted((k, repr(v)) for k, v in vals.items())))

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        """Describe this stage's computation on a column of ``meta`` layout,
        or ``None`` to decline (host fallback)."""
        return None

    def device_emit(self, table: DataTable, values: Any,
                    meta: ArrayMeta, ctx: dict) -> DataTable:
        """Write the fused computation's host-fetched output (``values``,
        shaped ``[N, *meta.shape]``) into the table the way this stage's
        host ``transform`` would. ``ctx`` carries segment-entry context
        (e.g. image paths captured during coercion)."""
        out = values if values.ndim == 1 else list(values)
        return table.with_column(self.device_output_col(), out)


class LambdaTransformer(Transformer):
    """Wraps an arbitrary table→table function as a stage (UDFTransformer
    analog). The function is persisted by pickle."""

    fn = Param(default=None, doc="function DataTable -> DataTable",
               is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        return self.fn(table)

    def infer_schema(self, schema: Any) -> Any:
        """Probe the UDF on a 0-row table realizing the schema: the column
        *set* it produces is observed concretely, while cell layouts of
        columns it touches become unknown (nothing provable about a UDF's
        values from zero rows). If the UDF cannot run on an empty table the
        schema degrades to inexact instead of failing the analysis."""
        from mmlspark_tpu.analysis.info import ColumnInfo, TableSchema
        try:
            empty = schema.empty_table()
            probed = self.fn(empty)
        except Exception as e:
            out = schema.as_inexact()
            out.warn(
                "opaque-stage",
                f"LambdaTransformer fn could not be probed on an empty "
                f"table ({type(e).__name__}: {e}); downstream column "
                "checks are best-effort", "info")
            return out
        cols = {}
        for name in probed.columns:
            if name in empty and name in schema.columns \
                    and probed[name] is empty[name]:
                cols[name] = schema.columns[name].copy()  # untouched
            else:
                cols[name] = ColumnInfo.unknown(
                    meta=dict(probed.column_meta(name)))
        out = TableSchema(cols, exact=schema.exact)
        out.pending = list(schema.pending)  # findings ride along the fold
        return out

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        # a UDF may filter or expand rows; assume row-preserving (the
        # common case) — the plan audit's crossing prediction documents
        # this as an approximation for row-changing UDFs
        return n

"""Plan-level precision/quantization pass for served programs.

The serve path computed in f32 end to end (ROADMAP item 5) while the
hardware's fast path is bf16 MXU passes and int8 weight traffic. This
module makes precision a first-class property of a device-plan segment
(:mod:`mmlspark_tpu.core.plan`): a :class:`PrecisionPolicy` resolved per
serve segment selects

* ``"f32"``  — the historical behavior, byte-identical programs;
* ``"bf16"`` — bf16 activations throughout the served program: float
  entry batches and every inter-stage value cast to bfloat16, ≥2-D float
  param leaves stored and shipped as bf16 (half the param HBM + H2D
  bytes); 1-D leaves (biases, norm scales/offsets) STAY f32 so
  normalization and bias adds keep full-precision accumulation — the
  numerics contract ``ops/group_norm.py`` documents;
* ``"int8w"`` — weight-only int8 on top of the bf16 activation policy
  (à la LLM.int8()/AWQ's weight-only serving mode): every eligible ≥2-D
  float param leaf is quantized per OUTPUT channel to int8 with an f32
  scale vector (4× less weight HBM/wire than f32), and the dequantize
  (``q.astype(f32) * scale → bf16``) happens INSIDE the jitted segment,
  fused by XLA into the consuming matmul — still exactly one program
  per (model, bucket).

The pass is applied by ``core/plan.segment_composite`` — the ONE builder
both the executor jit and the SPMD audit trace — so the verified program
can never drift from the dispatched one, and the policy's
:attr:`~PrecisionPolicy.cache_token` is part of the compiled-segment
cache key, so an f32 and an int8w serving of the same model never share
a program or a device param tree.

Weight scales are calibrated from the weights themselves (symmetric
max-abs per output channel — weight-only quantization needs no
activation statistics); the *parity* of the quantized program against
the f32 offline transform is calibrated at ``ModelServer.add_model``
from the analyzer-derived schema plus a sample batch, and pinned
per model by :meth:`PrecisionPolicy.resolve_tolerance` (docs/quantization.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

MODES = ("f32", "bf16", "int8w")

# default max-abs parity tolerances vs the f32 offline transform, per
# mode, for models that don't pin their own (docs/quantization.md has
# the measured per-model table; the serve gate pins the canonical MLP).
# bf16 matmuls carry ~2^-8 relative error per accumulation chain; int8
# per-channel weights add ~2^-7 relative weight error on top
DEFAULT_TOLERANCES = {"f32": 0.0, "bf16": 5e-2, "int8w": 2e-1}

# int8 symmetric range: scales map the per-channel max-abs onto ±127
_QMAX = 127.0

# leaves smaller than this (per-row fan-in × fan-out) are not worth
# shipping as int8 — the scale vector and dequant outweigh the win
MIN_QUANT_SIZE = 256


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Resolved precision of one served model's device segments.

    ``tolerance`` is the model's pinned max-abs parity bound against the
    f32 offline transform (None = the mode default); ``min_quant_size``
    gates which param leaves int8-quantize (smaller leaves cast to bf16
    instead). The policy is hashable and its :attr:`cache_token` folds
    into the compiled-segment cache key.
    """

    mode: str = "f32"
    tolerance: float | None = None
    min_quant_size: int = MIN_QUANT_SIZE

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"unknown precision mode {self.mode!r}; one of {MODES}")
        if self.tolerance is not None and self.tolerance < 0:
            raise ValueError(
                f"precision tolerance must be >= 0: {self.tolerance}")
        if self.min_quant_size < 1:
            raise ValueError(
                f"min_quant_size must be >= 1: {self.min_quant_size}")

    @staticmethod
    def parse(obj: Any) -> "PrecisionPolicy | None":
        """``None`` | mode string | dict of fields | policy → policy.

        ``None`` stays None (the f32 fast path: the plan applies no pass
        and the cache key component is None, so existing callers compile
        byte-identical programs)."""
        if obj is None:
            return None
        if isinstance(obj, PrecisionPolicy):
            return obj
        if isinstance(obj, str):
            return PrecisionPolicy(mode=obj)
        if isinstance(obj, dict):
            return PrecisionPolicy(**obj)
        raise TypeError(
            f"cannot parse a PrecisionPolicy from {type(obj).__name__}: "
            f"{obj!r}")

    @property
    def active(self) -> bool:
        """False for f32 — the plan treats an f32 policy exactly like no
        policy (same cache entries, no wrapping)."""
        return self.mode != "f32"

    @property
    def cache_token(self) -> tuple:
        return (self.mode, self.min_quant_size)

    def resolve_tolerance(self) -> float:
        """The pinned parity bound, defaulted per mode."""
        if self.tolerance is not None:
            return float(self.tolerance)
        return DEFAULT_TOLERANCES[self.mode]

    def describe(self) -> str:
        return f"{self.mode}(tol={self.resolve_tolerance():g})"


class QuantizedLeaf:
    """One int8-quantized param leaf: ``q`` int8 ``[..., C]`` plus the
    per-output-channel f32 ``scale`` ``[C]``. Registered as a pytree
    node, so device placement, sharding rules, and jit tracing all see
    the two component arrays as ordinary leaves — the int8 tensor ships
    thin over H2D and lives thin in HBM; :func:`materialize` dequantizes
    inside the jitted program."""

    __slots__ = ("q", "scale")

    def __init__(self, q: Any, scale: Any):
        self.q = q
        self.scale = scale

    def __repr__(self) -> str:
        shape = getattr(self.q, "shape", None)
        return f"QuantizedLeaf(int8{list(shape or ())})"


def _quant_flatten(leaf: QuantizedLeaf):
    return (leaf.q, leaf.scale), None


def _quant_flatten_with_keys(leaf: QuantizedLeaf):
    from jax.tree_util import GetAttrKey
    return ((GetAttrKey("q"), leaf.q),
            (GetAttrKey("scale"), leaf.scale)), None


def _quant_unflatten(_aux, children) -> QuantizedLeaf:
    return QuantizedLeaf(*children)


def _register() -> None:
    import jax
    try:
        jax.tree_util.register_pytree_with_keys(
            QuantizedLeaf, _quant_flatten_with_keys, _quant_unflatten)
    except ValueError:  # pragma: no cover - double import guard
        pass


_register()


def _is_quant(x: Any) -> bool:
    return isinstance(x, QuantizedLeaf)


def quantize_channelwise(w: np.ndarray) -> QuantizedLeaf:
    """Symmetric per-output-channel int8 quantization of one ≥2-D float
    weight (host-side numpy — the quantized tree is what uploads, so the
    H2D wire ships int8). Channels = the LAST axis (flax kernel layout:
    ``(..., in, out)`` / HWIO)."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=tuple(range(wf.ndim - 1)))
    scale = np.where(amax > 0, amax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(wf / scale), -_QMAX, _QMAX).astype(np.int8)
    return QuantizedLeaf(q, scale)


def _eligible_int8(leaf: Any, policy: PrecisionPolicy) -> bool:
    arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
    return (np.issubdtype(np.dtype(arr.dtype), np.floating)
            and getattr(arr, "ndim", 0) >= 2
            and int(np.prod(arr.shape)) >= policy.min_quant_size)


def quantize_params(params: Any, policy: PrecisionPolicy) -> Any:
    """The host-side half of the pass: map a segment's param pytree to
    its low-precision storage form.

    * int8w: eligible ≥2-D float leaves → :class:`QuantizedLeaf`;
    * bf16 (and int8w's non-quantized ≥2-D floats): cast to bfloat16;
    * 1-D float leaves (biases, norm scales) and non-floats: unchanged
      (f32 accumulation for the cheap adds; int/bool leaves are layout).
    """
    if not policy.active:
        return params
    import jax
    import jax.numpy as jnp

    def one(leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            return leaf
        if arr.ndim < 2:
            # keep the f32 constants f32: normalization scale/bias and
            # conv/dense biases accumulate at full precision
            return np.asarray(arr, np.float32)
        if policy.mode == "int8w" and _eligible_int8(arr, policy):
            return quantize_channelwise(arr)
        return np.asarray(arr, jnp.bfloat16)

    return jax.tree_util.tree_map(one, params)


def materialize(params: Any, policy: PrecisionPolicy) -> Any:
    """The in-program half: rebuild the compute-form param tree INSIDE
    the jitted segment. Dequantization (int8 → f32 scale multiply →
    bf16) traces here, so XLA fuses it into the consuming matmul and
    the weight's HBM-resident form stays int8."""
    if not policy.active:
        return params
    import jax
    import jax.numpy as jnp

    def one(leaf):
        if _is_quant(leaf):
            return (leaf.q.astype(jnp.float32)
                    * leaf.scale).astype(jnp.bfloat16)
        return leaf

    return jax.tree_util.tree_map(one, params, is_leaf=_is_quant)


def cast_activation(x: Any, policy: PrecisionPolicy) -> Any:
    """bf16 activation cast at a stage boundary: float values narrow to
    bfloat16, everything else (uint8 image batches, int ids, bools)
    passes through — integer entries already ship thin and the stage's
    own upcast convention handles them."""
    if not policy.active:
        return x
    import jax.numpy as jnp
    if hasattr(x, "dtype") and np.issubdtype(np.dtype(x.dtype),
                                             np.floating) \
            and x.dtype != jnp.bfloat16:
        return x.astype(jnp.bfloat16)
    return x


def cast_output(x: Any, dtype: str) -> Any:
    """Restore a segment output to its declared column dtype, so
    ``device_emit`` and the serve wire see exactly the layout the f32
    plan declared (``ArrayMeta.dtype``) whatever the internal policy."""
    import jax.numpy as jnp
    want = np.dtype(dtype)
    if getattr(x, "dtype", None) == want:
        return x
    return jnp.asarray(x, want)


def quantized_bytes(params: Any) -> tuple[int, int]:
    """(storage bytes, f32-equivalent bytes) of a (possibly quantized)
    param tree — the honest accounting behind the bench's weight-HBM
    claim. A :class:`QuantizedLeaf`'s scale vector counts toward
    STORAGE only (it is quantization overhead; the f32 model has no
    such leaf, so it must not inflate the denominator)."""
    import jax

    def size_of(leaf) -> int:
        return int(np.prod(getattr(leaf, "shape", ()) or (1,)))

    stored = 0
    f32_equiv = 0
    for leaf in jax.tree_util.tree_leaves(params, is_leaf=_is_quant):
        if _is_quant(leaf):
            stored += size_of(leaf.q) + size_of(leaf.scale) * 4
            f32_equiv += size_of(leaf.q) * 4
            continue
        stored += size_of(leaf) * np.dtype(leaf.dtype).itemsize
        f32_equiv += size_of(leaf) * 4
    return stored, f32_equiv

"""Framework configuration — env-var-overridable namespaced settings.

Analog of the reference's ``MMLConfig`` typesafe-config wrapper
(reference: core/env/src/main/scala/Configuration.scala:18-51). Settings
resolve in order: explicit ``set()`` > environment variable
``MMLSPARK_TPU_<NAME>`` > default.
"""

from __future__ import annotations

import os
from typing import Any, Callable


_DEFAULTS: dict[str, Any] = {
    "cache_dir": os.path.join(
        os.path.expanduser("~"), ".cache", "mmlspark_tpu"),
    "datasets_dir": os.path.join(
        os.path.expanduser("~"), ".cache", "mmlspark_tpu", "datasets"),
    "model_repo_url": "",          # remote zoo endpoint ("" = local only)
    "default_minibatch_size": 64,
    "image_threads": 8,            # host-side image-op parallelism
    "log_level": "INFO",
    "timings": True,               # per-stage timing logs (Timer analog)
    "compile_cache": "",           # AOT compile-cache dir ("" = off)
    "compile_cache_bytes": 1 << 30,  # compile-cache LRU byte budget
}

_overrides: dict[str, Any] = {}

# change listeners: fn(name) called after set()/reset() commits (name is
# "*" for a full reset). How already-created consumers (loggers caching
# their level, the obs runtime) honor later config changes without
# polling — keep callbacks idempotent and exception-free
_listeners: list[Callable[[str], None]] = []


def subscribe(fn: Callable[[str], None]) -> None:
    """Register a change listener (process lifetime; no unsubscribe)."""
    _listeners.append(fn)


def _notify(name: str) -> None:
    for fn in list(_listeners):
        fn(name)


def get(name: str, default: Any = None) -> Any:
    if name in _overrides:
        return _overrides[name]
    env = os.environ.get(f"MMLSPARK_TPU_{name.upper()}")
    if env is not None:
        base = _DEFAULTS.get(name, default)
        if isinstance(base, bool):
            return env.lower() in ("1", "true", "yes")
        if isinstance(base, int):
            return int(env)
        return env
    return _DEFAULTS.get(name, default)


def set(name: str, value: Any) -> None:  # noqa: A001 - config namespace
    _overrides[name] = value
    _notify(name)


def reset(name: str | None = None) -> None:
    if name is None:
        _overrides.clear()
        _notify("*")
    else:
        _overrides.pop(name, None)
        _notify(name)

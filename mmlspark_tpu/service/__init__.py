"""Shared process-supervision machinery.

The supervision core that ``train/service.py`` (PR 11) and the serve
fleet tier (``serve/fleet/supervisor.py``) both run on: atomic beacon
I/O, the decisions journal (disk always, obs mirror when the tracer is
on), the supervised-child wrapper with its output pump, and the
SIGTERM-grace-kill teardown helpers. Policy types stay with their
domains — ``RecoveryPolicy`` lives in ``train/service.py`` (the fleet
supervisor imports it), ``ScalePolicy`` in ``serve/fleet/scale.py`` —
this package is only the actuator plumbing they share.
"""

from mmlspark_tpu.service.core import (
    SupervisedProcess, SupervisorJournal, atomic_write_json, join_pumps,
    read_beacon, terminate_processes,
)

__all__ = [
    "SupervisedProcess",
    "SupervisorJournal",
    "atomic_write_json",
    "join_pumps",
    "read_beacon",
    "terminate_processes",
]

"""Supervisor core: journal, beacon I/O, child wrapper, teardown.

Factored out of ``train/service.py`` so the serve fleet supervisor
(``serve/fleet/supervisor.py``) shares ONE implementation of the
mechanics every out-of-process supervisor needs:

* :func:`atomic_write_json` / :func:`read_beacon` — the beacon
  transport. Workers publish liveness as one JSON file per rank,
  written atomically (tmp + ``os.replace``); the supervisor reads it
  back generation-checked, so a stale file from a previous generation
  never masquerades as the current worker.
* :class:`SupervisorJournal` — every supervisor decision is an event:
  appended to an on-disk ``decisions.jsonl`` ALWAYS (supervision
  forensics must not depend on telemetry being on), mirrored as an obs
  ``<prefix>/<kind>`` event plus ``<counter_prefix><kind>s`` counters
  when the tracer is enabled.
* :class:`SupervisedProcess` — one child process plus its stdout pump
  thread (tail-bounded, prefixed relay to the supervisor's stdout) and
  the progress/exit bookkeeping the watch loops condition on.
* :func:`terminate_processes` / :func:`join_pumps` — SIGTERM, a shared
  grace deadline, then kill; and the pump joins that keep teardown
  thread-clean (CC104).
"""

from __future__ import annotations

import json
import os
import signal as _signal
import subprocess
import sys
import threading
import time

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import event as _obs_event

_log = get_logger(__name__)


def atomic_write_json(path: str, payload: dict) -> None:
    """Write ``payload`` as JSON with no torn-read window: stage to a
    pid-suffixed temp file, then ``os.replace`` (atomic on POSIX)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def read_beacon(service_dir: str, rank: int,
                generation: int) -> dict | None:
    """``beacon_<rank>.json`` if readable AND stamped with this
    generation — a stale file from the previous generation is not this
    worker. Torn/absent reads are None, never an exception (the watch
    loop polls this on every tick)."""
    path = os.path.join(service_dir, f"beacon_{rank}.json")
    try:
        with open(path, encoding="utf-8") as f:
            b = json.load(f)
    except (OSError, ValueError):
        return None
    return b if b.get("generation") == generation else None


class SupervisorJournal:
    """The decision journal: disk always, obs mirror when enabled.

    ``record(kind, payload)`` appends ``{"ts", "kind", **payload}`` to
    ``path`` (jsonl), logs it, and — tracer on — emits an obs event
    ``<event_prefix>/<kind>`` (category ``cat``) plus bumps the counter
    ``<counter_prefix><kind>s`` when ``kind`` is in ``counter_kinds``.
    """

    def __init__(self, path: str, *, event_prefix: str, cat: str,
                 counter_prefix: str,
                 counter_kinds: tuple[str, ...] = (),
                 log_label: str | None = None):
        self.path = path
        self.event_prefix = event_prefix
        self.cat = cat
        self.counter_prefix = counter_prefix
        self.counter_kinds = tuple(counter_kinds)
        self.log_label = log_label or event_prefix

    def record(self, kind: str, payload: dict) -> None:
        entry = {"ts": time.time(), "kind": kind, **payload}
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry) + "\n")
        _log.info("%s: %s %s", self.log_label, kind, payload)
        if _obs_rt._enabled:
            _obs_event(f"{self.event_prefix}/{kind}", self.cat,
                       {k: str(v) for k, v in payload.items()})
            if kind in self.counter_kinds:
                _obs_registry().counter(
                    f"{self.counter_prefix}{kind}s").add()


class SupervisedProcess:
    """One supervised child process + its output pump and progress
    tracking.

    The pump thread relays the child's combined stdout/stderr to the
    supervisor's stdout line-prefixed (``[<log_prefix> <rank>] ...``)
    and keeps a bounded tail for post-mortems. Progress bookkeeping
    (``last_progress``/``progress_ts``) is what hang deadlines measure
    against; ``counter_last`` is the per-(name, labels) delta baseline
    for beacon counter re-aggregation (a value that went BACKWARD means
    the worker restarted and its registry reset).
    """

    TAIL_LINES = 40

    def __init__(self, rank: int, proc: subprocess.Popen, *,
                 log_prefix: str = "worker",
                 thread_name: str | None = None):
        self.rank = rank
        self.proc = proc
        self.tail: list[str] = []
        self._log_prefix = log_prefix
        self.thread = threading.Thread(
            target=self._pump,
            name=thread_name or f"SupervisedPump[{rank}]", daemon=True)
        self.thread.start()
        self.last_progress = -1
        self.progress_ts = time.monotonic()  # doubles as the no-beacon
        #                                      deadline baseline
        self.straggler_hits = 0
        self.exit_recorded = False
        self.counter_last: dict[tuple, float] = {}

    def _pump(self) -> None:
        for line in self.proc.stdout:
            self.tail.append(line)
            if len(self.tail) > self.TAIL_LINES:
                del self.tail[0]
            sys.stdout.write(f"[{self._log_prefix} {self.rank}] {line}")
            sys.stdout.flush()


def terminate_processes(workers: list, grace_s: float,
                        poll_s: float = 0.05) -> None:
    """SIGTERM every live child, give them ONE shared grace deadline to
    drain, then kill stragglers. ``workers`` are
    :class:`SupervisedProcess`; every child is reaped (``wait``) before
    return."""
    deadline = time.monotonic() + grace_s
    for w in workers:
        if w.proc.poll() is None:
            try:
                w.proc.send_signal(_signal.SIGTERM)
            except OSError:  # pragma: no cover - already gone
                pass
    for w in workers:
        while w.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(poll_s)
        if w.proc.poll() is None:
            w.proc.kill()
        w.proc.wait()


def join_pumps(workers: list, timeout_s: float = 2.0) -> None:
    """Join the output pump threads (no stray threads after teardown —
    the pump ends when the child's stdout hits EOF)."""
    for w in workers:
        if w.thread.is_alive():
            w.thread.join(timeout=timeout_s)

"""ModelDownloader — manifest-driven model zoo with sha256-verified cache.

Analog of the reference's ``src/downloader/`` (reference:
ModelDownloader.scala:23-252, Schema.scala:54-74): a remote/local
repository of pretrained models described by a manifest, transferred into a
local cache keyed by content hash, with integrity verification. Differences:
models are ModelBundle checkpoint directories (msgpack pytrees) instead of
CNTK graph files, and local/file repositories are first-class (the build
environment has no egress; HTTP stays supported for real deployments).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import shutil
import threading
from typing import Any, Iterable

from mmlspark_tpu.core import config
from mmlspark_tpu.core import fs as _fs
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.retry import RetryPolicy, call_with_retry
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry

_log = get_logger(__name__)

# transient-fault tolerance for model pulls: a dropped connection or a
# flaky shared filesystem during a supervised run's model-zoo fetch
# retries with jittered exponential backoff instead of aborting the run.
# urllib's URLError/HTTPError are OSError subclasses, so one tuple covers
# both the HTTP and filesystem repository paths — but a 4xx HTTP status
# is a PERMANENT answer (missing model, bad auth), not a transient
# fault: retrying it only delays the real error


def _transient_fetch_fault(exc: BaseException) -> bool:
    import urllib.error
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code >= 500  # 5xx/served errors may recover; 4xx won't
    return True


DEFAULT_FETCH_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                  max_delay_s=5.0, retry_on=(OSError,),
                                  retry_if=_transient_fetch_fault)

MANIFEST_NAME = "MANIFEST.json"


@dataclasses.dataclass
class ModelSchema:
    """Manifest entry (reference: downloader/Schema.scala:54-74)."""

    name: str
    dataset: str = ""
    model_type: str = ""
    uri: str = ""                 # location relative to the repo root
    hash: str = ""                # sha256 of the archived model dir
    size: int = 0
    input_node: str = "input"
    num_layers: int = 0
    layer_names: tuple = ()
    # measured held-out performance recorded at publish time (the honesty
    # contract: a zoo entry states what its weights are actually worth on
    # the dataset it names; "" = not evaluated, e.g. size stand-ins)
    eval_metric: str = ""
    eval_value: float = 0.0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["layer_names"] = list(self.layer_names)
        return d

    @staticmethod
    def from_json(d: dict) -> "ModelSchema":
        d = dict(d)
        d["layer_names"] = tuple(d.get("layer_names", ()))
        return ModelSchema(**d)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    for chunk in _fs.iter_chunks(path):
        h.update(chunk)
    return h.hexdigest()


@contextlib.contextmanager
def cache_entry_lock(path: str):
    """Exclusive lock on one cache entry, across threads AND processes.

    Two server workers loading the same model used to race
    ``ModelDownloader.download``: both fetched into the same ``dest`` and
    a reader could observe (and hash-record) a half-written file. The lock
    file is ``<dest>.lock``; each acquisition opens its own descriptor, so
    ``fcntl.flock`` excludes sibling threads as well as other processes.
    Where fcntl is unavailable (non-POSIX), degrades to a process-local
    mutex — atomic-rename publication below still keeps partially written
    files invisible cross-process.
    """
    lock_path = path + ".lock"
    local = _LOCAL_LOCKS.setdefault(lock_path, threading.Lock())
    with local:
        try:
            import fcntl
        except ImportError:  # pragma: no cover - non-POSIX fallback
            yield
            return
        os.makedirs(os.path.dirname(lock_path) or ".", exist_ok=True)
        with open(lock_path, "w") as fh:
            fcntl.flock(fh, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh, fcntl.LOCK_UN)


_LOCAL_LOCKS: dict[str, threading.Lock] = {}


class Repository:
    """A model repository rooted at a local dir, object-store URI, or URL.

    ``memory://`` / ``gs://`` / ``hdfs://`` roots route through the
    filesystem abstraction — the HDFSRepo analog (reference:
    downloader/src/main/scala/ModelDownloader.scala:39-104); HTTP(S) stays
    a plain manifest-over-CDN endpoint (DefaultModelRepo, :109-155).
    """

    def __init__(self, root: str):
        self.root = root

    def _is_http(self) -> bool:
        return self.root.startswith(("http://", "https://"))

    def read_manifest(self) -> list[ModelSchema]:
        if self._is_http():
            import urllib.request
            with urllib.request.urlopen(
                    f"{self.root}/{MANIFEST_NAME}") as r:
                entries = json.load(r)
        else:
            with _fs.open_file(_fs.join(self.root, MANIFEST_NAME), "r") as f:
                entries = json.load(f)
        return [ModelSchema.from_json(e) for e in entries]

    def fetch(self, schema: ModelSchema, dest: str) -> str:
        """Copy/download the model artifact to ``dest``; returns the path."""
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if self._is_http():
            import urllib.request
            with urllib.request.urlopen(f"{self.root}/{schema.uri}") as r, \
                    open(dest, "wb") as f:
                shutil.copyfileobj(r, f)
        else:
            with _fs.open_file(_fs.join(self.root, schema.uri)) as src, \
                    open(dest, "wb") as f:
                shutil.copyfileobj(src, f)
        return dest


class ModelDownloader:
    """Transfers models from a repository into a hash-verified local cache.

    Reference: ModelDownloader.scala:164-251 (``repoTransfer`` dedup by
    hash, ``downloadByName``/``downloadModels``).
    """

    def __init__(self, repo: str | Repository | None = None,
                 cache_dir: str | None = None,
                 retry: RetryPolicy | None = DEFAULT_FETCH_RETRY):
        if repo is None:
            repo = config.get("model_repo_url") or ""
        self.repo = repo if isinstance(repo, Repository) else Repository(repo)
        self.cache_dir = cache_dir or os.path.join(
            config.get("cache_dir"), "models")
        self.retry = retry

    def list_models(self) -> list[ModelSchema]:
        return self.repo.read_manifest()

    def _cache_path(self, schema: ModelSchema) -> str:
        tag = schema.hash[:16] if schema.hash else "nohash"
        return os.path.join(self.cache_dir, f"{schema.name}-{tag}.model")

    def download_by_name(self, name: str) -> str:
        for schema in self.list_models():
            if schema.name == name:
                return self.download(schema)
        raise KeyError(f"model {name!r} not in repository manifest "
                       f"({self.repo.root})")

    def download(self, schema: ModelSchema) -> str:
        """Fetch (or reuse) one model, concurrency-safe.

        The whole check-fetch-verify-publish sequence holds the cache
        entry's file lock, so two workers loading the same model serialize
        (the second observes the first's verified file and returns
        immediately); the fetch lands in a private temp file and is
        published with ``os.replace``, so no reader — locked or not — can
        ever observe a partially written cache entry.
        """
        dest = self._cache_path(schema)
        with cache_entry_lock(dest):
            return self._download_locked(schema, dest)

    def _download_locked(self, schema: ModelSchema, dest: str) -> str:
        sidecar = dest + ".sha256"
        if os.path.exists(dest):
            if schema.hash:
                if _sha256_file(dest) == schema.hash:
                    return dest  # hash-dedup hit (repoTransfer analog)
            elif os.path.exists(sidecar):
                # manifest carries no hash: verify against the sha256 we
                # recorded when the fetch completed, so a truncated or
                # corrupted cache entry is never served (the reference
                # always records a hash — Schema.scala:34-39; the sidecar
                # restores that guarantee for hashless manifests)
                with open(sidecar) as f:
                    recorded = f.read().strip()
                if recorded and _sha256_file(dest) == recorded:
                    return dest
            _log.warning("cached model %s failed hash check; refetching",
                         schema.name)
            os.remove(dest)
        tmp = f"{dest}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            actual = self._fetch_with_retry(schema, tmp)
            os.replace(tmp, dest)  # atomic publication of the verified file
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        with open(sidecar, "w") as f:
            f.write(actual)
        return dest

    def _fetch_with_retry(self, schema: ModelSchema, tmp: str) -> str:
        """One fetch-and-verify under the retry policy; returns the
        verified sha256. Transient faults (OSError family — dropped
        connections, flaky mounts) back off with jitter and refetch into
        the same private temp file (opened ``"wb"``, so a partial
        previous attempt is truncated, never appended to). The hash
        check is INSIDE the retried callable: a fault that corrupts
        bytes without raising (a short/garbled read that still
        completes) surfaces as the mismatch ``IOError`` and spends the
        same retry budget as a dropped connection. Each retry logs and
        bumps ``data.fetch_retries`` so a lossy link is visible in the
        registry, not just slower."""

        def fetch_and_verify() -> str:
            self.repo.fetch(schema, tmp)
            actual = _sha256_file(tmp)
            if schema.hash and actual != schema.hash:
                raise IOError(
                    f"model {schema.name!r}: sha256 mismatch "
                    f"(manifest {schema.hash[:12]}…, got {actual[:12]}…)")
            return actual

        if self.retry is None:
            return fetch_and_verify()

        def on_retry(attempt: int, exc: BaseException, delay: float) -> None:
            _log.warning(
                "fetch of model %s failed (attempt %d/%d: %s); retrying "
                "in %.2fs", schema.name, attempt, self.retry.max_attempts,
                exc, delay)
            if _obs_rt._enabled:
                _obs_registry().counter("data.fetch_retries",
                                        model=schema.name).add()

        return call_with_retry(fetch_and_verify, self.retry,
                               on_retry=on_retry)

    def download_models(self, names: Iterable[str] | None = None) -> list[str]:
        schemas = self.list_models()
        if names is not None:
            wanted = set(names)
            schemas = [s for s in schemas if s.name in wanted]
        return [self.download(s) for s in schemas]


# ---- publishing helpers (build a local repo; used by tests & tools) ----

def save_bundle_file(bundle: Any, path: str) -> None:
    """Serialize a ModelBundle to one file (pickle of module + msgpack'd
    params)."""
    import pickle

    import jax
    import numpy as np
    from flax import serialization

    host_params = jax.tree_util.tree_map(np.asarray, bundle.params)
    payload = {
        "module": bundle.module,
        "params_bytes": serialization.to_bytes(host_params),
        "params_skeleton": jax.tree_util.tree_map(
            lambda a: 0, host_params),
        "input_spec": bundle.input_spec,
        "output_names": bundle.output_names,
        "preprocess": bundle.preprocess,
        "name": bundle.name,
    }
    with _fs.open_file(path, "wb") as f:
        pickle.dump(payload, f)


def load_bundle_file(path: str) -> Any:
    import pickle

    from flax import serialization

    from mmlspark_tpu.models.bundle import ModelBundle

    with _fs.open_file(path, "rb") as f:
        payload = pickle.load(f)
    params = serialization.from_bytes(
        payload["params_skeleton"], payload["params_bytes"])
    return ModelBundle(
        module=payload["module"],
        params=params,
        input_spec=tuple(payload["input_spec"]),
        output_names=tuple(payload["output_names"]),
        preprocess=payload["preprocess"],
        name=payload["name"],
    )


def publish_model(bundle: Any, repo_root: str,
                  schema: ModelSchema | None = None) -> ModelSchema:
    """Write a bundle + manifest entry into a repository (local dir,
    ``memory://``, or any registered object-store scheme)."""
    _fs.makedirs(repo_root)
    uri = f"{bundle.name}.model"
    path = _fs.join(repo_root, uri)
    save_bundle_file(bundle, path)
    entry = schema or ModelSchema(name=bundle.name)
    entry.uri = uri
    entry.hash = _sha256_file(path)
    entry.size = _fs.size(path)
    entry.layer_names = tuple(bundle.output_names)
    manifest_path = _fs.join(repo_root, MANIFEST_NAME)
    entries = []
    if _fs.exists(manifest_path):
        with _fs.open_file(manifest_path, "r") as f:
            entries = [e for e in json.load(f) if e["name"] != entry.name]
    entries.append(entry.to_json())
    with _fs.open_file(manifest_path, "w") as f:
        json.dump(entries, f, indent=1)
    return entry

"""Columnar data layer: DataTable, readers, and the model downloader.

Analog of the reference's Spark DataFrame usage plus ``src/readers/`` and
``src/downloader/``.
"""

from mmlspark_tpu.data.table import DataTable

__all__ = ["DataTable"]

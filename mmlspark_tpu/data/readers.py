"""Binary/image file ingestion with recursive globs, zip traversal, and
seeded subsampling.

Analog of the reference's custom Spark datasources ``BinaryFileFormat`` /
``ImageFileFormat`` and the ``spark.readImages`` / ``spark.readBinaryFiles``
implicits (reference: readers/src/main/scala/BinaryFileFormat.scala:36-179,
ImageFileFormat.scala:43-82, Readers.scala:14-46). Design differences,
TPU-first:

* No Spark executors: files are listed host-side and read by a thread pool
  (IO-bound), the analog of per-host sharded ingest feeding HBM. For
  multi-host training each process passes its ``shard_index``/``num_shards``
  so hosts read disjoint file shards (no shuffle engine).
* Zip archives are traversed entry-by-entry without full extraction
  (``ZipIterator`` analog, reference: core/env/src/main/scala/
  StreamUtilities.scala:43-81).
* Subsampling is a deterministic per-record hash of the path against the
  seed, so a sample is reproducible across runs and hosts (the reference
  uses a seeded Random per split, BinaryFileFormat.scala:63-74).
* Decode prefers the native C++ extension (libjpeg/libpng), falling back to
  OpenCV — decode happens at read time like the reference's in-reader
  ``Imgcodecs.imdecode`` (ImageReader.scala:45-63).
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from concurrent.futures import ThreadPoolExecutor
from glob import glob as _glob
from typing import Any, Iterable, Iterator

import numpy as np

from mmlspark_tpu.core import fs as _fs
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.schema import make_image, mark_image_column
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.obs import runtime as _obs_rt
from mmlspark_tpu.obs.metrics import registry as _obs_registry
from mmlspark_tpu.obs.spans import span as _obs_span

_log = get_logger(__name__)

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".pgm", ".gif",
                    ".tif", ".tiff", ".webp")


def _keep(path: str, sample_ratio: float, seed: int) -> bool:
    """Deterministic per-path sampling decision."""
    if sample_ratio >= 1.0:
        return True
    digest = hashlib.sha1(f"{seed}:{path}".encode()).digest()
    frac = int.from_bytes(digest[:8], "big") / 2 ** 64
    return frac < sample_ratio


def list_files(path: str, recursive: bool = False,
               extensions: tuple | None = None) -> list[str]:
    """Expand a path/glob/dir into a sorted file list.

    Scheme'd paths (``memory://``, ``gs://``, …) list through the
    filesystem abstraction — the distributed-FS ingest path (core/hadoop
    analog)."""
    scheme, _ = _fs.split_scheme(path)
    if scheme and scheme != "file":
        files = _fs.list_files(path, recursive=recursive)
        if not files and not _fs.exists(path):
            # match the local branch: a typo'd prefix is an error, not a
            # silent empty dataset
            raise FileNotFoundError(path)
        if extensions:
            files = [f for f in files
                     if f.lower().endswith(extensions)
                     or f.lower().endswith(".zip")]
        return files
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "*")
        files = _glob(pattern, recursive=recursive)
    elif any(ch in path for ch in "*?["):
        files = _glob(path, recursive=recursive)
    elif os.path.isfile(path):
        files = [path]
    else:
        raise FileNotFoundError(path)
    files = [f for f in files if os.path.isfile(f)]
    if extensions:
        files = [f for f in files
                 if f.lower().endswith(extensions)
                 or f.lower().endswith(".zip")]
    return sorted(files)


def _iter_records(
    files: list[str],
    inspect_zip: bool,
    sample_ratio: float,
    seed: int,
    extensions: tuple | None,
) -> Iterator[tuple[str, bytes]]:
    """Yield (virtual_path, bytes). Zip entries get path 'archive.zip/entry'."""
    for f in files:
        if inspect_zip and f.lower().endswith(".zip"):
            # nested with: ZipFile does not close file objects it was given
            with _fs.open_file(f) as fp, zipfile.ZipFile(fp) as zf:
                for info in zf.infolist():
                    if info.is_dir():
                        continue
                    vpath = f"{f}/{info.filename}"
                    if extensions and not info.filename.lower().endswith(
                            extensions):
                        continue
                    if _keep(vpath, sample_ratio, seed):
                        yield vpath, zf.read(info)
        else:
            if _keep(f, sample_ratio, seed):
                with _fs.open_file(f, "rb") as fh:
                    yield f, fh.read()


def decode_image(data: bytes) -> np.ndarray | None:
    """Decode encoded image bytes to an HWC uint8 BGR array (OpenCV
    convention, matching the reference's Imgcodecs.imdecode output).

    Tries the native C++ extension first, then OpenCV.
    """
    from mmlspark_tpu.native import imgops
    arr = imgops.decode(data)
    if arr is not None:
        return arr
    try:
        import cv2
        decoded = cv2.imdecode(np.frombuffer(data, np.uint8),
                               cv2.IMREAD_COLOR)
        return decoded
    except Exception:
        return None


def stream_binary_files(
    path: str,
    recursive: bool = False,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    extensions: tuple | None = None,
    chunk_rows: int = 256,
) -> Iterator[DataTable]:
    """Stream whole files as chunked {path, bytes} DataTables.

    Bounded memory: at most ``chunk_rows`` records are alive at a time —
    the streaming-capable reader analog (reference:
    readers/src/main/scala/ImageReader.scala:85-98 ``ImageReader.stream``,
    non-splittable-but-streaming BinaryFileFormat.scala:118-179).
    """
    if not 0.0 <= sample_ratio <= 1.0:
        raise ValueError(f"sample_ratio must be in [0,1], got {sample_ratio}")
    files = list_files(path, recursive, extensions)
    files = files[shard_index::num_shards]
    paths: list[str] = []
    blobs: list[bytes] = []
    for vpath, data in _iter_records(files, inspect_zip, sample_ratio, seed,
                                     extensions):
        paths.append(vpath)
        blobs.append(data)
        if len(paths) >= chunk_rows:
            yield DataTable({"path": paths, "bytes": blobs})
            paths, blobs = [], []
    if paths:
        yield DataTable({"path": paths, "bytes": blobs})


DECODE_THREAD_PREFIX = "stream-images-decode"


def stream_images(
    path: str,
    recursive: bool = False,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    drop_invalid: bool = True,
    image_col: str = "image",
    num_threads: int = 8,
    chunk_rows: int = 256,
    resize: tuple | None = None,
) -> Iterator[DataTable]:
    """Stream decoded images as chunked image-struct DataTables.

    Each chunk decodes on a shared thread pool; memory is bounded by
    ``chunk_rows`` decoded images (ImageNet-shard-scale ingest without
    materializing the dataset). ONE pool serves the whole stream — a
    fresh pool per 256-row chunk cost ``num_threads`` thread spawns per
    chunk, pure overhead on shard-scale streams.

    ``resize`` is the EXPLICIT host-geometry opt-in: ``(h, w)`` resizes
    every decoded image on the decode pool (the legacy host-preprocess
    wire form, and the baseline side of the thin-wire A/B); the default
    ``None`` passes images through at SOURCE resolution — the thin-wire
    form, where a ``DevicePreprocess`` spec replays the geometry inside
    the jitted train step and only uint8 source pixels cross the link
    (docs/training_input.md §on-device preprocessing). No downstream
    stage silently depends on the reader's geometry either way.

    Pool lifetime: a consumer that abandons the generator early —
    ``close()``, a ``break``, or GC — shuts the pool down *synchronously*
    (in-flight decodes finish, every worker thread exits before close
    returns), so shard-scale training jobs that stop mid-stream never
    leak decode threads; tests/test_streaming.py pins it."""
    pool = (ThreadPoolExecutor(max_workers=num_threads,
                               thread_name_prefix=DECODE_THREAD_PREFIX)
            if num_threads > 1 else None)
    try:
        for raw in stream_binary_files(path, recursive, sample_ratio,
                                       inspect_zip, seed, shard_index,
                                       num_shards,
                                       extensions=IMAGE_EXTENSIONS,
                                       chunk_rows=chunk_rows):
            yield _decode_chunk(raw, drop_invalid, image_col, num_threads,
                                pool=pool, resize=resize)
    finally:
        # runs on generator close/GC too: an abandoned stream must not
        # leak its decode threads. wait=True makes the shutdown
        # deterministic — the (bounded, ≤ one chunk) in-flight decodes
        # drain and the workers exit before close() returns, instead of
        # lingering detached behind a fire-and-forget signal
        if pool is not None:
            pool.shutdown(wait=True)


def _decode_chunk(raw: DataTable, drop_invalid: bool, image_col: str,
                  num_threads: int,
                  pool: ThreadPoolExecutor | None = None,
                  resize: tuple | None = None) -> DataTable:
    if resize is not None:
        rh, rw = int(resize[0]), int(resize[1])

    def decode_one(args):
        p, b = args
        arr = decode_image(b)
        if arr is not None and resize is not None:
            from mmlspark_tpu.native import imgops
            arr = imgops.resize(arr, rh, rw)
        return (p, arr)

    records = list(zip(raw["path"], raw["bytes"]))
    # decode-pool span: one interval per chunk on the pulling thread (the
    # train-input producer when streaming), so a timeline shows decode
    # pressure against assemble/commit/step directly
    with _obs_span("data/decode_chunk", "data",
                   {"rows": len(records)} if _obs_rt._enabled else None):
        if len(records) > 1 and pool is not None:
            decoded = list(pool.map(decode_one, records))
        elif len(records) > 1 and num_threads > 1:
            # one-shot callers (read_images) still get a pool for this
            # chunk; num_threads <= 1 stays strictly sequential
            with ThreadPoolExecutor(
                    max_workers=num_threads,
                    thread_name_prefix=DECODE_THREAD_PREFIX) as one_shot:
                decoded = list(one_shot.map(decode_one, records))
        else:
            decoded = [decode_one(r) for r in records]

    images, n_bad = [], 0
    for p, arr in decoded:
        if arr is None:
            n_bad += 1
            if not drop_invalid:
                images.append(None)
            continue
        images.append(make_image(p, arr))
    if n_bad:
        _log.warning("read_images: %d/%d files failed to decode%s",
                     n_bad, len(decoded),
                     " (dropped)" if drop_invalid else " (kept as None)")
    if _obs_rt._enabled:
        reg = _obs_registry()
        reg.counter("data.images_decoded").add(len(decoded) - n_bad)
        if n_bad:
            reg.counter("data.decode_failures").add(n_bad)
    table = DataTable({image_col: images})
    return mark_image_column(table, image_col)


def read_binary_files(
    path: str,
    recursive: bool = False,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    extensions: tuple | None = None,
) -> DataTable:
    """Read whole files (or zip entries) as rows of {path, bytes}."""
    chunks = list(stream_binary_files(
        path, recursive, sample_ratio, inspect_zip, seed, shard_index,
        num_shards, extensions, chunk_rows=1 << 62))
    return chunks[0] if chunks else DataTable({"path": [], "bytes": []})


def read_images(
    path: str,
    recursive: bool = False,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    shard_index: int = 0,
    num_shards: int = 1,
    drop_invalid: bool = True,
    image_col: str = "image",
    num_threads: int = 8,
    resize: tuple | None = None,
) -> DataTable:
    """Read and decode images into an image-struct column.

    Returns a DataTable with column ``image`` of
    {path, height, width, channels, data} dicts (ImageSchema analog).
    ``resize``: optional host ``(h, w)`` resize on the decode pool —
    same explicit opt-in as :func:`stream_images`; default keeps source
    resolution.
    """
    raw = read_binary_files(path, recursive, sample_ratio, inspect_zip, seed,
                            shard_index, num_shards,
                            extensions=IMAGE_EXTENSIONS)
    return _decode_chunk(raw, drop_invalid, image_col, num_threads,
                         resize=resize)

"""DataTable — the columnar table every pipeline stage consumes and produces.

The reference's stages operate on Spark DataFrames whose columns carry
metadata (categorical levels, score-column roles) in an ``mml`` metadata tag
(reference: core/schema/src/main/scala/SparkSchema.scala:23-129,
Categoricals.scala:21-90). JAX is Python and single-process per host, so the
TPU-native analog is a light immutable-ish columnar table:

* columns are NumPy arrays (numeric / bool / fixed-width) or object arrays
  (strings, bytes, dicts, variable-length vectors),
* per-column metadata is a plain dict carried in ``table.meta[col]`` — the
  sidecar-schema replacement for Spark's column metadata facility,
* zero-copy round-trips to/from pandas and Arrow power the Spark offload
  bridge (Arrow batches from executors) and local files.

Partitioning: Spark's RDD partitions become an optional ``num_partitions``
hint plus :meth:`partitions` iteration used by sampling/repartition stages;
compute-heavy stages instead batch rows directly into device arrays.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Iterable, Iterator, Mapping, Sequence

import numpy as np


def is_missing(v: Any) -> bool:
    """True for None and float NaN of any width (Python float or np.floating).

    The single missing-value predicate shared by all stages (imputation,
    indexing, profiling, conversion) so semantics cannot drift.
    """
    if v is None:
        return True
    if isinstance(v, (float, np.floating)):
        return bool(np.isnan(v))
    return False


def to_py_scalar(v: Any) -> Any:
    """Unwrap a NumPy scalar to the equivalent Python scalar (pass-through
    otherwise) — the shared idiom for building dict keys / JSON values from
    column cells."""
    return v.item() if isinstance(v, np.generic) else v


def _object_column(values: Any) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def _as_column(values: Any) -> np.ndarray:
    """Coerce input values to a 1-D numpy column (object dtype if ragged)."""
    if isinstance(values, np.ndarray):
        if values.ndim == 1:
            return values
        # 2-D numeric arrays become object columns of row vectors
        return _object_column(values)
    values = list(values)
    if not values:
        return np.empty(0, dtype=object)
    first = values[0]
    if isinstance(first, (str, bytes, dict, list, tuple, np.ndarray)) or first is None:
        return _object_column(values)
    arr = np.asarray(values)
    if arr.ndim != 1:
        return _object_column(values)
    return arr


# Canonical image-struct contract. core/schema re-exports these — one
# definition of "image dict" for the whole framework (schema.py imports
# this module, so the constants must live here to avoid a cycle).
IMAGE_FIELDS = ("path", "height", "width", "channels", "data")
K_IMAGE = "is_image"            # column-meta marker for image columns
# wire format over Arrow: the ImageSchema struct plus 'mode' carrying the
# numpy dtype so float images round-trip
_IMAGE_WIRE_FIELDS = {"path", "height", "width", "channels", "mode", "data"}


def _looks_like_image_column(col: np.ndarray) -> bool:
    """Unmarked-column fallback: EVERY non-None row must be a dict with
    exactly the image fields. Subset/first-row sniffing would hijack
    generic dict columns that merely share key names (and silently drop
    their extra keys on the wire); columns marked via ``K_IMAGE`` meta
    skip this and get strict per-row validation instead."""
    want = set(IMAGE_FIELDS)
    seen = False
    for v in col:
        if v is None:
            continue
        if not (isinstance(v, dict) and set(v.keys()) == want):
            return False
        seen = True
    return seen


def _image_structs_to_arrow(name: str, col: np.ndarray) -> Any:
    import pyarrow as pa
    paths, hs, ws, cs, modes, blobs = [], [], [], [], [], []
    mask = []
    for i, v in enumerate(col):
        if v is None:
            mask.append(True)
            paths.append(None); hs.append(None); ws.append(None)
            cs.append(None); modes.append(None); blobs.append(None)
            continue
        if not (isinstance(v, dict) and set(IMAGE_FIELDS) <= set(v.keys())):
            raise ValueError(
                f"image column {name!r} row {i} is not an image struct "
                f"(need fields {IMAGE_FIELDS}, got {v!r:.120})")
        mask.append(False)
        arr = np.ascontiguousarray(np.asarray(v["data"]))
        h, w, c = int(v["height"]), int(v["width"]), int(v["channels"])
        if arr.size != h * w * c:
            raise ValueError(
                f"image column {name!r} row {i}: data has {arr.size} "
                f"values, dims say {h}x{w}x{c}")
        paths.append(v.get("path", ""))
        hs.append(h)
        ws.append(w)
        cs.append(c)
        modes.append(arr.dtype.str)
        blobs.append(arr.tobytes())
    return pa.StructArray.from_arrays(
        [pa.array(paths, pa.string()), pa.array(hs, pa.int32()),
         pa.array(ws, pa.int32()), pa.array(cs, pa.int32()),
         pa.array(modes, pa.string()), pa.array(blobs, pa.binary())],
        names=["path", "height", "width", "channels", "mode", "data"],
        mask=pa.array(mask, pa.bool_()))


def _image_structs_from_arrow(col: Any) -> list:
    out = []
    for v in col.to_pylist():
        if v is None:
            out.append(None)
            continue
        h, w, c = int(v["height"]), int(v["width"]), int(v["channels"])
        # copy: frombuffer over bytes is read-only, but image dicts are
        # writable everywhere else (in-place normalization must not crash
        # only on tables that crossed the bridge)
        data = np.frombuffer(v["data"],
                             np.dtype(v["mode"])).reshape(h, w, c).copy()
        out.append({"path": v["path"], "height": h, "width": w,
                    "channels": c, "data": data})
    return out


class DataTable:
    """An ordered mapping column-name → 1-D column, with per-column metadata."""

    def __init__(
        self,
        columns: Mapping[str, Any] | None = None,
        meta: Mapping[str, Mapping[str, Any]] | None = None,
        num_partitions: int | None = None,
    ):
        self._cols: dict[str, np.ndarray] = {}
        n = None
        for name, values in (columns or {}).items():
            col = _as_column(values)
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(
                    f"column {name!r} has {len(col)} rows, expected {n}")
            self._cols[name] = col
        self._nrows = n or 0
        # sidecar schema: per-column metadata (categorical levels, score
        # roles, image flag, …) — the `mml` metadata-tag analog
        self.meta: dict[str, dict[str, Any]] = {
            k: dict(v) for k, v in (meta or {}).items() if k in self._cols
        }
        self.num_partitions = num_partitions

    # ---- basic accessors ----

    @property
    def columns(self) -> list[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._nrows

    @property
    def num_rows(self) -> int:
        return self._nrows

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(
                f"no column {name!r}; available: {self.columns}")
        return self._cols[name]

    def column_meta(self, name: str) -> dict[str, Any]:
        return self.meta.get(name, {})

    def dtype(self, name: str) -> np.dtype:
        return self[name].dtype

    def __repr__(self) -> str:
        cols = ", ".join(f"{k}:{v.dtype}" for k, v in self._cols.items())
        return f"DataTable[{self._nrows} rows; {cols}]"

    # ---- functional updates (tables are treated as immutable) ----

    def with_column(
        self,
        name: str,
        values: Any,
        meta: Mapping[str, Any] | None = None,
    ) -> "DataTable":
        col = _as_column(values)
        if self._cols and len(col) != self._nrows:
            raise ValueError(
                f"column {name!r} has {len(col)} rows, expected {self._nrows}")
        out = self._shallow_copy()
        out._cols[name] = col
        if self._cols == {}:
            out._nrows = len(col)
        if meta is not None:
            out.meta[name] = dict(meta)
        return out

    def with_meta(self, name: str, **meta: Any) -> "DataTable":
        """Merge metadata entries into a column's sidecar schema."""
        if name not in self._cols:
            raise KeyError(f"no column {name!r}")
        out = self._shallow_copy()
        out.meta.setdefault(name, {})
        out.meta[name] = {**out.meta[name], **meta}
        return out

    def select(self, *names: str) -> "DataTable":
        for n in names:
            if n not in self._cols:
                raise KeyError(f"no column {n!r}; available: {self.columns}")
        return DataTable(
            {n: self._cols[n] for n in names},
            {n: self.meta[n] for n in names if n in self.meta},
            self.num_partitions,
        )

    def drop(self, *names: str) -> "DataTable":
        keep = [n for n in self.columns if n not in names]
        return self.select(*keep)

    def rename(self, mapping: Mapping[str, str]) -> "DataTable":
        cols = {mapping.get(k, k): v for k, v in self._cols.items()}
        meta = {mapping.get(k, k): v for k, v in self.meta.items()}
        return DataTable(cols, meta, self.num_partitions)

    def take(self, indices: Any) -> "DataTable":
        """Row subset/reorder by integer indices or boolean mask."""
        indices = np.asarray(indices)
        if indices.dtype == bool:
            indices = np.flatnonzero(indices)
        elif not np.issubdtype(indices.dtype, np.integer):
            indices = indices.astype(np.intp)  # e.g. empty list → float64
        return DataTable(
            {k: v[indices] for k, v in self._cols.items()},
            self.meta,
            self.num_partitions,
        )

    def head(self, n: int) -> "DataTable":
        return self.take(np.arange(min(n, self._nrows)))

    def filter(self, predicate: Callable[[dict[str, Any]], bool]) -> "DataTable":
        mask = np.fromiter(
            (bool(predicate(row)) for row in self.iter_rows()),
            dtype=bool, count=self._nrows)
        return self.take(mask)

    def concat(self, other: "DataTable") -> "DataTable":
        if set(self.columns) != set(other.columns):
            raise ValueError(
                f"column mismatch: {self.columns} vs {other.columns}")
        cols = {}
        for k in self.columns:
            a, b = self._cols[k], other._cols[k]
            if a.dtype == object or b.dtype == object:
                merged = np.empty(len(a) + len(b), dtype=object)
                merged[:len(a)] = a
                merged[len(a):] = b
                cols[k] = merged
            else:
                cols[k] = np.concatenate([a, b])
        meta = {**other.meta, **self.meta}
        return DataTable(cols, meta, self.num_partitions)

    def _shallow_copy(self) -> "DataTable":
        out = DataTable.__new__(DataTable)
        out._cols = dict(self._cols)
        out._nrows = self._nrows
        out.meta = {k: dict(v) for k, v in self.meta.items()}
        out.num_partitions = self.num_partitions
        return out

    # ---- row iteration (for host-side stages; device stages batch columns) --

    def iter_rows(self) -> Iterator[dict[str, Any]]:
        names = self.columns
        cols = [self._cols[n] for n in names]
        for i in range(self._nrows):
            yield {n: c[i] for n, c in zip(names, cols)}

    def to_rows(self) -> list[dict[str, Any]]:
        return list(self.iter_rows())

    # ---- partitioning (analog of RDD partitions for sampling stages) ----

    def partitions(self, n: int | None = None) -> list["DataTable"]:
        n = n or self.num_partitions or 1
        n = max(1, min(n, max(1, self._nrows)))
        bounds = np.linspace(0, self._nrows, n + 1).astype(int)
        return [self.take(np.arange(bounds[i], bounds[i + 1]))
                for i in range(n)]

    def repartition(self, n: int) -> "DataTable":
        out = self._shallow_copy()
        out.num_partitions = n
        return out

    # ---- conversions ----

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]],
                  meta: Mapping[str, Mapping[str, Any]] | None = None
                  ) -> "DataTable":
        if not rows:
            return DataTable()
        # union of all row keys in first-encounter order — keys absent from
        # the first row must not be silently dropped; missing cells are None
        names = list(dict.fromkeys(k for r in rows for k in r))
        return DataTable({n: [r.get(n) for r in rows] for n in names}, meta)

    @staticmethod
    def from_pandas(df: Any, meta: Mapping[str, Mapping[str, Any]] | None = None
                    ) -> "DataTable":
        cols = {}
        for name in df.columns:
            s = df[name]
            if str(s.dtype) == "object" or str(s.dtype).startswith(("str", "string")):
                cols[name] = s.tolist()
            else:
                cols[name] = s.to_numpy()
        return DataTable(cols, meta)

    def to_pandas(self) -> Any:
        import pandas as pd
        return pd.DataFrame({k: v for k, v in self._cols.items()})

    @staticmethod
    def from_arrow(batch: Any, meta: Mapping[str, Mapping[str, Any]] | None = None
                   ) -> "DataTable":
        """From a pyarrow Table or RecordBatch (the Spark-bridge wire format).

        Image-struct columns (the ImageSchema wire shape:
        path/height/width/channels/mode/data-bytes) rebuild into the
        in-memory image dicts and the column is marked as an image column.
        """
        import pyarrow as pa
        cols: dict[str, Any] = {}
        image_cols: list[str] = []
        for name in batch.schema.names:
            col = batch.column(name)
            field_type = batch.schema.field(name).type
            # exact field-set match, mirroring _looks_like_image_column on
            # the serialize side — a non-image struct that happens to carry
            # these six names PLUS extras must not be rebuilt as images
            # (which would silently drop its extra fields)
            if (pa.types.is_struct(field_type)
                    and {f.name for f in field_type} == _IMAGE_WIRE_FIELDS):
                cols[name] = _image_structs_from_arrow(col)
                image_cols.append(name)
                continue
            try:
                cols[name] = col.to_numpy(zero_copy_only=False)
            except Exception:
                cols[name] = col.to_pylist()
        table = DataTable(cols, meta)
        for name in image_cols:
            table = table.with_meta(name, **{K_IMAGE: True})
        return table

    def to_arrow(self) -> Any:
        """To a pyarrow Table. Image-struct columns serialize as a struct of
        (path, height, width, channels, mode, data-bytes) — the Arrow form
        of the reference's ImageSchema (reference:
        core/schema/src/main/scala/ImageSchema.scala:12-17), so image
        tables cross the Spark bridge losslessly."""
        import pyarrow as pa
        arrays = {}
        for k, v in self._cols.items():
            is_image = self.column_meta(k).get(K_IMAGE) or (
                v.dtype == object and _looks_like_image_column(v))
            if is_image:
                arrays[k] = _image_structs_to_arrow(k, v)
            elif v.dtype == object:
                arrays[k] = pa.array(list(v))
            else:
                arrays[k] = pa.array(v)
        return pa.table(arrays)

    @staticmethod
    def from_csv(path: str, **kwargs: Any) -> "DataTable":
        import pandas as pd
        return DataTable.from_pandas(pd.read_csv(path, **kwargs))

    # ---- batch extraction for device compute ----

    def column_matrix(self, name: str, dtype: Any = np.float32) -> np.ndarray:
        """Stack a column of equal-length vectors/scalars into a 2-D matrix.

        This is the host-side marshalling step that replaces the reference's
        per-element JNI FloatVector copies (reference:
        cntk-model/src/main/scala/CNTKModel.scala:67-74) with one vectorized
        contiguous copy ready for device transfer.
        """
        col = self._cols[name]
        if col.dtype != object:
            return col.astype(dtype)[:, None] if col.ndim == 1 else col.astype(dtype)
        if self._nrows == 0:
            return np.empty((0, 0), dtype=dtype)
        return np.stack([np.asarray(v, dtype=dtype).reshape(-1) for v in col])

"""ComputeModelStatistics / ComputePerInstanceStatistics — metadata-driven
evaluation.

Analog of the reference's ``src/compute-model-statistics/`` and
``src/compute-per-instance-statistics/`` (reference:
ComputeModelStatistics.scala:22-339, ComputePerInstanceStatistics.scala:16-50).
Like the reference, the evaluators locate the label / scores / scored-labels
columns and the score kind from the column metadata stamped by Train*
models (the ``mml`` metadata protocol) rather than taking mandatory column
params — explicit params are overrides.

Classification: accuracy, precision, recall, AUC (binary), confusion
matrix, ROC curve; micro/macro averaged precision/recall for multiclass.
Regression: mse, rmse, r2, mae. All exact vectorized NumPy (the reference
runs Spark reduce jobs).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import (
    SchemaConstants, find_score_column, get_categorical_levels,
    get_score_value_kind,
)
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.stages.indexers import index_values

# evaluation metric selectors (reference: ComputeModelStatistics.scala:22-41)
CLASSIFICATION_METRICS = "classification"
REGRESSION_METRICS = "regression"
ALL_METRICS = "all"


def confusion_matrix(y: np.ndarray, pred: np.ndarray, k: int) -> np.ndarray:
    """Counts over rows whose codes are in [0, k); out-of-range codes (the
    index_values -1 'unseen' sentinel) are excluded rather than silently
    wrapping into the last class via negative indexing."""
    cm = np.zeros((k, k), dtype=np.int64)
    valid = (y >= 0) & (y < k) & (pred >= 0) & (pred < k)
    np.add.at(cm, (y[valid], pred[valid]), 1)
    return cm


def roc_curve(y: np.ndarray, score: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Exact ROC: (fpr, tpr, thresholds), scores descending."""
    if len(score) == 0:
        return (np.array([0.0, 1.0]), np.array([0.0, 1.0]),
                np.array([np.inf, -np.inf]))
    order = np.argsort(-score, kind="stable")
    y_sorted = y[order]
    tps = np.cumsum(y_sorted)
    fps = np.cumsum(1 - y_sorted)
    p = max(int(tps[-1]) if len(tps) else 0, 1)
    n = max(int(fps[-1]) if len(fps) else 0, 1)
    # keep the last point of each threshold run
    thr = score[order]
    keep = np.r_[np.diff(thr) != 0, True]
    tpr = np.r_[0.0, tps[keep] / p]
    fpr = np.r_[0.0, fps[keep] / n]
    return fpr, tpr, np.r_[np.inf, thr[keep]]


def auc(fpr: np.ndarray, tpr: np.ndarray) -> float:
    return float(np.trapezoid(tpr, fpr))


def _locate(table: DataTable, label_col: str | None, scores_col: str | None,
            scored_labels_col: str | None) -> tuple[str | None, str | None,
                                                    str | None, str | None]:
    """Resolve (kind, label, scores, scored_labels) from metadata with
    param overrides (getSchemaInfo analog,
    reference: ComputeModelStatistics.scala:213-226)."""
    scores = scores_col or find_score_column(
        table, SchemaConstants.SCORES_COLUMN)
    scored_labels = scored_labels_col or find_score_column(
        table, SchemaConstants.SCORED_LABELS_COLUMN)
    label = label_col or find_score_column(
        table, SchemaConstants.LABEL_COLUMN)
    kind = None
    for c in (scores, scored_labels):
        if c is not None:
            kind = get_score_value_kind(table, c)
            if kind:
                break
    return kind, label, scores, scored_labels


class ComputeModelStatistics(Transformer):
    """Aggregate metrics; returns a one-row metrics table. The confusion
    matrix and ROC are exposed on ``self.confusion_matrix_`` /
    ``self.roc_`` after transform (the reference returns them through
    separate transformer outputs)."""

    evaluation_metric = Param(
        default=ALL_METRICS, doc="which metric family to compute", type_=str,
        validator=Param.one_of(CLASSIFICATION_METRICS, REGRESSION_METRICS,
                               ALL_METRICS))
    label_col = Param(default=None, doc="label column override", type_=str)
    scores_col = Param(default=None, doc="scores column override", type_=str)
    scored_labels_col = Param(default=None,
                              doc="scored-labels column override", type_=str)

    def transform(self, table: DataTable) -> DataTable:
        kind, label, scores, scored_labels = _locate(
            table, self.label_col, self.scores_col, self.scored_labels_col)
        metric = self.evaluation_metric
        if metric == ALL_METRICS:
            if kind == SchemaConstants.CLASSIFICATION_KIND:
                metric = CLASSIFICATION_METRICS
            elif kind == SchemaConstants.REGRESSION_KIND:
                metric = REGRESSION_METRICS
            else:
                raise ValueError(
                    "no score metadata found on the table; set "
                    "evaluation_metric and column params explicitly")
        if metric == CLASSIFICATION_METRICS:
            return self._classification(table, label, scores, scored_labels)
        return self._regression(table, label, scores)

    # -- classification --

    def _classification(self, table: DataTable, label: str | None,
                        scores: str | None, scored_labels: str | None
                        ) -> DataTable:
        if label is None or scored_labels is None:
            raise ValueError("need label and scored-labels columns "
                             "(metadata or params)")
        levels = get_categorical_levels(table, scored_labels)
        if levels is None:
            vals = list(table[label]) + list(table[scored_labels])
            from mmlspark_tpu.stages.indexers import sorted_levels
            levels = sorted_levels(np.asarray(vals, dtype=object))
        y = index_values(table[label], levels).astype(np.int64)
        pred = index_values(table[scored_labels], levels).astype(np.int64)
        k = max(len(levels), 2)
        cm = confusion_matrix(y, pred, k)
        self.confusion_matrix_ = cm

        # rows whose TRUE label is unseen (-1) cannot be scored and are
        # excluded; an unseen PREDICTED label counts as an error. Recall
        # denominators therefore count every scorable row (not just cm rows,
        # which exclude invalid predictions); precision is per predicted
        # class, so invalid predictions contribute to no class.
        scorable = (y >= 0) & (y < k)
        y, pred = y[scorable], pred[scorable]
        n = len(y)
        accuracy = float((y == pred).sum()) / n if n else 0.0
        tp = np.diag(cm).astype(np.float64)
        pred_pos = cm.sum(axis=0).astype(np.float64)
        actual_pos = np.bincount(y, minlength=k).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            prec_per = np.where(pred_pos > 0, tp / pred_pos, 0.0)
            rec_per = np.where(actual_pos > 0, tp / actual_pos, 0.0)

        row: dict[str, Any] = {"evaluation_type": "Classification",
                               "accuracy": accuracy}
        if k == 2:
            # positive class = level 1 (the reference evaluates the indexed
            # positive label of BinaryClassificationMetrics)
            row["precision"] = float(prec_per[1])
            row["recall"] = float(rec_per[1])
            auc_val = None
            if scores is not None:
                proba = table.column_matrix(scores, dtype=np.float64)
                pos_score = (proba[:, 1] if proba.ndim == 2
                             and proba.shape[1] >= 2 else proba.reshape(-1))
                fpr, tpr, _ = roc_curve(y, pos_score[scorable])
                self.roc_ = np.stack([fpr, tpr], axis=1)
                auc_val = auc(fpr, tpr)
            row["AUC"] = auc_val
        else:
            micro = float(tp.sum() / n) if n else 0.0
            row["micro_precision"] = micro
            row["micro_recall"] = micro
            row["macro_precision"] = float(prec_per.mean())
            row["macro_recall"] = float(rec_per.mean())
        return DataTable.from_rows([row])

    # -- regression --

    def _regression(self, table: DataTable, label: str | None,
                    scores: str | None) -> DataTable:
        if label is None or scores is None:
            raise ValueError("need label and scores columns "
                             "(metadata or params)")
        y = np.asarray(table[label], dtype=np.float64)
        pred = np.asarray(table[scores], dtype=np.float64)
        err = y - pred
        mse = float(np.mean(err ** 2)) if len(y) else 0.0
        var = float(np.var(y)) if len(y) else 0.0
        r2 = 1.0 - mse / var if var > 0 else 0.0
        return DataTable.from_rows([{
            "evaluation_type": "Regression",
            "mean_squared_error": mse,
            "root_mean_squared_error": float(np.sqrt(mse)),
            "R^2": r2,
            "mean_absolute_error": float(np.mean(np.abs(err)))
            if len(y) else 0.0,
        }])


class ComputePerInstanceStatistics(Transformer):
    """Per-row metrics appended as columns: L1/L2 loss for regression,
    log_loss for classification (reference:
    ComputePerInstanceStatistics.scala:16-50)."""

    label_col = Param(default=None, doc="label column override", type_=str)
    scores_col = Param(default=None, doc="scores column override", type_=str)
    scored_labels_col = Param(default=None,
                              doc="scored-labels column override", type_=str)
    epsilon = Param(default=1e-15, doc="log-loss clamp", type_=float)

    def transform(self, table: DataTable) -> DataTable:
        kind, label, scores, scored_labels = _locate(
            table, self.label_col, self.scores_col, self.scored_labels_col)
        if kind == SchemaConstants.REGRESSION_KIND or (
                kind is None and scored_labels is None):
            if label is None or scores is None:
                raise ValueError(
                    "need label and scores columns: the table carries no "
                    "score metadata, so set label_col/scores_col explicitly")
            y = np.asarray(table[label], dtype=np.float64)
            pred = np.asarray(table[scores], dtype=np.float64)
            out = table.with_column("L1_loss", np.abs(y - pred))
            return out.with_column("L2_loss", (y - pred) ** 2)
        # classification log-loss from the probability vectors
        if label is None or scores is None:
            raise ValueError(
                "need label and scores columns: the scored-labels metadata "
                "identifies a classification table but no label/probability "
                "columns were found — set label_col/scores_col explicitly")
        levels = get_categorical_levels(table, scored_labels)
        if levels is None:
            raise ValueError("scored-labels column carries no levels")
        y = index_values(table[label], levels).astype(np.int64)
        proba = table.column_matrix(scores, dtype=np.float64)
        eps = self.epsilon
        # unseen labels (code -1 or >= #classes) get NaN loss rather than a
        # silently wrong number computed against an arbitrary class
        valid = (y >= 0) & (y < proba.shape[1])
        loss = np.full(len(y), np.nan)
        rows = np.flatnonzero(valid)
        p_true = np.clip(proba[rows, y[rows]], eps, 1.0)
        loss[rows] = -np.log(p_true)
        return table.with_column("log_loss", loss)

"""FindBestModel — model selection across trained models by metric.

Analog of the reference's ``src/find-best-model/`` (reference:
FindBestModel.scala:80-150): evaluates each candidate model on the given
table with ComputeModelStatistics, picks the best by the chosen metric,
and exposes the full metrics table (``all_model_metrics_``) and the best
model's ROC the way the reference records ``rocCurve``/``bestModelMetrics``.
"""

from __future__ import annotations

from typing import Any

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, Transformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml.metrics import ComputeModelStatistics

# metric → (column in the metrics row, higher is better)
_METRIC_INFO = {
    "accuracy": ("accuracy", True),
    "AUC": ("AUC", True),
    "precision": ("precision", True),
    "recall": ("recall", True),
    "mse": ("mean_squared_error", False),
    "rmse": ("root_mean_squared_error", False),
    "r2": ("R^2", True),
    "mae": ("mean_absolute_error", False),
}


class FindBestModel(Estimator):
    """Selects the best of several fitted models by an evaluation metric.

    Scores every candidate on the given table with
    :class:`ComputeModelStatistics` and keeps the winner plus the full
    metrics table (reference: find-best-model/src/main/scala/
    FindBestModel.scala:80-130)."""

    models = Param(default=None, doc="candidate fitted models",
                   is_complex=True)
    evaluation_metric = Param(default="accuracy", doc="selection metric",
                              type_=str,
                              validator=Param.one_of(*_METRIC_INFO))

    def fit(self, table: DataTable) -> "BestModel":
        models = list(self.models or [])
        if not models:
            raise ValueError("no candidate models")
        col, higher_better = _METRIC_INFO[self.evaluation_metric]
        rows: list[dict[str, Any]] = []
        best_i, best_v, best_roc = -1, None, None
        for i, model in enumerate(models):
            scored = model.transform(table)
            evaluator = ComputeModelStatistics()
            metrics = evaluator.transform(scored)
            row = dict(metrics.to_rows()[0])
            row["model"] = f"{type(model).__name__}[{model.uid}]"
            rows.append(row)
            v = row.get(col)
            if v is None:
                raise ValueError(
                    f"metric {self.evaluation_metric!r} not produced for "
                    f"model {row['model']} (got {sorted(row)})")
            better = (best_v is None or
                      (v > best_v if higher_better else v < best_v))
            if better:
                best_i, best_v = i, v
                best_roc = getattr(evaluator, "roc_", None)
        best = BestModel(
            best_model=models[best_i],
            best_metric=float(best_v),
            evaluation_metric=self.evaluation_metric)
        best.all_model_metrics_ = DataTable.from_rows(rows)
        best.roc_ = best_roc
        return best


class BestModel(Transformer):
    """The winning model from :class:`FindBestModel`, with its metric and
    the per-candidate metrics table on ``all_model_metrics_``."""

    best_model = Param(default=None, doc="the winning fitted model",
                       is_complex=True)
    best_metric = Param(default=None, doc="winning metric value",
                        type_=float)
    evaluation_metric = Param(default="accuracy", doc="selection metric",
                              type_=str)

    def transform(self, table: DataTable) -> DataTable:
        return self.best_model.transform(table)

"""Classical train/evaluate layer.

Analog of the reference's L5: ``src/train-classifier/``,
``src/train-regressor/``, ``src/compute-model-statistics/``,
``src/compute-per-instance-statistics/``, ``src/find-best-model/``.
The reference delegates learning to SparkML learners; here the learner
family is JAX-native (jit-compiled full-batch/minibatch training on the
accelerator) with host-side tree learners gated behind scikit-learn.
"""

from mmlspark_tpu.ml.learners import (
    DecisionTreeClassifier, DecisionTreeRegressor, GBTClassifier,
    GBTRegressor, LinearRegression, LogisticRegression, MLPClassifier,
    MLPRegressor, NaiveBayes, RandomForestClassifier, RandomForestRegressor,
)
from mmlspark_tpu.ml.metrics import (
    ComputeModelStatistics, ComputePerInstanceStatistics,
)
from mmlspark_tpu.ml.find_best import BestModel, FindBestModel
from mmlspark_tpu.ml.train_classifier import (
    TrainClassifier, TrainedClassifierModel,
)
from mmlspark_tpu.ml.train_regressor import (
    TrainRegressor, TrainedRegressorModel,
)

__all__ = [
    "BestModel", "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "DecisionTreeClassifier", "DecisionTreeRegressor", "FindBestModel",
    "GBTClassifier", "GBTRegressor", "LinearRegression",
    "LogisticRegression", "MLPClassifier", "MLPRegressor", "NaiveBayes",
    "RandomForestClassifier", "RandomForestRegressor", "TrainClassifier",
    "TrainedClassifierModel", "TrainRegressor", "TrainedRegressorModel",
]

"""TrainRegressor — one-call regression over a mixed-type table.

Analog of the reference's ``src/train-regressor/`` (reference:
TrainRegressor.scala:52-160): label cast to double (:84-104), automatic
featurization per learner family, learner fit; the fitted model stamps
Regression score metadata.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import (
    SchemaConstants, set_label_column, set_score_column,
)
from mmlspark_tpu.core.stage import Estimator, HasLabelCol, Transformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.ml.learners import Learner, LinearRegression
from mmlspark_tpu.ml.train_classifier import (
    drop_missing_labels, featurize_and_extract, featurize_params_for,
)


class TrainRegressor(Estimator, HasLabelCol):
    """One-call regression: label cast + automatic featurization + learner
    fit (reference: train-regressor/src/main/scala/TrainRegressor.scala:52-130)."""

    model = Param(default=None, doc="the learner to fit (default "
                  "LinearRegression)", is_complex=True)
    feature_columns = Param(default=None, doc="input columns to featurize "
                            "(default: all but the label)",
                            type_=(list, tuple))
    number_of_features = Param(default=None, doc="hash-slot override",
                               type_=int)

    def fit(self, table: DataTable) -> "TrainedRegressorModel":
        learner: Learner = self.model or LinearRegression()
        if learner.is_classifier:
            raise ValueError(f"{type(learner).__name__} is not a regressor")
        table = drop_missing_labels(table, self.label_col)
        labels = table[self.label_col]
        if labels.dtype == object:
            y = np.asarray([float(v) for v in labels], dtype=np.float64)
        else:
            y = labels.astype(np.float64)

        n_feats, one_hot = featurize_params_for(learner)
        if self.number_of_features:
            n_feats = self.number_of_features
        feat_model, features_col, x, y = featurize_and_extract(
            table, self.label_col, y, self.feature_columns, n_feats, one_hot)
        y = y.astype(np.float64)

        fitted = learner.fit_arrays(x, y)
        return TrainedRegressorModel(
            label_col=self.label_col, features_col=features_col,
            featurize_model=feat_model, fitted_learner=fitted)

    def infer_schema(self, schema):
        from mmlspark_tpu.ml.train_classifier import _train_infer_schema
        return _train_infer_schema(self, schema, classification=False)

    def infer_rows(self, n, schema):
        from mmlspark_tpu.ml.train_classifier import _train_infer_rows
        return _train_infer_rows(self, n, schema)


class TrainedRegressorModel(Transformer, HasLabelCol):
    """Fitted :class:`TrainRegressor`: featurizes, predicts, and stamps
    regression score metadata (reference: TrainRegressor.scala)."""

    features_col = Param(default="features", doc="assembled features column",
                         type_=str)
    featurize_model = Param(default=None, doc="fitted featurization pipeline",
                            is_complex=True)
    fitted_learner = Param(default=None, doc="fitted learner",
                           is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        out = self.featurize_model.transform(table)
        x = out.column_matrix(self.features_col)
        pred, _ = self.fitted_learner.predict_arrays(x)

        scores_col = SchemaConstants.SCORES_COLUMN
        kind = SchemaConstants.REGRESSION_KIND
        out = out.drop(self.features_col)
        out = out.with_column(scores_col, np.asarray(pred, dtype=np.float64))
        out = set_score_column(out, self.uid, scores_col,
                               SchemaConstants.SCORES_COLUMN, kind)
        if self.label_col in out:
            out = set_label_column(out, self.uid, self.label_col, kind)
        return out

    def infer_schema(self, schema):
        from mmlspark_tpu.ml.train_classifier import _score_column_infos
        out = self.featurize_model.infer_schema(schema)
        out = out.drop(self.features_col)
        out.columns.update(_score_column_infos(
            self.uid, SchemaConstants.REGRESSION_KIND, None, None,
            classification=False))
        if self.label_col in out.columns:
            li = out.columns[self.label_col]
            li.meta[SchemaConstants.K_COLUMN_PURPOSE] = \
                SchemaConstants.LABEL_COLUMN
            li.meta[SchemaConstants.K_MODEL_UID] = self.uid
            li.meta[SchemaConstants.K_SCORE_VALUE_KIND] = \
                SchemaConstants.REGRESSION_KIND
        return out

    def infer_rows(self, n, schema):
        # scoring re-runs the featurization, whose na.drop analog may
        # remove rows — delegate to the fitted featurize pipeline
        if n is None:
            return None
        return self.featurize_model.infer_rows(n, schema)

"""The learner family behind TrainClassifier / TrainRegressor.

The reference passes SparkML learners (LogisticRegression, DecisionTree,
GBT, RandomForest, NaiveBayes, MultilayerPerceptron, LinearRegression, …)
into ``TrainClassifier``/``TrainRegressor`` (reference:
train-classifier/src/main/scala/TrainClassifier.scala:97-201,
VerifyTrainClassifier.scala benchmark matrix). Here the same roles are
filled TPU-first:

* **JAX learners** (LogisticRegression, LinearRegression, MLP*) — the
  featurized matrix is one dense device array; training is a jit-compiled
  optax loop whose per-step cost is a batched matmul on the MXU. bfloat16 is
  not used at these tiny widths; float32 keeps parity with CI tolerances.
* **NaiveBayes** — closed-form count statistics (one pass, vectorized).
* **Tree learners** (DecisionTree/RandomForest/GBT ×{Classifier,Regressor})
  — host-side, delegated to scikit-learn when available (the featurize
  hash-size heuristic treats them as the reference treats tree learners);
  they raise a clear error if sklearn is absent.

Every learner implements ``fit_arrays(X, y) -> FittedLearner`` with
``predict_arrays(X) -> (labels_or_values, probabilities_or_None)``;
DataTable plumbing lives in TrainClassifier/TrainRegressor, keeping the
learner layer a pure array API (easy to jit, easy to fuzz).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from mmlspark_tpu.core.params import Param, Params

# learner families for the featurize hash-size heuristic
# (reference: TrainClassifier.scala:186-201)
FAMILY_LINEAR = "linear"
FAMILY_TREE = "tree"
FAMILY_NN = "nn"


class Learner(Params):
    """A learner is param'd config + fit_arrays; not itself a pipeline
    stage (TrainClassifier wraps it)."""

    family: str = FAMILY_LINEAR
    is_classifier: bool = True

    def fit_arrays(self, x: np.ndarray, y: np.ndarray,
                   num_classes: int | None = None) -> "FittedLearner":
        raise NotImplementedError


class FittedLearner:
    # input-pipeline accounting for learners trained through _train_jax
    # (input_bound_fraction et al.; None for closed-form/host learners)
    input_stats: dict | None = None

    def predict_arrays(self, x: np.ndarray
                       ) -> tuple[np.ndarray, np.ndarray | None]:
        """Return (predictions, probabilities-or-None)."""
        raise NotImplementedError


# ---- JAX linear / MLP learners ----

# committed-batch lookahead for the learner train loops (see
# train/input.DeviceLoader): the permutation gather + H2D upload of batch
# i+1 overlaps the compiled step of batch i. Numerics are unchanged at any
# depth; 2 is classic double-buffering
LEARNER_PREFETCH_DEPTH = 2


def _train_jax(loss_fn: Callable, params0: Any, x: np.ndarray, y: np.ndarray,
               learning_rate: float, epochs: int, batch_size: int,
               seed: int, weight_decay: float = 0.0,
               stats_out: dict | None = None) -> Any:
    """Shared jit-compiled optax Adam loop over padded minibatches.

    Batch assembly (the shuffled fancy-index gather) and the device commit
    run on a background thread ``LEARNER_PREFETCH_DEPTH`` steps ahead of
    consumption, so the step loop only pulls device-resident batches.
    ``stats_out``, when given, receives the input-wait/step-time
    decomposition (``input_bound_fraction`` et al.)."""
    import time

    import jax
    import optax

    from mmlspark_tpu.train.input import DeviceLoader, input_stats

    n = x.shape[0]
    batch_size = int(min(batch_size, n))
    steps_per_epoch = -(-n // batch_size)  # ceil: tail rows get visited
    opt = optax.adamw(learning_rate, weight_decay=weight_decay) \
        if weight_decay else optax.adam(learning_rate)
    opt_state = opt.init(params0)

    @jax.jit
    def step(params, opt_state, xb, yb):
        # classical learners are tiny: full-f32 matmuls cost nothing on the
        # MXU but the default bf16 visibly degrades tabular accuracy (the
        # CPU and TPU backends must agree on what these models learn)
        with jax.default_matmul_precision("float32"):
            loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def host_batches():
        rng = np.random.default_rng(seed)
        for _ in range(epochs):
            order = rng.permutation(n)
            for s in range(steps_per_epoch):
                idx = order[s * batch_size:(s + 1) * batch_size]
                if len(idx) < batch_size:  # static shapes for the jit cache
                    idx = np.concatenate([idx,
                                          order[:batch_size - len(idx)]])
                yield x[idx], y[idx]

    dev0 = jax.devices()[0]

    def commit(batch):
        # through the planner's upload seam (core/plan.train_commit):
        # classical-learner transfers share the crossing/byte counters
        # with the Trainer and the pipeline executor
        from mmlspark_tpu.core import plan as plan_lib
        return (plan_lib.train_commit(batch[0], dev0),
                plan_lib.train_commit(batch[1], dev0))

    params = params0
    loader = DeviceLoader(host_batches(), commit,
                          depth=LEARNER_PREFETCH_DEPTH, name="learner")
    t0 = time.perf_counter()
    try:
        for xb, yb in loader:
            params, opt_state, _ = step(params, opt_state, xb, yb)
    finally:
        loader.close()
    if stats_out is not None:
        stats_out.update(input_stats(loader, time.perf_counter() - t0))
    return params


class LogisticRegression(Learner):
    """Multinomial logistic regression; binary is the 2-class case.

    The reference wraps multiclass LR in OneVsRest
    (TrainClassifier.scala:109-134); a multinomial softmax head is the
    equivalent single-matmul form and maps better onto the MXU.
    """

    family = FAMILY_LINEAR
    is_classifier = True

    learning_rate = Param(default=0.05, doc="Adam learning rate", type_=float)
    epochs = Param(default=100, doc="training epochs", type_=int)
    batch_size = Param(default=8192, doc="minibatch size", type_=int)
    reg_param = Param(default=0.0, doc="L2 regularization", type_=float)
    seed = Param(default=0, doc="shuffle seed", type_=int)

    def fit_arrays(self, x, y, num_classes=None):
        import jax.numpy as jnp
        import optax

        k = int(num_classes or (int(y.max()) + 1 if len(y) else 2))
        k = max(k, 2)
        d = x.shape[1]
        params0 = {"w": jnp.zeros((d, k), jnp.float32),
                   "b": jnp.zeros((k,), jnp.float32)}

        def loss_fn(params, xb, yb):
            logits = xb @ params["w"] + params["b"]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            return ce.mean() + self.reg_param * (params["w"] ** 2).sum()

        stats: dict = {}
        params = _train_jax(loss_fn, params0,
                            x.astype(np.float32), y.astype(np.int32),
                            self.learning_rate, self.epochs, self.batch_size,
                            self.seed, stats_out=stats)
        fitted = _LinearFitted(np.asarray(params["w"]),
                               np.asarray(params["b"]), classifier=True)
        fitted.input_stats = stats
        return fitted


class LinearRegression(Learner):
    family = FAMILY_LINEAR
    is_classifier = False

    reg_param = Param(default=1e-6, doc="ridge regularization", type_=float)

    def fit_arrays(self, x, y, num_classes=None):
        # closed-form ridge: (X'X + λI)^-1 X'y, solved host-side in float64
        # — at featurized dims the normal-equations solve is cheap enough
        # that it never needs the device (and f64 beats bf16 conditioning)
        x64 = np.column_stack([x.astype(np.float64),
                               np.ones(len(x))])
        a = x64.T @ x64 + self.reg_param * np.eye(x64.shape[1])
        b = x64.T @ y.astype(np.float64)
        wb = np.linalg.solve(a, b)
        return _LinearFitted(wb[:-1][:, None], wb[-1:], classifier=False)


class _LinearFitted(FittedLearner):
    def __init__(self, w: np.ndarray, b: np.ndarray, classifier: bool):
        self.w, self.b, self.classifier = w, b, classifier

    def predict_arrays(self, x):
        z = x.astype(np.float64) @ self.w + self.b
        if not self.classifier:
            return z[:, 0], None
        z = z - z.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        return p.argmax(axis=1), p


class _MLPBase(Learner):
    layers = Param(default=None, doc="hidden layer widths",
                   type_=(list, tuple))
    learning_rate = Param(default=1e-3, doc="Adam learning rate", type_=float)
    epochs = Param(default=100, doc="training epochs", type_=int)
    batch_size = Param(default=4096, doc="minibatch size", type_=int)
    seed = Param(default=0, doc="init/shuffle seed", type_=int)

    def _init_params(self, dims: list[int]) -> dict:
        import jax.numpy as jnp
        rng = np.random.default_rng(self.seed)
        params = {}
        for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
            scale = np.sqrt(2.0 / din)
            params[f"w{i}"] = jnp.asarray(
                rng.normal(scale=scale, size=(din, dout)), jnp.float32)
            params[f"b{i}"] = jnp.zeros((dout,), jnp.float32)
        return params

    @staticmethod
    def _forward(params: dict, xb, n_layers: int):
        import jax.numpy as jnp
        h = xb
        for i in range(n_layers):
            h = h @ params[f"w{i}"] + params[f"b{i}"]
            if i < n_layers - 1:
                h = jnp.maximum(h, 0.0)
        return h


class MLPClassifier(_MLPBase):
    family = FAMILY_NN
    is_classifier = True

    def fit_arrays(self, x, y, num_classes=None):
        import optax

        k = max(int(num_classes or int(y.max()) + 1), 2)
        hidden = list(self.layers or [64])
        dims = [x.shape[1]] + hidden + [k]
        n_layers = len(dims) - 1
        params0 = self._init_params(dims)

        def loss_fn(params, xb, yb):
            logits = self._forward(params, xb, n_layers)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        stats: dict = {}
        params = _train_jax(loss_fn, params0, x.astype(np.float32),
                            y.astype(np.int32), self.learning_rate,
                            self.epochs, self.batch_size, self.seed,
                            stats_out=stats)
        fitted = _MLPFitted({k2: np.asarray(v) for k2, v in params.items()},
                            n_layers, classifier=True)
        fitted.input_stats = stats
        return fitted


class MLPRegressor(_MLPBase):
    family = FAMILY_NN
    is_classifier = False

    def fit_arrays(self, x, y, num_classes=None):
        hidden = list(self.layers or [64])
        dims = [x.shape[1]] + hidden + [1]
        n_layers = len(dims) - 1
        params0 = self._init_params(dims)

        def loss_fn(params, xb, yb):
            pred = self._forward(params, xb, n_layers)[:, 0]
            return ((pred - yb) ** 2).mean()

        stats: dict = {}
        params = _train_jax(loss_fn, params0, x.astype(np.float32),
                            y.astype(np.float32), self.learning_rate,
                            self.epochs, self.batch_size, self.seed,
                            stats_out=stats)
        fitted = _MLPFitted({k: np.asarray(v) for k, v in params.items()},
                            n_layers, classifier=False)
        fitted.input_stats = stats
        return fitted


class _MLPFitted(FittedLearner):
    def __init__(self, params: dict, n_layers: int, classifier: bool):
        self.params, self.n_layers, self.classifier = params, n_layers, classifier

    def predict_arrays(self, x):
        h = x.astype(np.float32)
        for i in range(self.n_layers):
            h = h @ self.params[f"w{i}"] + self.params[f"b{i}"]
            if i < self.n_layers - 1:
                h = np.maximum(h, 0.0)
        if not self.classifier:
            return h[:, 0].astype(np.float64), None
        z = h - h.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        return p.argmax(axis=1), p


class NaiveBayes(Learner):
    """Multinomial naive Bayes over non-negative features (closed form)."""

    family = FAMILY_LINEAR
    is_classifier = True

    smoothing = Param(default=1.0, doc="Laplace smoothing", type_=float)

    def fit_arrays(self, x, y, num_classes=None):
        k = max(int(num_classes or int(y.max()) + 1), 2)
        x = np.maximum(x.astype(np.float64), 0.0)
        d = x.shape[1]
        counts = np.zeros((k, d))
        prior = np.zeros(k)
        for c in range(k):
            mask = y == c
            prior[c] = mask.sum()
            counts[c] = x[mask].sum(axis=0)
        prior = np.log((prior + 1.0) / (prior.sum() + k))
        theta = np.log((counts + self.smoothing) /
                       (counts.sum(axis=1, keepdims=True)
                        + self.smoothing * d))
        return _NBFitted(prior, theta)


class _NBFitted(FittedLearner):
    def __init__(self, log_prior: np.ndarray, log_theta: np.ndarray):
        self.log_prior, self.log_theta = log_prior, log_theta

    def predict_arrays(self, x):
        joint = np.maximum(x.astype(np.float64), 0.0) @ self.log_theta.T \
            + self.log_prior
        z = joint - joint.max(axis=1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        return joint.argmax(axis=1), p


# ---- host-side tree learners (scikit-learn delegation) ----

def _require_sklearn():
    try:
        import sklearn  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "tree learners delegate to scikit-learn, which is not "
            "installed; use LogisticRegression/MLPClassifier or install "
            "scikit-learn") from e


class _SklearnLearner(Learner):
    family = FAMILY_TREE

    max_depth = Param(default=5, doc="maximum tree depth", type_=int)
    n_estimators = Param(default=20, doc="number of trees (forest/GBT)",
                         type_=int)
    seed = Param(default=0, doc="random seed", type_=int)

    def _make(self) -> Any:
        raise NotImplementedError

    def fit_arrays(self, x, y, num_classes=None):
        _require_sklearn()
        est = self._make()
        est.fit(x, y)
        return _SklearnFitted(est, self.is_classifier)


class _SklearnFitted(FittedLearner):
    def __init__(self, est: Any, classifier: bool):
        self.est, self.classifier = est, classifier

    def predict_arrays(self, x):
        pred = self.est.predict(x)
        proba = (self.est.predict_proba(x)
                 if self.classifier and hasattr(self.est, "predict_proba")
                 else None)
        return pred, proba


class DecisionTreeClassifier(_SklearnLearner):
    is_classifier = True

    def _make(self):
        from sklearn.tree import DecisionTreeClassifier as Impl
        return Impl(max_depth=self.max_depth, random_state=self.seed)


class DecisionTreeRegressor(_SklearnLearner):
    is_classifier = False

    def _make(self):
        from sklearn.tree import DecisionTreeRegressor as Impl
        return Impl(max_depth=self.max_depth, random_state=self.seed)


class RandomForestClassifier(_SklearnLearner):
    is_classifier = True

    def _make(self):
        from sklearn.ensemble import RandomForestClassifier as Impl
        return Impl(n_estimators=self.n_estimators, max_depth=self.max_depth,
                    random_state=self.seed)


class RandomForestRegressor(_SklearnLearner):
    is_classifier = False

    def _make(self):
        from sklearn.ensemble import RandomForestRegressor as Impl
        return Impl(n_estimators=self.n_estimators, max_depth=self.max_depth,
                    random_state=self.seed)


class GBTClassifier(_SklearnLearner):
    is_classifier = True

    def _make(self):
        from sklearn.ensemble import GradientBoostingClassifier as Impl
        return Impl(n_estimators=self.n_estimators, max_depth=self.max_depth,
                    random_state=self.seed)


class GBTRegressor(_SklearnLearner):
    is_classifier = False

    def _make(self):
        from sklearn.ensemble import GradientBoostingRegressor as Impl
        return Impl(n_estimators=self.n_estimators, max_depth=self.max_depth,
                    random_state=self.seed)

"""TrainClassifier — one-call classification over a mixed-type table.

Analog of the reference's ``src/train-classifier/`` (reference:
TrainClassifier.scala:97-348): label reindexing via ValueIndexer
(``convertLabel``, :203-249), automatic featurization with a hash-size /
one-hot heuristic per learner family (``getFeaturizeParams``, :186-201),
learner fit, and a fitted model whose transform stamps the score-column
metadata protocol (scores / scored_labels / scored_probabilities,
:297-348) that ComputeModelStatistics consumes.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import (
    SchemaConstants, find_unused_column_name, set_categorical_levels,
    set_label_column, set_score_column,
)
from mmlspark_tpu.core.stage import Estimator, HasLabelCol, Transformer
from mmlspark_tpu.data.table import DataTable, is_missing
from mmlspark_tpu.ml.learners import (
    FAMILY_LINEAR, FAMILY_NN, FAMILY_TREE, Learner, LogisticRegression,
)
from mmlspark_tpu.stages.featurize import (
    Featurize, NUM_FEATURES_DEFAULT, NUM_FEATURES_TREE_OR_NN,
)
from mmlspark_tpu.stages.indexers import index_values, sorted_levels

_log = get_logger(__name__)


def featurize_params_for(learner: Learner) -> tuple[int, bool]:
    """(hash slots, one-hot?) per learner family
    (reference: TrainClassifier.scala:186-201)."""
    if learner.family in (FAMILY_TREE, FAMILY_NN):
        return NUM_FEATURES_TREE_OR_NN, learner.family != FAMILY_TREE
    return NUM_FEATURES_DEFAULT, True


def featurize_and_extract(table: DataTable, label_col: str, y: np.ndarray,
                          feature_columns: Any, n_feats: int, one_hot: bool
                          ) -> tuple[Any, str, np.ndarray, np.ndarray]:
    """Shared Train* wiring: fit Featurize on the non-label columns, thread
    the label through the row-dropping transform, return
    (featurize_model, features_col, x, y)."""
    feat_cols = list(feature_columns or
                     [c for c in table.columns if c != label_col])
    features_col = find_unused_column_name(table, "features")
    feat_model = Featurize(
        feature_columns={features_col: feat_cols},
        number_of_features=n_feats,
        one_hot_encode_categoricals=one_hot,
        allow_images=True).fit(table)
    # temp label column must not collide with a real feature column
    label_tmp = find_unused_column_name(table, "__label")
    feat = feat_model.transform(table.with_column(label_tmp, y))
    x = feat.column_matrix(features_col)
    return feat_model, features_col, x, np.asarray(feat[label_tmp])


def drop_missing_labels(table: DataTable, label_col: str) -> DataTable:
    col = table[label_col]
    if col.dtype == object:
        mask = np.fromiter((not is_missing(v) for v in col), dtype=bool,
                           count=len(col))
    elif np.issubdtype(col.dtype, np.floating):
        mask = ~np.isnan(col)
    else:
        return table
    return table if mask.all() else table.take(mask)


class TrainClassifier(Estimator, HasLabelCol):
    """One-call classification: label indexing + automatic featurization +
    learner fit, yielding a model that stamps score metadata.

    Reference: train-classifier/src/main/scala/TrainClassifier.scala:97-184
    (hash-size-by-learner-family heuristic at :186-201)."""

    model = Param(default=None, doc="the learner to fit (default "
                  "LogisticRegression)", is_complex=True)
    feature_columns = Param(default=None, doc="input columns to featurize "
                            "(default: all but the label)",
                            type_=(list, tuple))
    number_of_features = Param(default=None, doc="hash-slot override",
                               type_=int)

    def fit(self, table: DataTable) -> "TrainedClassifierModel":
        learner: Learner = self.model or LogisticRegression()
        if not learner.is_classifier:
            raise ValueError(f"{type(learner).__name__} is not a classifier")
        table = drop_missing_labels(table, self.label_col)

        # label → contiguous codes, levels kept for inverse mapping
        levels = sorted_levels(table[self.label_col])
        codes = index_values(table[self.label_col], levels)

        n_feats, one_hot = featurize_params_for(learner)
        if self.number_of_features:
            n_feats = self.number_of_features
        feat_model, features_col, x, y = featurize_and_extract(
            table, self.label_col, codes, self.feature_columns, n_feats,
            one_hot)
        y = y.astype(np.int64)

        fitted = learner.fit_arrays(x, y, num_classes=len(levels))
        # input-pipeline honesty: was the fit compute- or input-bound?
        # (jax learners train through the prefetching DeviceLoader —
        # train/input.py; closed-form/host learners report nothing)
        stats = getattr(fitted, "input_stats", None)
        if stats:
            _log.debug("TrainClassifier[%s]: input_bound_fraction=%s "
                       "(wait %ss / step %ss, %s batches)",
                       type(learner).__name__,
                       stats.get("input_bound_fraction"),
                       stats.get("input_wait_s"), stats.get("step_s"),
                       stats.get("batches"))
        return TrainedClassifierModel(
            label_col=self.label_col, features_col=features_col,
            featurize_model=feat_model, fitted_learner=fitted,
            label_levels=list(levels))

    def infer_schema(self, schema: Any) -> Any:
        return _train_infer_schema(self, schema, classification=True)

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        return _train_infer_rows(self, n, schema)


def _score_column_infos(uid: str, kind: str, num_classes: int | None,
                        label_info: Any, classification: bool) -> dict:
    """The abstract score columns a Trained*Model.transform writes, with
    the metadata protocol stamped (what the evaluators will read)."""
    from mmlspark_tpu.analysis.info import ColumnInfo

    def stamp(info: Any, purpose: str) -> Any:
        info.meta[SchemaConstants.K_COLUMN_PURPOSE] = purpose
        info.meta[SchemaConstants.K_MODEL_UID] = uid
        info.meta[SchemaConstants.K_SCORE_VALUE_KIND] = kind
        return info

    if not classification:
        return {SchemaConstants.SCORES_COLUMN: stamp(
            ColumnInfo.scalar("float64"), SchemaConstants.SCORES_COLUMN)}
    labels = (label_info.copy() if label_info is not None
              else ColumnInfo.unknown())
    labels.has_missing = True  # out-of-range codes emit None
    return {
        SchemaConstants.SCORES_COLUMN: stamp(
            ColumnInfo.vector(num_classes, "float64"),
            SchemaConstants.SCORES_COLUMN),
        SchemaConstants.SCORED_LABELS_COLUMN: stamp(
            labels, SchemaConstants.SCORED_LABELS_COLUMN),
        SchemaConstants.SCORED_PROBABILITIES_COLUMN: stamp(
            ColumnInfo.vector(num_classes, "float64"),
            SchemaConstants.SCORED_PROBABILITIES_COLUMN),
    }


def _train_infer_schema(est: Any, schema: Any, classification: bool) -> Any:
    """Shared TrainClassifier/TrainRegressor estimator inference: label
    and feature columns must exist; the fitted model will add the stamped
    score columns (widths are fit-time artifacts)."""
    from mmlspark_tpu.analysis.info import SchemaError
    out = schema.copy()
    if est.label_col not in out.columns and schema.exact:
        raise SchemaError(
            "missing-input-column",
            f"{type(est).__name__} trains on missing label column "
            f"{est.label_col!r}; available: {list(schema)}")
    missing = [c for c in (est.feature_columns or [])
               if c not in out.columns]
    if missing and schema.exact:
        raise SchemaError(
            "missing-input-column",
            f"{type(est).__name__} featurizes missing column(s) "
            f"{missing}; available: {list(schema)}")
    kind = (SchemaConstants.CLASSIFICATION_KIND if classification
            else SchemaConstants.REGRESSION_KIND)
    out.columns.update(_score_column_infos(
        est.uid, kind, None, out.get(est.label_col), classification))
    return out


def _train_infer_rows(est: Any, n: int | None, schema: Any) -> int | None:
    """Train* fitting drops rows with missing labels and the featurization
    na.drop may remove more — the count is unknowable when any consumed
    column can hold missing values."""
    if n is None:
        return None
    cols = list(est.feature_columns
                or [c for c in schema.columns if c != est.label_col])
    cols.append(est.label_col)
    for c in cols:
        ci = schema.get(c)
        if ci is not None and ci.has_missing:
            return None
    return n


class TrainedClassifierModel(Transformer, HasLabelCol):
    """Fitted :class:`TrainClassifier`: featurizes, scores, and stamps
    scores/scored-labels/probabilities column metadata for the evaluators
    (reference: TrainClassifier.scala:280-381)."""

    features_col = Param(default="features", doc="assembled features column",
                         type_=str)
    featurize_model = Param(default=None, doc="fitted featurization pipeline",
                            is_complex=True)
    fitted_learner = Param(default=None, doc="fitted learner",
                           is_complex=True)
    label_levels = Param(default=None, doc="label level values (code order)",
                         is_complex=True)

    def transform(self, table: DataTable) -> DataTable:
        out = self.featurize_model.transform(table)
        x = out.column_matrix(self.features_col)
        pred_codes, proba = self.fitted_learner.predict_arrays(x)
        levels = list(self.label_levels)
        pred_codes = np.asarray(pred_codes, dtype=np.int64)
        scored_labels = [levels[c] if 0 <= c < len(levels) else None
                         for c in pred_codes]

        scores_col = SchemaConstants.SCORES_COLUMN
        labels_col = SchemaConstants.SCORED_LABELS_COLUMN
        probs_col = SchemaConstants.SCORED_PROBABILITIES_COLUMN
        if proba is None:  # learners without probabilities score one-hot
            k = max(len(levels), int(pred_codes.max(initial=0)) + 1)
            proba = np.zeros((len(pred_codes), k))
            proba[np.arange(len(pred_codes)), pred_codes] = 1.0

        out = out.drop(self.features_col)
        out = out.with_column(scores_col, proba.astype(np.float64))
        out = out.with_column(labels_col, scored_labels)
        out = out.with_column(probs_col, proba.astype(np.float64))

        kind = SchemaConstants.CLASSIFICATION_KIND
        out = set_score_column(out, self.uid, scores_col,
                               SchemaConstants.SCORES_COLUMN, kind)
        out = set_score_column(out, self.uid, labels_col,
                               SchemaConstants.SCORED_LABELS_COLUMN, kind)
        out = set_score_column(out, self.uid, probs_col,
                               SchemaConstants.SCORED_PROBABILITIES_COLUMN,
                               kind)
        out = set_categorical_levels(out, labels_col, levels)
        if self.label_col in out:
            out = set_label_column(out, self.uid, self.label_col, kind)
        return out

    def infer_schema(self, schema: Any) -> Any:
        out = self.featurize_model.infer_schema(schema)
        out = out.drop(self.features_col)
        levels = list(self.label_levels or [])
        label_info = schema.get(self.label_col)
        infos = _score_column_infos(
            self.uid, SchemaConstants.CLASSIFICATION_KIND,
            len(levels) or None, label_info, classification=True)
        labels_col = SchemaConstants.SCORED_LABELS_COLUMN
        infos[labels_col].meta[SchemaConstants.K_IS_CATEGORICAL] = True
        infos[labels_col].meta[
            SchemaConstants.K_CATEGORICAL_LEVELS] = levels
        out.columns.update(infos)
        if self.label_col in out.columns:
            li = out.columns[self.label_col]
            li.meta[SchemaConstants.K_COLUMN_PURPOSE] = \
                SchemaConstants.LABEL_COLUMN
            li.meta[SchemaConstants.K_MODEL_UID] = self.uid
            li.meta[SchemaConstants.K_SCORE_VALUE_KIND] = \
                SchemaConstants.CLASSIFICATION_KIND
        return out

    def infer_rows(self, n: int | None, schema: Any) -> int | None:
        # scoring re-runs the featurization, whose na.drop analog may
        # remove rows — delegate to the fitted featurize pipeline
        if n is None:
            return None
        return self.featurize_model.infer_rows(n, schema)

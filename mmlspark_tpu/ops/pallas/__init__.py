"""Hand-written Pallas kernels — the repo's kernel library.

XLA schedules most device compute well (elementwise chains fuse into
the surrounding program for free), so kernels exist only where the
default lowering measurably loses to a VMEM-resident formulation:

* :mod:`~mmlspark_tpu.ops.pallas.resize` — the train-input gather path
  (crop + bilinear resize + normalize), which XLA lowers as four
  batched gathers plus three f32 blend passes through HBM;
* :mod:`~mmlspark_tpu.ops.pallas.attention` — flash-style fused
  attention (online-softmax tiling): the serving-path attention of
  ``models/vit.py`` and the local block of
  ``parallel/ring_attention.py``, replacing three HBM materializations
  of the ``[B, H, Tq, Tk]`` score matrix.

Every kernel keeps the PR 10 discipline: ONE shared body = Pallas
kernel = XLA reference = numpy oracle, the kernel ULP-pinned against
the reference UNDER JIT, ``interpret=True`` off-TPU so CPU tier-1
executes the kernel body itself, and an ``impl: auto|xla|pallas`` flag
with a VMEM-budget fallback to the reference.
"""

from mmlspark_tpu.ops.pallas.attention import (
    attention_block_update, flash_attention, flash_attention_host,
    flash_attention_reference,
)
from mmlspark_tpu.ops.pallas.resize import (
    fused_resize_norm, fused_resize_norm_host, fused_resize_norm_reference,
)

__all__ = [
    "attention_block_update", "flash_attention", "flash_attention_host",
    "flash_attention_reference", "fused_resize_norm",
    "fused_resize_norm_host", "fused_resize_norm_reference",
]

"""Hand-written Pallas kernels for the train-input hot path.

XLA schedules most of the device preprocessing chain well (elementwise
augment ops fuse into the surrounding step program for free), but the
fused gather path — crop + bilinear resize + normalize — lowers as four
separate batched gathers plus three blend passes over f32 intermediates,
each a round-trip through HBM. The kernels here do that chain in one
VMEM-resident pass per sample. Every kernel ships with a pure-XLA
reference implementation pinned ≤ 1 ULP equal (tests/test_train_preprocess
and the tier-1 ``check_train_device_preprocess`` gate), and runs in
interpreter mode on non-TPU backends so CPU tests execute the kernel
itself, not a shadow path.
"""

from mmlspark_tpu.ops.pallas.resize import (
    fused_resize_norm, fused_resize_norm_host, fused_resize_norm_reference,
)

__all__ = [
    "fused_resize_norm", "fused_resize_norm_host",
    "fused_resize_norm_reference",
]

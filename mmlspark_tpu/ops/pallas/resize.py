"""Fused per-sample crop → bilinear resize → normalize, as one Pallas pass.

The hot gather path of on-device train preprocessing
(:mod:`mmlspark_tpu.train.preprocess`): each sample takes a (possibly
random) fixed-size crop window out of the source-resolution uint8 image,
bilinearly resizes the window to the training resolution, and scales the
result into normalized float32 — the geometry the thin-wire ingest mode
replays on device instead of paying for it on a host thread pool.

Under plain XLA the chain lowers as four batched gathers (the corner
taps) with three f32 blend passes between them, each materializing an
``[N, OH, OW, C]`` intermediate in HBM. The kernel reads each sample's
source block into VMEM once (grid over samples — one output tile per
program) and does the window slice, the four static-index taps, the
blend, and the normalize scale there: one HBM read of uint8 source + one
HBM write of f32 output per element.

Three implementations share ONE coordinate/weight grid
(:func:`_grids`, precomputed in numpy float32 at trace time), so they can
be pinned against each other exactly:

* :func:`fused_resize_norm_reference` — pure XLA (``vmap`` over samples),
  the semantics anchor;
* the Pallas kernel — ≤ 1 ULP equal to the reference
  (``np.testing.assert_array_max_ulp``), asserted on the CPU backend in
  interpreter mode so the kernel body itself executes in tier-1;
* :func:`fused_resize_norm_host` — the numpy oracle host baselines and
  property tests compare against: ≤ 2 ULP from the device paths (XLA
  contracts the four-tap blend into FMAs, numpy cannot — one extra
  rounding per tap), far inside the 1e-5 end-to-end loss tolerance.

Coordinate math matches the repo's bilinear convention
(``stages/image._device_resize_step`` / native ``img_resize_bilinear``):
align-corners f32 source coordinates, left-associated blend — except the
output stays float32 (training consumes normalized floats; the inference
path's final uint8 quantization step does not apply).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp


def _grids(ch: int, cw: int, oh: int, ow: int) -> tuple:
    """Static gather indices + blend weights for a (ch, cw) → (oh, ow)
    align-corners bilinear resize. All float math in numpy float32 so the
    XLA reference, the Pallas kernel, and the numpy oracle consume
    bit-identical constants."""
    sy = (np.float32(ch - 1) / np.float32(oh - 1)) if oh > 1 else np.float32(0)
    sx = (np.float32(cw - 1) / np.float32(ow - 1)) if ow > 1 else np.float32(0)
    fy = np.arange(oh, dtype=np.float32) * sy
    fx = np.arange(ow, dtype=np.float32) * sx
    y0 = fy.astype(np.int32)
    x0 = fx.astype(np.int32)
    y1 = np.minimum(y0 + 1, ch - 1)
    x1 = np.minimum(x0 + 1, cw - 1)
    # subtract in f32 (int32 operands would promote the whole weight
    # chain to f64, and the numpy oracle would then blend in f64 while
    # the device paths blend in canonicalized f32)
    wy = (fy - y0.astype(np.float32)).reshape(oh, 1, 1)
    wx = (fx - x0.astype(np.float32)).reshape(1, ow, 1)
    one = np.float32(1)
    # the four corner weights, precomputed: v = Σ v_ij * w_ij is then a
    # single multiply-add sequence identical across implementations
    w00 = (one - wy) * (one - wx)
    w01 = (one - wy) * wx
    w10 = wy * (one - wx)
    w11 = wy * wx
    return y0, y1, x0, x1, w00, w01, w10, w11


def _blend(win, g, scale: np.float32):
    """The shared tap/blend/normalize body over one (ch, cw, C) window.
    jnp and numpy expose identical take/astype/arithmetic surface, so the
    SAME code is the kernel body, the XLA reference, and the numpy oracle
    — implementations cannot drift apart op by op."""
    xp = jnp if isinstance(win, jnp.ndarray) else np
    y0, y1, x0, x1, w00, w01, w10, w11 = g
    rows0 = xp.take(win, y0, axis=0)
    rows1 = xp.take(win, y1, axis=0)
    v00 = xp.take(rows0, x0, axis=1).astype(np.float32)
    v01 = xp.take(rows0, x1, axis=1).astype(np.float32)
    v10 = xp.take(rows1, x0, axis=1).astype(np.float32)
    v11 = xp.take(rows1, x1, axis=1).astype(np.float32)
    v = v00 * w00 + v01 * w01 + v10 * w10 + v11 * w11
    return v * scale


def fused_resize_norm_reference(x, oy, ox, crop: tuple, out_hw: tuple,
                                scale: float) -> jnp.ndarray:
    """Pure-XLA fused path: per-sample window slice + bilinear taps +
    normalize, vmapped over the batch. The semantics anchor the Pallas
    kernel is pinned against."""
    ch, cw = int(crop[0]), int(crop[1])
    c = x.shape[-1]
    g = _grids(ch, cw, int(out_hw[0]), int(out_hw[1]))
    s = np.float32(scale)

    def one(img, y, xo):
        win = jax.lax.dynamic_slice(img, (y, xo, 0), (ch, cw, c))
        return _blend(win, g, s)

    return jax.vmap(one)(x, oy.astype(jnp.int32), ox.astype(jnp.int32))


def fused_resize_norm_host(x, oy, ox, crop: tuple, out_hw: tuple,
                           scale: float) -> np.ndarray:
    """Numpy oracle: the identical tap/blend/normalize sequence on host.
    Also the "host-preprocess" baseline wire format of the thin-wire A/B
    (``train/preprocess.host_preprocess``)."""
    x = np.asarray(x)
    ch, cw = int(crop[0]), int(crop[1])
    oh, ow = int(out_hw[0]), int(out_hw[1])
    g = _grids(ch, cw, oh, ow)
    s = np.float32(scale)
    oy = np.asarray(oy, np.int64)
    ox = np.asarray(ox, np.int64)
    out = np.empty((len(x), oh, ow, x.shape[-1]), np.float32)
    for i in range(len(x)):
        win = x[i, oy[i]:oy[i] + ch, ox[i]:ox[i] + cw]
        out[i] = _blend(win, g, s)
    return out


def _kernel(x_ref, oy_ref, ox_ref, yidx_ref, xidx_ref, w_ref, o_ref, *,
            crop: tuple, scale: np.float32):
    # the grid arrays arrive as kernel INPUTS (this jax's pallas rejects
    # closure-captured array constants), packed [2, OH] / [2, OW] /
    # [4, OH, OW] — same numpy values every implementation consumes
    ch, cw = crop
    c = x_ref.shape[-1]
    win = jax.lax.dynamic_slice(
        x_ref[0], (oy_ref[0, 0], ox_ref[0, 0], 0), (ch, cw, c))
    g = (yidx_ref[0], yidx_ref[1], xidx_ref[0], xidx_ref[1],
         w_ref[0][..., None], w_ref[1][..., None],
         w_ref[2][..., None], w_ref[3][..., None])
    o_ref[0] = _blend(win, g, scale)


def _fits_vmem(h: int, w: int, oh: int, ow: int, c: int) -> bool:
    """Conservative per-sample VMEM estimate: the uint8 source block, the
    sliced window, four f32 corner taps + the f32 blend/output, lane dim
    padded to 128. Blocks past the ~16 MB budget fall back to the XLA
    reference (same math, more HBM traffic)."""
    c_pad = -(-c // 128) * 128
    est = h * w * c_pad * 2 + 6 * oh * ow * c_pad * 4
    return est < 14 * 2 ** 20


def _pallas_call(x, oy, ox, crop: tuple, out_hw: tuple, scale: float):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, w, c = x.shape
    ch, cw = int(crop[0]), int(crop[1])
    oh, ow = int(out_hw[0]), int(out_hw[1])
    y0, y1, x0, x1, w00, w01, w10, w11 = _grids(ch, cw, oh, ow)
    yidx = np.stack([y0, y1])                      # [2, OH] int32
    xidx = np.stack([x0, x1])                      # [2, OW] int32
    wts = np.stack([w00, w01, w10, w11])[..., 0]   # [4, OH, OW] f32
    kern = functools.partial(_kernel, crop=(ch, cw),
                             scale=np.float32(scale))
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((2, oh), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, ow), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, oh, ow), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, oh, ow, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, c), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(x, oy.astype(jnp.int32).reshape(n, 1),
      ox.astype(jnp.int32).reshape(n, 1), yidx, xidx, wts)


IMPLS = ("auto", "xla", "pallas")


def fused_resize_norm(x, oy, ox, crop: tuple, out_hw: tuple, scale: float,
                      impl: str = "auto") -> jnp.ndarray:
    """Fused crop → bilinear resize → normalize over an ``[N, H, W, C]``
    batch: sample ``i`` takes the ``crop``-sized window at ``(oy[i],
    ox[i])``, resizes it to ``out_hw``, and returns float32 ``* scale``.

    ``impl`` selects the backend ("the TrainConfig flag" — threaded from
    ``DevicePreprocess.impl``): ``"xla"`` forces the reference,
    ``"pallas"`` forces the kernel (interpreter mode off-TPU — the CPU
    fallback executes the kernel body, not a shadow path), and ``"auto"``
    uses the kernel on the TPU backend and the reference elsewhere.
    Windows too large for the per-sample VMEM budget always take the
    reference — identical math, different schedule.
    """
    if impl not in IMPLS:
        raise ValueError(f"unknown fused_resize_norm impl {impl!r}; "
                         f"one of {IMPLS}")
    n, h, w, c = x.shape
    ch, cw = int(crop[0]), int(crop[1])
    if ch > h or cw > w:
        raise ValueError(f"crop window ({ch}, {cw}) larger than the "
                         f"source image ({h}, {w})")
    use_pallas = impl == "pallas" or (
        impl == "auto" and jax.default_backend() == "tpu")
    if use_pallas and _fits_vmem(h, w, int(out_hw[0]), int(out_hw[1]), c):
        return _pallas_call(x, oy, ox, crop, out_hw, scale)
    return fused_resize_norm_reference(x, oy, ox, crop, out_hw, scale)

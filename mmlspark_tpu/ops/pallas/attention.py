"""Fused flash-style attention (online-softmax tiling) as one Pallas pass.

The serving-path attention of :mod:`mmlspark_tpu.models.vit` and the
local block of :mod:`mmlspark_tpu.parallel.ring_attention`. Under plain
XLA, attention materializes the ``[B, H, Tq, Tk]`` score matrix in HBM
three times over (scores → masked scores → softmax weights) before the
weighted sum; the kernel keeps one (batch, head) tile's Q/K/V blocks in
VMEM and accumulates the softmax online (running max + denominator, Dao
et al.'s FlashAttention recurrence — the same recurrence
``ring_attention`` already runs across ring hops, here applied across
K blocks inside one chip), so the score matrix never touches HBM.

The PR 10 kernel discipline (``ops/pallas/resize.py``):

* ONE shared body — :func:`_online_update` (a single K/V block's
  online-softmax update over 2-D ``[T, D]`` tiles) and
  :func:`_flash_tile` (the block loop) are written over the ``xp``
  namespace, so the SAME code is the Pallas kernel body, the XLA
  reference (``vmap`` over batch × heads), and the numpy oracle —
  implementations cannot drift apart op by op;
* the kernel is pinned ≤ 1 ULP against :func:`flash_attention_reference`
  UNDER JIT (eager comparisons drift via FMA contraction — repo
  convention), and the numpy oracle is pinned against the jitted
  reference (tests/test_attention.py);
* ``interpret=True`` off-TPU, so CPU tier-1 executes the kernel body
  itself, not a shadow path;
* ``impl: auto | xla | pallas`` selects the backend (auto = kernel on
  TPU, reference elsewhere), and tiles past the VMEM budget fall back
  to the reference — identical math, different schedule.

Masking semantics match ``parallel/ring_attention``: ``kv_mask`` is a
``[B, Tk]`` key-validity mask (True = real key), ``causal`` adds the
lower-triangular constraint, and fully-masked query rows yield EXACT
zeros (the guarded accumulator), not NaN. The mask ships as one
``[B, Tq, Tk]`` int8 tensor consumed identically by all three
implementations.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

IMPLS = ("auto", "xla", "pallas")

# K-block width of the online-softmax loop: one MXU-lane-aligned stripe
# of the score tile per update
DEFAULT_BLOCK_K = 128

# the denominator guard for fully-masked query rows (exactly the
# ring/ulysses value, so the paths agree bit-for-bit on masked rows)
_DENOM_FLOOR = np.float32(1e-30)


def _online_update(q, ks, vs, keep, m, denom, acc, scale, xp):
    """THE shared body: one K/V block's flash-attention update for one
    (batch, head) tile.

    ``q`` ``[Tq, D]`` f32, ``ks``/``vs`` ``[Tk, D]`` f32, ``keep``
    ``[Tq, Tk]`` bool, carry ``m``/``denom`` ``[Tq, 1]`` f32 and ``acc``
    ``[Tq, D]`` f32. Returns the updated ``(m, denom, acc)``. Also the
    per-hop local-block update of ``ring_attention`` (each ring step IS
    one such update with the resident K/V block)."""
    scores = xp.dot(q, ks.T) * scale
    scores = xp.where(keep, scores, -xp.inf)
    blk_max = xp.max(scores, axis=-1, keepdims=True)
    m_new = xp.maximum(m, blk_max)
    # guard -inf - -inf (rows with every key masked so far)
    corr = xp.where(xp.isfinite(m), xp.exp(m - m_new), np.float32(0))
    p = xp.exp(xp.where(xp.isfinite(scores), scores - m_new, -xp.inf))
    acc = acc * corr + xp.dot(p, vs)
    denom = denom * corr + xp.sum(p, axis=-1, keepdims=True)
    return m_new, denom, acc


def _flash_tile(q, k, v, keep, scale, xp, block_k: int):
    """Full attention for one (batch, head) tile via the online-softmax
    block loop: ``q`` ``[Tq, D]``, ``k``/``v`` ``[Tk, D]``, ``keep``
    ``[Tq, Tk]`` bool → ``[Tq, D]`` f32. The block loop is a static
    python loop (``Tk``/``block_k`` are trace-time constants), so the
    SAME code unrolls identically in the kernel, the XLA reference, and
    the numpy oracle."""
    tq, d = q.shape
    tk = k.shape[0]
    m = xp.full((tq, 1), -xp.inf, np.float32)
    denom = xp.zeros((tq, 1), np.float32)
    acc = xp.zeros((tq, d), np.float32)
    for start in range(0, tk, block_k):
        stop = min(start + block_k, tk)
        m, denom, acc = _online_update(
            q, k[start:stop], v[start:stop], keep[:, start:stop],
            m, denom, acc, scale, xp)
    return acc / xp.maximum(denom, _DENOM_FLOOR)


def _mask3(b: int, tq: int, tk: int, kv_mask, causal: bool):
    """The one ``[B, Tq, Tk]`` int8 mask every implementation consumes
    (True→1 = attend). Built with jnp (traced); callers on the host
    oracle path convert with numpy themselves via :func:`host_mask3`."""
    if kv_mask is None:
        keep = jnp.ones((b, tq, tk), bool)
    else:
        keep = jnp.broadcast_to(jnp.asarray(kv_mask, bool)[:, None, :],
                                (b, tq, tk))
    if causal:
        keep = keep & jnp.tril(jnp.ones((tq, tk), bool))[None]
    return keep.astype(jnp.int8)


def host_mask3(b: int, tq: int, tk: int, kv_mask, causal: bool
               ) -> np.ndarray:
    """Numpy twin of :func:`_mask3` for the oracle path."""
    if kv_mask is None:
        keep = np.ones((b, tq, tk), bool)
    else:
        keep = np.broadcast_to(np.asarray(kv_mask, bool)[:, None, :],
                               (b, tq, tk)).copy()
    if causal:
        keep = keep & np.tril(np.ones((tq, tk), bool))[None]
    return keep.astype(np.int8)


def _resolve_scale(scale, d: int) -> np.float32:
    """The f32 softmax scale — np.float32 so all implementations
    multiply by the bit-identical constant."""
    return np.float32(1.0 / np.sqrt(d) if scale is None else scale)


def flash_attention_reference(q, k, v, mask3, scale,
                              block_k: int = DEFAULT_BLOCK_K):
    """Pure-XLA anchor: the SAME ``_flash_tile`` body vmapped over
    (batch, heads). ``q``/``k``/``v`` ``[B, H, T, D]`` (any float
    dtype — upcast to f32 like the ring path), ``mask3`` ``[B, Tq, Tk]``
    int8. Returns ``[B, H, Tq, D]`` float32."""
    s = np.float32(scale)

    def tile(q2, k2, v2, keep2):
        return _flash_tile(q2.astype(jnp.float32),
                           k2.astype(jnp.float32),
                           v2.astype(jnp.float32),
                           keep2 != 0, s, jnp, block_k)

    over_h = jax.vmap(tile, in_axes=(0, 0, 0, None))
    return jax.vmap(over_h)(q, k, v, mask3)


def flash_attention_host(q, k, v, mask3, scale,
                         block_k: int = DEFAULT_BLOCK_K) -> np.ndarray:
    """Numpy oracle: the identical tile body, python-looped over
    (batch, heads)."""
    q = np.asarray(q, np.float32)
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    mask3 = np.asarray(mask3)
    s = np.float32(scale)
    b, h, tq, d = q.shape
    out = np.empty((b, h, tq, d), np.float32)
    for bi in range(b):
        keep = mask3[bi] != 0
        for hi in range(h):
            out[bi, hi] = _flash_tile(q[bi, hi], k[bi, hi], v[bi, hi],
                                      keep, s, np, block_k)
    return out


# ---- the Pallas kernels ----

def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *,
                  scale: np.float32, block_k: int):
    # one (batch, head) tile per program: refs arrive [1, 1, T, D] /
    # [1, Tq, Tk]; squeeze to the 2-D tiles the shared body works on
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    keep = mask_ref[0] != 0
    o_ref[0, 0] = _flash_tile(q, k, v, keep, scale, jnp, block_k)


def _flash_call(q, k, v, mask3, scale, block_k: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    kern = functools.partial(_flash_kernel, scale=np.float32(scale),
                             block_k=block_k)
    return pl.pallas_call(
        kern,
        grid=(b, h),
        in_specs=[
            pl.BlockSpec((1, 1, tq, d), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, d), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, d), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq, tk), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, tq, d), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(q, k, v, mask3)


def _fits_vmem(tq: int, tk: int, d: int, block_k: int) -> bool:
    """Conservative per-(batch, head) VMEM bound: f32 Q/K/V tiles and
    accumulator (lane dim padded to 128), the int8 mask, and two f32
    score stripes of ``block_k``. Past the ~16 MB budget the wrapper
    falls back to the XLA reference — identical math."""
    d_pad = -(-d // 128) * 128
    bk = -(-min(block_k, tk) // 128) * 128
    est = 4 * (2 * tk * d_pad + 2 * tq * d_pad) \
        + tq * (-(-tk // 128) * 128) + 4 * 2 * tq * bk
    return est < 14 * 2 ** 20


def resolve_impl(impl: str) -> str:
    """``auto`` → the kernel on the TPU backend, the XLA reference
    elsewhere (tier-1 exercises the kernel explicitly via
    ``impl="pallas"``, which runs it in interpreter mode off-TPU)."""
    if impl not in IMPLS:
        raise ValueError(
            f"unknown attention impl {impl!r}; one of {IMPLS}")
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return impl


def flash_attention(q, k, v, kv_mask=None, causal: bool = False,
                    scale=None, impl: str = "auto",
                    block_k: int = DEFAULT_BLOCK_K):
    """Fused attention over ``[B, H, T, D]`` operands (bhtd layout —
    what :class:`~mmlspark_tpu.models.vit.BhtdSelfAttention` computes
    in). ``kv_mask``: ``[B, Tk]`` bool key-validity mask (True = real
    key); ``causal`` adds the triangular constraint. Returns
    ``[B, H, Tq, D]`` float32 (callers cast back to their compute
    dtype); fully-masked query rows are exact zeros."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    s = _resolve_scale(scale, d)
    mask3 = _mask3(b, tq, tk, kv_mask, causal)
    if resolve_impl(impl) == "pallas" and _fits_vmem(tq, tk, d, block_k):
        return _flash_call(q, k, v, mask3, s, block_k)
    return flash_attention_reference(q, k, v, mask3, s, block_k)


# ---- the KV-cache decode variant (q_len=1 against cached K/V) ----
#
# Autoregressive serving (serve/generate.py) holds a slot-major KV-cache
# [slots, H, T_max, D] as plan-managed device state and issues ONE query
# row per slot per token step. The decode attention is the same online-
# softmax recurrence restricted to Tq=1 — ONE shared body
# (`_decode_tile`) that is the Pallas kernel, the XLA reference, and the
# numpy oracle — with the slot's validity mask ([S, T] — True up to the
# slot's current length) standing in for the causal constraint (the
# cache never holds a future position). A fully-masked slot (inactive,
# length 0) yields EXACT zeros via the shared denominator floor, which
# is what lets inactive slots ride the fixed-shape decode program
# without polluting anything.


def decode_mask2(s: int, tk: int, kv_mask):
    """The one ``[S, Tk]`` int8 validity mask the decode implementations
    consume (True→1 = attend). Traced (jnp); the host oracle converts
    with :func:`host_decode_mask2`."""
    if kv_mask is None:
        return jnp.ones((s, tk), jnp.int8)
    return jnp.asarray(kv_mask, bool).astype(jnp.int8)


def host_decode_mask2(s: int, tk: int, kv_mask) -> np.ndarray:
    """Numpy twin of :func:`decode_mask2` for the oracle path."""
    if kv_mask is None:
        return np.ones((s, tk), np.int8)
    return np.asarray(kv_mask, bool).astype(np.int8)


def _decode_tile(q, k, v, keep, scale, xp, block_k: int):
    """THE shared decode body: attention of one query row against one
    (slot, head) cache tile via the online-softmax block loop. ``q``
    ``[1, D]`` f32, ``k``/``v`` ``[Tk, D]`` f32, ``keep`` ``[1, Tk]``
    bool → ``[1, D]`` f32.

    Same recurrence as :func:`_flash_tile`, with the two ``Tq=1``
    contractions written as broadcast-multiply + axis reductions instead
    of ``xp.dot``: a ``dot_general`` with an M=1 operand reassociates
    under vmap batching (the reference) vs. the standalone lowering (the
    kernel tile), drifting tens of ULPs — the reduce form lowers
    bit-identically both ways, which is what lets the ≤ 1 ULP pin hold
    for the decode variant too."""
    tk = k.shape[0]
    m = xp.full((1, 1), -xp.inf, np.float32)
    denom = xp.zeros((1, 1), np.float32)
    acc = xp.zeros((1, k.shape[1]), np.float32)
    for start in range(0, tk, block_k):
        stop = min(start + block_k, tk)
        ks, vs, kp = k[start:stop], v[start:stop], keep[:, start:stop]
        # [1, bk] scores: sum over D of q ⊙ ks (the vmap-stable form)
        scores = xp.sum(q[:, None, :] * ks[None, :, :], axis=-1) * scale
        scores = xp.where(kp, scores, -xp.inf)
        blk_max = xp.max(scores, axis=-1, keepdims=True)
        m_new = xp.maximum(m, blk_max)
        corr = xp.where(xp.isfinite(m), xp.exp(m - m_new), np.float32(0))
        p = xp.exp(xp.where(xp.isfinite(scores), scores - m_new,
                            -xp.inf))
        # [1, D] weighted values: sum over the block of p ⊙ vs
        acc = acc * corr + xp.sum(p[0][:, None] * vs, axis=0)[None]
        denom = denom * corr + xp.sum(p, axis=-1, keepdims=True)
        m = m_new
    return acc / xp.maximum(denom, _DENOM_FLOOR)


def decode_attention_reference(q, k, v, mask2, scale,
                               block_k: int = DEFAULT_BLOCK_K):
    """Pure-XLA anchor of the decode variant: the SAME ``_decode_tile``
    body vmapped over (slot, head). ``q`` ``[S, H, D]``, ``k``/``v``
    ``[S, H, Tk, D]``, ``mask2`` ``[S, Tk]`` int8 (shared across heads).
    Returns ``[S, H, D]`` float32."""
    s = np.float32(scale)

    def tile(q1, k2, v2, keep1):
        out = _decode_tile(q1[None].astype(jnp.float32),
                           k2.astype(jnp.float32),
                           v2.astype(jnp.float32),
                           keep1[None] != 0, s, jnp, block_k)
        return out[0]

    over_h = jax.vmap(tile, in_axes=(0, 0, 0, None))
    return jax.vmap(over_h)(q, k, v, mask2)


def decode_attention_host(q, k, v, mask2, scale,
                          block_k: int = DEFAULT_BLOCK_K) -> np.ndarray:
    """Numpy oracle of the decode variant: identical tile body,
    python-looped over (slot, head)."""
    q = np.asarray(q, np.float32)
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    mask2 = np.asarray(mask2)
    sc = np.float32(scale)
    s, h, d = q.shape
    out = np.empty((s, h, d), np.float32)
    for si in range(s):
        keep = mask2[si][None] != 0
        for hi in range(h):
            out[si, hi] = _decode_tile(q[si, hi][None], k[si, hi],
                                       v[si, hi], keep, sc, np,
                                       block_k)[0]
    return out


def _decode_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *,
                   scale: np.float32, block_k: int):
    # one (slot, head) tile per program: q arrives [1, 1, D] (a single
    # query row), K/V [1, 1, Tk, D], the mask [1, Tk]; the shared body
    # runs on the 2-D [1, D] / [Tk, D] tiles
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    keep = mask_ref[:] != 0
    o_ref[0] = _decode_tile(q, k, v, keep, scale, jnp, block_k)


def _decode_call(q, k, v, mask2, scale, block_k: int):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, h, d = q.shape
    tk = k.shape[2]
    kern = functools.partial(_decode_kernel, scale=np.float32(scale),
                             block_k=block_k)
    return pl.pallas_call(
        kern,
        grid=(s, h),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, d), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, d), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((s, h, d), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(q, k, v, mask2)


def decode_attention(q, k, v, kv_mask=None, scale=None,
                     impl: str = "auto", block_k: int = DEFAULT_BLOCK_K):
    """Single-token decode attention against cached K/V.

    ``q`` ``[S, H, D]`` (one query per slot), ``k``/``v`` ``[S, H, Tk, D]``
    (the slot-major cache, one slot's layer-slice per row), ``kv_mask``
    ``[S, Tk]`` bool (True = valid cached position; typically
    ``arange(Tk) <= position``). Returns ``[S, H, D]`` float32;
    fully-masked slots yield exact zeros. Same ``impl``/VMEM-fallback
    discipline as :func:`flash_attention`."""
    s_, h, d = q.shape
    tk = k.shape[2]
    sc = _resolve_scale(scale, d)
    mask2 = decode_mask2(s_, tk, kv_mask)
    if resolve_impl(impl) == "pallas" and _fits_vmem(1, tk, d, block_k):
        return _decode_call(q, k, v, mask2, sc, block_k)
    return decode_attention_reference(q, k, v, mask2, sc, block_k)


# ---- the ring-hop local block: one online update as a kernel ----

def _update_kernel(q_ref, k_ref, v_ref, mask_ref, m_ref, d_ref, a_ref,
                   mo_ref, do_ref, ao_ref, *, scale: np.float32):
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    keep = mask_ref[0] != 0
    m, denom, acc = _online_update(q, k, v, keep, m_ref[0, 0],
                                   d_ref[0, 0], a_ref[0, 0], scale, jnp)
    mo_ref[0, 0] = m
    do_ref[0, 0] = denom
    ao_ref[0, 0] = acc


def _update_call(q4, k4, v4, mask3, m, denom, acc, scale):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q4.shape
    tk = k4.shape[2]

    def tile4(i, j):
        return (i, j, 0, 0)

    def tile_mask(i, j):
        return (i, 0, 0)

    kern = functools.partial(_update_kernel, scale=np.float32(scale))
    spec4 = lambda last: pl.BlockSpec((1, 1, tq, last), tile4,  # noqa: E731
                                      memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kern,
        grid=(b, h),
        in_specs=[
            spec4(d),
            pl.BlockSpec((1, 1, tk, d), tile4, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, tk, d), tile4, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq, tk), tile_mask,
                         memory_space=pltpu.VMEM),
            spec4(1), spec4(1), spec4(d),
        ],
        out_specs=(spec4(1), spec4(1), spec4(d)),
        out_shape=(jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
                   jax.ShapeDtypeStruct((b, h, tq, d), jnp.float32)),
        interpret=jax.default_backend() != "tpu",
    )(q4, k4, v4, mask3, m, denom, acc)


def attention_block_update(q4, k4, v4, keep3, m, denom, acc, scale,
                           impl: str = "xla"):
    """One flash block update over batched ``[B, H, T, D]`` operands —
    ``ring_attention``'s per-hop local block behind its ``impl`` flag.

    ``keep3``: ``[B, Tq, Tk]`` bool (shared across heads). Carry
    ``m``/``denom`` ``[B, H, Tq, 1]``, ``acc`` ``[B, H, Tq, D]``, all
    f32. ``impl="xla"`` runs the shared body vmapped (exactly the
    historical inline update); ``impl="pallas"`` runs it as one fused
    kernel per (batch, head) tile — the score block never leaves VMEM.
    """
    s = np.float32(scale)
    if resolve_impl(impl) == "pallas" \
            and _fits_vmem(q4.shape[2], k4.shape[2], q4.shape[3],
                           k4.shape[2]):
        return _update_call(q4, k4, v4, keep3.astype(jnp.int8),
                            m, denom, acc, s)

    def upd(q2, k2, v2, keep2, m2, d2, a2):
        return _online_update(q2, k2, v2, keep2, m2, d2, a2, s, jnp)

    over_h = jax.vmap(upd, in_axes=(0, 0, 0, None, 0, 0, 0))
    return jax.vmap(over_h)(q4, k4, v4, keep3, m, denom, acc)

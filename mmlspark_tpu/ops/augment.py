"""Device-side batched image augmentation (jit-compiled, per-sample PRNG).

The reference augments by materializing flipped copies host-side
(reference: image-featurizer/src/main/scala/ImageSetAugmenter.scala:38-61
unions a LR-flipped DataFrame); `stages.image.ImageSetAugmenter` mirrors
that for parity. On TPU the profitable form (SURVEY §2.5 item 4) is
augmentation INSIDE the compiled train step: the batch is already in HBM,
the ops are elementwise/gather work the VPU hides under the matmuls, and
no extra host↔device traffic or dataset copies exist.

All functions take a PRNG key and an NHWC batch and are safe under
``jax.jit``/``shard_map`` (fixed shapes, no host control flow)::

    def train_step(state, key, x, y):
        x = augment_batch(key, x, flip_lr=True, crop_pad=4,
                          brightness=0.1)
        ...
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def random_flip_lr(key: jax.Array, batch: jnp.ndarray) -> jnp.ndarray:
    """Flip each sample left-right with probability 0.5."""
    coin = jax.random.bernoulli(key, 0.5, (batch.shape[0],))
    return jnp.where(coin[:, None, None, None], batch[:, :, ::-1, :], batch)


def random_flip_ud(key: jax.Array, batch: jnp.ndarray) -> jnp.ndarray:
    """Flip each sample up-down with probability 0.5."""
    coin = jax.random.bernoulli(key, 0.5, (batch.shape[0],))
    return jnp.where(coin[:, None, None, None], batch[:, ::-1, :, :], batch)


def _photometric(batch: jnp.ndarray, fn) -> jnp.ndarray:
    """Run a photometric op in float and cast back. Integer batches
    (uint8 pixels) round + clip to the dtype's range — computing in the
    integer dtype would wrap negative shifts modularly and truncate
    fractional contrast factors to 0/1.

    The integer contract is EXACT and pinned against the numpy oracles
    below (tests/test_ops.py): compute in f32, round half-to-even
    (``jnp.round`` == ``np.round``), then clip to ``iinfo`` bounds — in
    that order, so a 255-pixel under a positive shift stays 255 and a
    0-pixel under a negative shift stays 0, with no modular wrap and no
    off-by-one at the boundaries from clipping before the round."""
    if jnp.issubdtype(batch.dtype, jnp.integer):
        info = jnp.iinfo(batch.dtype)
        out = fn(batch.astype(jnp.float32))
        return jnp.clip(jnp.round(out), info.min, info.max
                        ).astype(batch.dtype)
    return fn(batch).astype(batch.dtype)


# ---- numpy oracles: the host-reference semantics of each op given its
#      effective draw (shift / factor / offsets). Property tests feed
#      them the SAME values the jax op drew (replaying the documented
#      key schedule) and hold the device output EXACTLY equal for every
#      integer dtype (the round/clip edges and the pad+crop geometry
#      cannot drift silently); float batches match to reduction-order
#      ULPs (XLA and numpy sum the contrast mean in different orders) ----

def host_photometric(batch: np.ndarray, fn) -> np.ndarray:
    """Numpy twin of :func:`_photometric`: f32 compute → round
    half-to-even → clip to the integer dtype's range."""
    batch = np.asarray(batch)
    if np.issubdtype(batch.dtype, np.integer):
        info = np.iinfo(batch.dtype)
        out = fn(batch.astype(np.float32))
        return np.clip(np.round(out), info.min, info.max
                       ).astype(batch.dtype)
    return fn(batch).astype(batch.dtype)


def host_brightness(batch: np.ndarray, shift: np.ndarray) -> np.ndarray:
    """:func:`random_brightness` given its drawn per-sample ``shift``
    (shape ``[N]`` or ``[N,1,1,1]``, the op's own scale)."""
    shift = np.asarray(shift, np.float32).reshape(-1, 1, 1, 1)
    return host_photometric(batch, lambda b: b + shift)


def host_contrast(batch: np.ndarray, factor: np.ndarray) -> np.ndarray:
    """:func:`random_contrast` given its drawn per-sample ``factor``."""
    factor = np.asarray(factor, np.float32).reshape(-1, 1, 1, 1)

    def op(b):
        mean = b.mean(axis=(1, 2, 3), keepdims=True, dtype=np.float32)
        return mean + (b - mean) * factor

    return host_photometric(batch, op)


def host_crop(batch: np.ndarray, pad: int, oy: np.ndarray,
              ox: np.ndarray) -> np.ndarray:
    """:func:`random_crop` given its drawn per-sample offsets: reflect-pad
    ``pad`` on each spatial side, slice the original H×W window at
    ``(oy[i], ox[i])``."""
    batch = np.asarray(batch)
    n, h, w, _c = batch.shape
    padded = np.pad(batch, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                    mode="reflect")
    return np.stack([padded[i, oy[i]:oy[i] + h, ox[i]:ox[i] + w]
                     for i in range(n)])


def random_brightness(key: jax.Array, batch: jnp.ndarray,
                      delta: float) -> jnp.ndarray:
    """Add a per-sample uniform offset in [-delta, delta] (values in the
    batch's own scale — pass delta≈0.1 for [0,1] inputs, ≈25 for uint8
    ranges; integer batches round + clip to the dtype range)."""
    shift = jax.random.uniform(key, (batch.shape[0], 1, 1, 1),
                               minval=-delta, maxval=delta)
    return _photometric(batch, lambda b: b + shift)


def random_contrast(key: jax.Array, batch: jnp.ndarray,
                    lo: float = 0.8, hi: float = 1.2) -> jnp.ndarray:
    """Scale each sample's deviation from its own mean by U[lo, hi]."""
    factor = jax.random.uniform(key, (batch.shape[0], 1, 1, 1),
                                minval=lo, maxval=hi)

    def op(b):
        mean = b.mean(axis=(1, 2, 3), keepdims=True)
        return mean + (b - mean) * factor

    return _photometric(batch, op)


def random_crop(key: jax.Array, batch: jnp.ndarray,
                pad: int) -> jnp.ndarray:
    """Pad ``pad`` pixels on each spatial side (reflect) and take a random
    H×W crop per sample — the standard CIFAR augmentation, as one gather.
    """
    n, h, w, c = batch.shape
    padded = jnp.pad(batch, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                     mode="reflect")
    ky, kx = jax.random.split(key)
    oy = jax.random.randint(ky, (n,), 0, 2 * pad + 1)
    ox = jax.random.randint(kx, (n,), 0, 2 * pad + 1)

    def crop_one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    return jax.vmap(crop_one)(padded, oy, ox)


def augment_batch(key: jax.Array, batch: jnp.ndarray,
                  flip_lr: bool = True, flip_ud: bool = False,
                  crop_pad: int = 0, brightness: float = 0.0,
                  contrast: tuple[float, float] | None = None
                  ) -> jnp.ndarray:
    """Compose the enabled augmentations (static config → one compiled
    program; per-sample randomness folds out of the single key)."""
    keys = jax.random.split(key, 5)
    if crop_pad:
        batch = random_crop(keys[0], batch, crop_pad)
    if flip_lr:
        batch = random_flip_lr(keys[1], batch)
    if flip_ud:
        batch = random_flip_ud(keys[2], batch)
    if brightness:
        batch = random_brightness(keys[3], batch, brightness)
    if contrast is not None:
        batch = random_contrast(keys[4], batch, *contrast)
    return batch

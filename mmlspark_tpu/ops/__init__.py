"""TPU device kernels (Pallas) and op-level utilities.

The reference's device compute lives in CNTK's C++ kernels behind JNI;
here the hot device ops XLA doesn't already schedule optimally get
hand-written Pallas kernels, with jnp reference implementations for
equivalence tests and non-TPU backends.
"""

from mmlspark_tpu.ops.augment import (
    augment_batch, random_brightness, random_contrast, random_crop,
    random_flip_lr, random_flip_ud,
)
from mmlspark_tpu.ops.group_norm import group_norm, group_norm_reference
from mmlspark_tpu.ops.pallas import (
    attention_block_update, flash_attention, flash_attention_host,
    flash_attention_reference, fused_resize_norm, fused_resize_norm_host,
    fused_resize_norm_reference,
)

__all__ = [
    "attention_block_update", "augment_batch", "flash_attention",
    "flash_attention_host", "flash_attention_reference",
    "fused_resize_norm", "fused_resize_norm_host",
    "fused_resize_norm_reference", "group_norm", "group_norm_reference",
    "random_brightness", "random_contrast", "random_crop",
    "random_flip_lr", "random_flip_ud",
]

"""Fused GroupNorm(+activation) Pallas kernel for NHWC feature maps.

Motivation (PERF_NOTES round 3): ResNet-50 featurization is
bandwidth-limited and its GroupNorm layers are pure HBM traffic — XLA
lowers GN as separate reduce + normalize passes over the feature map.
This kernel reads each sample's (H·W, C) block into VMEM once and does
everything there: per-group statistics via two tiny mask matmuls
(lane-aligned — no awkward lane-dim reshapes), normalization, scale/bias,
and the optional ReLU that always follows GN in the ResNet blocks. One
HBM read + one HBM write per element.

Per-sample VMEM footprint: the largest ResNet-50 GN input is 56·56·256
(f32 ≈ 3.2 MB in + out) — comfortably inside the ~16 MB budget, so the
grid is simply the batch dimension.

Training still works: ``jax.custom_vjp`` routes the backward through the
jnp reference implementation (correctness first; the forward is the
featurize/inference hot path). Non-TPU backends run the same kernel in
interpreter mode, keeping CPU tests honest.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def group_norm_reference(x: jnp.ndarray, scale: jnp.ndarray,
                         bias: jnp.ndarray, num_groups: int,
                         eps: float = 1e-6, relu: bool = False
                         ) -> jnp.ndarray:
    """Plain-jnp GroupNorm over the channel (last) axis of NHWC input."""
    n, h, w, c = x.shape
    _validate_groups(c, num_groups)
    cg = c // num_groups
    xf = x.astype(jnp.float32).reshape(n, h * w, num_groups, cg)
    mean = xf.mean(axis=(1, 3), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 3), keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = out.reshape(n, h, w, c) * scale + bias
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(x.dtype)


def _gn_kernel(x_ref, scale_ref, bias_ref, o_ref, *, num_groups: int,
               eps: float, relu: bool):
    import jax.experimental.pallas as pl  # noqa: F401 (kernel namespace)

    h, w, c = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    hw = h * w
    cg = c // num_groups
    xs = x_ref[0].reshape(hw, c).astype(jnp.float32)

    # channel→group aggregation as a mask matmul (lane-aligned; avoids
    # lane-dim reshapes that Mosaic lays out badly)
    ch = jax.lax.broadcasted_iota(jnp.int32, (c, num_groups), 0)
    gr = jax.lax.broadcasted_iota(jnp.int32, (c, num_groups), 1)
    mask = (ch // cg == gr).astype(jnp.float32)        # (C, G)

    # statistics must aggregate in f32 — the MXU's default bf16 multiply
    # visibly corrupts means over thousands of elements
    denom = float(hw * cg)
    hi = jax.lax.Precision.HIGHEST

    # TWO-PASS (centered) variance. The one-pass E[x²] − E[x]² form
    # cancels catastrophically in f32 for feature maps whose mean
    # dominates their spread (x ~ μ ± σ with μ ≫ σ: E[x²] and E[x]²
    # agree to ~σ²/μ² relative — at μ=200, σ=0.02 the f32 one-pass
    # variance was pure noise). Centering first costs one extra pass
    # over the VMEM-resident block and keeps every accumulation f32 —
    # the same stance flax's force_float32_reductions takes, and what a
    # bf16 activation policy (docs/quantization.md) relies on
    s1 = jnp.sum(xs, axis=0, keepdims=True)            # (1, C) Σx
    g1 = jnp.dot(s1, mask, precision=hi) / denom       # (1, G) group mean
    mean_c = jnp.dot(g1, mask.T, precision=hi)         # (1, C) broadcast
    xc = xs - mean_c                                   # centered block
    s2 = jnp.sum(xc * xc, axis=0, keepdims=True)       # (1, C) Σ(x−μ)²
    g2 = jnp.dot(s2, mask, precision=hi) / denom       # (1, G) variance
    rstd = jax.lax.rsqrt(jnp.maximum(g2, 0.0) + eps)

    # group→channel broadcast via the transposed mask
    rstd_c = jnp.dot(rstd, mask.T, precision=hi)       # (1, C)

    out = xc * rstd_c
    out = out * scale_ref[0].reshape(1, c).astype(jnp.float32) \
        + bias_ref[0].reshape(1, c).astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    o_ref[0] = out.reshape(h, w, c).astype(o_ref.dtype)


def _group_norm_fwd_pallas(x: jnp.ndarray, scale: jnp.ndarray,
                           bias: jnp.ndarray, num_groups: int, eps: float,
                           relu: bool) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, w, c = x.shape
    kern = functools.partial(_gn_kernel, num_groups=num_groups, eps=eps,
                             relu=relu)
    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), x.dtype),
        interpret=jax.default_backend() != "tpu",
    )(x, scale.reshape(1, c), bias.reshape(1, c))


def _fits_vmem(h: int, w: int, c: int, itemsize: int) -> bool:
    """Conservative per-sample VMEM estimate for the kernel's buffers.

    The lane dim pads to 128, and the kernel holds the input block, an f32
    working copy, its square, the f32 output, and the cast output —
    roughly ``HW × C_pad × (2·itemsize + 12)`` bytes. Blocks that would
    blow the ~16 MB budget fall back to the XLA lowering (the 112×112×64
    ResNet stem GN is the notable case: C=64 pads 2×)."""
    c_pad = -(-c // 128) * 128
    est = h * w * c_pad * (2 * itemsize + 12)
    return est < 14 * 2 ** 20


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _group_norm_custom(x: jnp.ndarray, scale: jnp.ndarray,
                       bias: jnp.ndarray, num_groups: int, eps: float,
                       relu: bool) -> jnp.ndarray:
    return _group_norm_fwd_pallas(x, scale, bias, num_groups, eps, relu)


def _validate_groups(c: int, num_groups: int) -> None:
    # channels that match no group would silently normalize to zero (the
    # iota mask has no row for them) — refuse loudly instead
    if num_groups <= 0 or c % num_groups != 0:
        raise ValueError(
            f"group_norm: {c} channels not divisible into "
            f"{num_groups} groups")


def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               num_groups: int, eps: float = 1e-6,
               relu: bool = False) -> jnp.ndarray:
    """Fused GroupNorm(+ReLU): Pallas forward (when the per-sample block
    fits VMEM), reference-impl backward; XLA reference otherwise."""
    n, h, w, c = x.shape
    _validate_groups(c, num_groups)
    if not _fits_vmem(h, w, c, x.dtype.itemsize):
        return group_norm_reference(x, scale, bias, num_groups, eps, relu)
    return _group_norm_custom(x, scale, bias, num_groups, eps, relu)


def _gn_fwd(x, scale, bias, num_groups, eps, relu):
    out = _group_norm_fwd_pallas(x, scale, bias, num_groups, eps, relu)
    return out, (x, scale, bias)


def _gn_bwd(num_groups, eps, relu, res, g):
    x, scale, bias = res
    _, vjp = jax.vjp(
        lambda xx, ss, bb: group_norm_reference(
            xx, ss, bb, num_groups, eps, relu), x, scale, bias)
    return vjp(g)


_group_norm_custom.defvjp(_gn_fwd, _gn_bwd)

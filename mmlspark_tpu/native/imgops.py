"""ctypes bindings for the native imgops library, with lazy build + fallback."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger(__name__)

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "src", "imgops.cpp")

_lock = threading.Lock()
_lib: Any = None
_tried = False


def _lib_path() -> str:
    """Build target: next to the source when writable (dev checkout), else
    a user cache dir (installed wheels ship only the .cpp — the NativeLoader
    analog extracts/builds into a writable location, reference:
    core/env/src/main/scala/NativeLoader.java:47-68). Resolved lazily at
    first use (not import) so ``config.set('cache_dir', ...)`` is honored
    and an unwritable filesystem degrades to the NumPy fallback instead of
    breaking the import."""
    if os.access(_HERE, os.W_OK):
        return os.path.join(_HERE, "libimgops.so")
    from mmlspark_tpu.core import config
    d = os.path.join(config.get("cache_dir"), "native")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "libimgops.so")


def _build(lib_path: str) -> bool:
    cmd = ["g++", "-O3", "-fPIC", "-shared", _SRC,
           "-ljpeg", "-lpng", "-o", lib_path]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        _log.warning("imgops native build unavailable: %s", e)
        return False
    if res.returncode != 0:
        _log.warning("imgops native build failed:\n%s", res.stderr[-2000:])
        return False
    return True


def _load() -> Any:
    """Build (if needed) and dlopen the library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            lib_path = _lib_path()
        except OSError as e:
            _log.warning("imgops: no writable build dir (%s); "
                         "using NumPy/OpenCV fallbacks", e)
            return None
        src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
        lib_fresh = (os.path.exists(lib_path)
                     and os.path.getmtime(lib_path) >= src_mtime)
        if not lib_fresh and not _build(lib_path):  # concurrency: allow(CC102): one-shot cc build; serializing every caller behind the build IS the contract, and no other lock ever nests inside
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            _log.warning("imgops dlopen failed: %s", e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.img_decode.argtypes = [u8p, ctypes.c_int,
                                   ctypes.POINTER(u8p),
                                   ctypes.POINTER(ctypes.c_int),
                                   ctypes.POINTER(ctypes.c_int),
                                   ctypes.POINTER(ctypes.c_int)]
        lib.img_decode.restype = ctypes.c_int
        lib.img_free.argtypes = [u8p]
        lib.img_unroll.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                   ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_float),
                                   ctypes.c_int, ctypes.c_float,
                                   ctypes.c_float]
        lib.img_unroll.restype = ctypes.c_int
        lib.img_unroll_batch.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                         ctypes.c_int, ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_float),
                                         ctypes.c_int, ctypes.c_float,
                                         ctypes.c_float]
        lib.img_unroll_batch.restype = ctypes.c_int
        lib.img_resize_bilinear.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                            ctypes.c_int, u8p, ctypes.c_int,
                                            ctypes.c_int]
        lib.img_resize_bilinear.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def decode(data: bytes) -> np.ndarray | None:
    """Decode JPEG/PNG bytes to HWC uint8 BGR; None if the native path
    can't handle it (caller falls back to OpenCV)."""
    lib = _load()
    if lib is None or len(data) < 4:
        return None
    buf = np.frombuffer(data, np.uint8)
    out = ctypes.POINTER(ctypes.c_uint8)()
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    rc = lib.img_decode(buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                        len(data), ctypes.byref(out), ctypes.byref(h),
                        ctypes.byref(w), ctypes.byref(c))
    if rc != 0:
        return None
    try:
        n = h.value * w.value * c.value
        arr = np.ctypeslib.as_array(out, shape=(n,)).reshape(
            h.value, w.value, c.value).copy()
    finally:
        lib.img_free(out)
    return arr


def unroll(hwc: np.ndarray, to_rgb: bool = False, scale: float = 1.0,
           offset: float = 0.0) -> np.ndarray:
    """HWC uint8 → CHW float32 with optional channel swap + affine.

    The UnrollImage hot loop (reference: image-transformer/src/main/scala/
    UnrollImage.scala:18-42 iterates pixel-by-pixel in Scala); here one C++
    pass, or a vectorized NumPy fallback. Float images (a legitimate wire
    dtype — see the image mode field in data/table.py) are processed in
    float32 host-side rather than silently truncated to uint8.
    """
    hwc = np.asarray(hwc)
    if hwc.ndim == 2:
        hwc = hwc[:, :, None]
    if hwc.dtype != np.uint8:
        x = hwc.astype(np.float32, copy=False)
        if to_rgb and x.shape[2] == 3:
            x = x[:, :, ::-1]
        return np.transpose(x, (2, 0, 1)).astype(np.float32) * scale + offset
    hwc = np.ascontiguousarray(hwc)
    h, w, c = hwc.shape
    lib = _load()
    if lib is None:
        x = hwc[:, :, ::-1] if (to_rgb and c == 3) else hwc
        return (np.transpose(x, (2, 0, 1)).astype(np.float32) * scale
                + offset)
    out = np.empty((c, h, w), np.float32)
    lib.img_unroll(hwc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                   h, w, c, out.ctypes.data_as(
                       ctypes.POINTER(ctypes.c_float)),
                   int(to_rgb), float(scale), float(offset))
    return out


def unroll_batch(batch_hwc: np.ndarray, to_rgb: bool = False,
                 scale: float = 1.0, offset: float = 0.0) -> np.ndarray:
    """[N,H,W,C] uint8 → [N,C,H,W] float32 in one native call.

    Float batches stay float (vectorized host path) — no silent uint8
    truncation of legitimate float image columns."""
    batch_hwc = np.asarray(batch_hwc)
    if batch_hwc.dtype != np.uint8:
        x = batch_hwc.astype(np.float32, copy=False)
        if to_rgb and x.shape[-1] == 3:
            x = x[..., ::-1]
        return (np.transpose(x, (0, 3, 1, 2)).astype(np.float32) * scale
                + offset)
    batch_hwc = np.ascontiguousarray(batch_hwc)
    n, h, w, c = batch_hwc.shape
    lib = _load()
    if lib is None:
        x = batch_hwc[..., ::-1] if (to_rgb and c == 3) else batch_hwc
        return (np.transpose(x, (0, 3, 1, 2)).astype(np.float32) * scale
                + offset)
    out = np.empty((n, c, h, w), np.float32)
    lib.img_unroll_batch(
        batch_hwc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w, c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(to_rgb), float(scale), float(offset))
    return out


def resize(hwc: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear uint8 resize; OpenCV fallback."""
    hwc = np.ascontiguousarray(hwc, dtype=np.uint8)
    if hwc.ndim == 2:
        hwc = hwc[:, :, None]
    h, w, c = hwc.shape
    lib = _load()
    if lib is None:
        import cv2
        out = cv2.resize(hwc, (width, height),
                         interpolation=cv2.INTER_LINEAR)
        return out if out.ndim == 3 else out[:, :, None]
    out = np.empty((height, width, c), np.uint8)
    rc = lib.img_resize_bilinear(
        hwc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), height, width)
    if rc != 0:
        raise ValueError(f"resize failed for shape {hwc.shape}")
    return out

"""Native extension loader — builds and binds the C++ imgops library.

Analog of the reference's ``NativeLoader`` which extracts platform .so files
from jar resources and dlopens them (reference:
core/env/src/main/scala/NativeLoader.java:28-127). Here the library is
compiled from the in-repo C++ source on first use (cached next to the
source), bound via ctypes, and every entry point degrades gracefully to a
NumPy/OpenCV fallback when the toolchain or image libraries are missing.
"""

from mmlspark_tpu.native import imgops

__all__ = ["imgops"]

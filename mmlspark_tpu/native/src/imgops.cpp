// imgops — native host-side image ops for mmlspark_tpu.
//
// The TPU-native equivalent of the reference's OpenCV-C++-via-JNI image
// path (reference: readers/src/main/scala/ImageReader.scala:45-63 decode;
// image-transformer/src/main/scala/UnrollImage.scala:18-42 per-pixel unroll
// loop). Decode runs on TPU-VM hosts feeding HBM; unroll/pack is the hot
// row→tensor marshalling step, vectorized in C++ instead of a per-pixel
// Scala loop.
//
// C ABI (ctypes-friendly):
//   img_decode(data, len, &out, &h, &w, &c)  -> 0 on success; out = malloc'd
//       HWC BGR uint8 buffer (caller frees via img_free)
//   img_free(ptr)
//   img_unroll(hwc, h, w, c, out, to_rgb, scale, offset) -> CHW float32
//   img_resize_bilinear(in, h, w, c, out, oh, ow)
//
// Build: g++ -O3 -fPIC -shared imgops.cpp -ljpeg -lpng -o libimgops.so

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <csetjmp>
#include <cstdio>

#include <jpeglib.h>
#include <png.h>

extern "C" {

void img_free(uint8_t* p) { std::free(p); }

// ---- JPEG ----

struct JpegErr {
    jpeg_error_mgr mgr;
    jmp_buf jb;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
    JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
    longjmp(err->jb, 1);
}

static int decode_jpeg(const uint8_t* data, int len, uint8_t** out,
                       int* h, int* w, int* c) {
    jpeg_decompress_struct cinfo;
    JpegErr jerr;
    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = jpeg_err_exit;
    uint8_t* buf = nullptr;
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        std::free(buf);
        return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
                 static_cast<unsigned long>(len));
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return 1;
    }
#ifdef JCS_EXTENSIONS
    cinfo.out_color_space = JCS_EXT_BGR;  // libjpeg-turbo: decode straight to BGR
#else
    cinfo.out_color_space = JCS_RGB;
#endif
    jpeg_start_decompress(&cinfo);
    const int H = cinfo.output_height, W = cinfo.output_width,
              C = cinfo.output_components;
    buf = static_cast<uint8_t*>(std::malloc(static_cast<size_t>(H) * W * C));
    if (!buf) { jpeg_destroy_decompress(&cinfo); return 1; }
    while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = buf + static_cast<size_t>(cinfo.output_scanline) * W * C;
        jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
#ifndef JCS_EXTENSIONS
    if (C == 3) {  // RGB -> BGR swap
        for (size_t i = 0; i < static_cast<size_t>(H) * W; i++) {
            uint8_t t = buf[i * 3];
            buf[i * 3] = buf[i * 3 + 2];
            buf[i * 3 + 2] = t;
        }
    }
#endif
    *out = buf; *h = H; *w = W; *c = C;
    return 0;
}

// ---- PNG (libpng >= 1.6 simplified API) ----

static int decode_png(const uint8_t* data, int len, uint8_t** out,
                      int* h, int* w, int* c) {
    png_image image;
    std::memset(&image, 0, sizeof(image));
    image.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&image, data,
                                          static_cast<size_t>(len)))
        return 1;
    image.format = PNG_FORMAT_BGR;
    const int H = image.height, W = image.width, C = 3;
    uint8_t* buf = static_cast<uint8_t*>(
        std::malloc(PNG_IMAGE_SIZE(image)));
    if (!buf) { png_image_free(&image); return 1; }
    if (!png_image_finish_read(&image, nullptr, buf, 0, nullptr)) {
        png_image_free(&image);
        std::free(buf);
        return 1;
    }
    *out = buf; *h = H; *w = W; *c = C;
    return 0;
}

int img_decode(const uint8_t* data, int len, uint8_t** out,
               int* h, int* w, int* c) {
    if (len < 4) return 1;
    if (data[0] == 0xFF && data[1] == 0xD8)
        return decode_jpeg(data, len, out, h, w, c);
    if (data[0] == 0x89 && data[1] == 'P' && data[2] == 'N' && data[3] == 'G')
        return decode_png(data, len, out, h, w, c);
    return 2;  // unsupported container: caller falls back to OpenCV
}

// ---- unroll: HWC uint8 -> CHW float32 (+ optional BGR->RGB, affine) ----

int img_unroll(const uint8_t* hwc, int h, int w, int c, float* out,
               int to_rgb, float scale, float offset) {
    const size_t plane = static_cast<size_t>(h) * w;
    for (int ch = 0; ch < c; ch++) {
        const int src_ch = (to_rgb && c == 3) ? (c - 1 - ch) : ch;
        float* dst = out + static_cast<size_t>(ch) * plane;
        const uint8_t* src = hwc + src_ch;
        for (size_t i = 0; i < plane; i++)
            dst[i] = static_cast<float>(src[i * c]) * scale + offset;
    }
    return 0;
}

// batched variant: N images, contiguous in and out
int img_unroll_batch(const uint8_t* hwc, int n, int h, int w, int c,
                     float* out, int to_rgb, float scale, float offset) {
    const size_t in_stride = static_cast<size_t>(h) * w * c;
    const size_t out_stride = in_stride;  // same element count
    for (int i = 0; i < n; i++)
        img_unroll(hwc + i * in_stride, h, w, c, out + i * out_stride,
                   to_rgb, scale, offset);
    return 0;
}

// ---- bilinear resize (uint8 HWC) ----

int img_resize_bilinear(const uint8_t* in, int h, int w, int c,
                        uint8_t* out, int oh, int ow) {
    if (h <= 0 || w <= 0 || oh <= 0 || ow <= 0) return 1;
    const float sy = oh > 1 ? static_cast<float>(h - 1) / (oh - 1) : 0.f;
    const float sx = ow > 1 ? static_cast<float>(w - 1) / (ow - 1) : 0.f;
    for (int y = 0; y < oh; y++) {
        const float fy = y * sy;
        const int y0 = static_cast<int>(fy);
        const int y1 = y0 + 1 < h ? y0 + 1 : y0;
        const float wy = fy - y0;
        for (int x = 0; x < ow; x++) {
            const float fx = x * sx;
            const int x0 = static_cast<int>(fx);
            const int x1 = x0 + 1 < w ? x0 + 1 : x0;
            const float wx = fx - x0;
            for (int ch = 0; ch < c; ch++) {
                const float v00 = in[(static_cast<size_t>(y0) * w + x0) * c + ch];
                const float v01 = in[(static_cast<size_t>(y0) * w + x1) * c + ch];
                const float v10 = in[(static_cast<size_t>(y1) * w + x0) * c + ch];
                const float v11 = in[(static_cast<size_t>(y1) * w + x1) * c + ch];
                const float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                                v10 * wy * (1 - wx) + v11 * wy * wx;
                out[(static_cast<size_t>(y) * ow + x) * c + ch] =
                    static_cast<uint8_t>(v + 0.5f);
            }
        }
    }
    return 0;
}

}  // extern "C"

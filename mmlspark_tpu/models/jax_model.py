"""JaxModel — batched DNN inference as a pipeline stage.

The CNTKModel analog (reference: cntk-model/src/main/scala/CNTKModel.scala).
The reference broadcasts serialized model bytes to Spark executors, clones
the graph per task, marshals rows element-by-element into JNI FloatVectors,
evaluates minibatches, and merges outputs back row-wise
(CNTKModel.scala:51-114). The TPU-native redesign:

* the model is a :class:`ModelBundle` (flax module + pytree) — no broadcast
  or per-task clone needed; jit-compiled functions are pure and cached,
* input coercion is one vectorized host copy (``column_matrix`` /
  image stacking) instead of per-element JNI sets,
* the minibatch iterator pads the tail batch to a fixed shape so XLA
  compiles exactly one program per (batch, input) shape,
* dispatch is asynchronous: host marshalling of batch *i+1* overlaps device
  compute of batch *i* (JAX's async dispatch replaces the reference's
  re-batching iterator pipelining),
* inference is **data-parallel over the device mesh**: params live
  device-resident (transferred once, replicated) and each minibatch is
  committed batch-sharded over the ``dp``/``fsdp`` axes, so scoring keeps
  every chip busy — the reference's primary parallelism (Spark-partition DP
  inference, CNTKModel.scala:248-256) mapped to one host feeding a mesh,
* outputs are fetched in a single device→host transfer per transform call
  (no per-minibatch sync),
* output-node selection by name or index matches CNTK node selection
  (CNTKModel.scala:98-108).
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np

from mmlspark_tpu.core import config
from mmlspark_tpu.core.logging_utils import get_logger, timed
from mmlspark_tpu.core.params import Param
# minibatches lives in core.plan (shared with fused pipeline segments);
# re-exported here for the bridge and existing callers
from mmlspark_tpu.core.plan import (  # noqa: F401
    dp_rounded_minibatch, mesh_dp, minibatches, pipeline_minibatches,
)
from mmlspark_tpu.core.schema import is_image_column
from mmlspark_tpu.core.stage import (
    ArrayMeta, DeviceOp, DeviceStage, HasInputCol, HasOutputCol, Transformer,
)
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle, PREPROCESSORS
from mmlspark_tpu.parallel import mesh as mesh_lib

_log = get_logger(__name__)


def _source_dtype(col: np.ndarray, sample: Any) -> Any:
    """uint8 sources stay uint8 (¼ the host→device bytes; the on-device
    forward upcasts) — decoded image bytes are the hot inference input, as
    in the reference's byte-typed image schema. Everything else → float32."""
    d = getattr(np.asarray(sample), "dtype", None)
    return np.uint8 if d == np.uint8 else np.float32


def coerce_input_matrix(table: DataTable, column: str,
                        input_spec: tuple) -> np.ndarray:
    """Coerce an input column to a [N, *input_spec] array (uint8 or float32).

    Accepts: image-struct columns (stacked HWC), vector columns (reshaped to
    the model spec), scalar numeric columns. The dtype-coercion analog of
    CNTKModel.scala:228-245, vectorized.
    """
    col = table[column]
    if is_image_column(table, column):
        # uint8 only when EVERY row is uint8 — a lone float row must not be
        # silently truncated into a uint8 buffer
        datas = [np.asarray(r["data"]) for r in col]
        dtype = (np.uint8 if all(d.dtype == np.uint8 for d in datas)
                 else np.float32)
        first = datas[0]
        if all(d.shape == first.shape and d.dtype == dtype for d in datas):
            # uniform shape+dtype: ONE C-level bulk copy
            batch = np.stack(datas)
        else:
            # mixed-dtype/shape fallback: preallocated per-row assignment
            # (each row cast into the target buffer, no intermediate stack)
            batch = np.empty((len(datas),) + first.shape, dtype=dtype)
            for i, d in enumerate(datas):
                batch[i] = d
    elif col.dtype == object:
        batch = table.column_matrix(column,
                                    dtype=_source_dtype(col, col[0]))
    else:
        batch = table.column_matrix(column, dtype=np.float32)
    want = (len(table),) + tuple(input_spec)
    if batch.shape != want:
        if int(np.prod(batch.shape)) != int(np.prod(want)):
            raise ValueError(
                f"column {column!r} has shape {batch.shape[1:]} per row; "
                f"model expects {tuple(input_spec)}")
        batch = batch.reshape(want)
    return batch


class JaxModel(Transformer, DeviceStage, HasInputCol, HasOutputCol):
    """Applies a jit-compiled model to an input column, in minibatches."""

    model = Param(default=None, doc="ModelBundle to apply", is_complex=True)
    minibatch_size = Param(
        default=None, doc="device minibatch size (None = config default)",
        type_=int)
    output_node = Param(
        default=None, doc="output node to select, by name",
        type_=str)
    output_node_index = Param(
        default=None, doc="output node to select, by index", type_=int)
    mesh_spec = Param(
        default=None, is_complex=True,
        doc="inference mesh layout (MeshSpec/dict); None = data parallelism "
            "over every local device; an explicit spec smaller than the "
            "host's device count uses a prefix of the local devices")
    max_inflight = Param(
        default=8, type_=int, validator=Param.gt(1),
        doc="max minibatch outputs resident on device at once during "
            "transform(); older outputs are fetched to host as newer "
            "batches dispatch, bounding HBM use on very large tables "
            "while keeping the async upload/compute/fetch overlap. "
            "Minimum 2: a window of 1 would serialize fetch with compute")

    def __getstate__(self):
        # jitted closures, device arrays, and locks don't pickle; drop on
        # serialize
        d = self.__dict__.copy()
        d.pop("_jit_cache", None)
        d.pop("_mesh_cache", None)
        d.pop("_jit_lock", None)
        return d

    def set_model_location(self, path: str) -> "JaxModel":
        """Load the model from a published bundle file — the
        ``CNTKModel.setModelLocation`` analog (reference:
        CNTKModel.scala:151-154); pair with ``ModelDownloader`` for the
        zoo-download path."""
        from mmlspark_tpu.data.downloader import load_bundle_file
        self.set(model=load_bundle_file(path))
        return self

    def _resolve_node(self, bundle: ModelBundle) -> str:
        if self.output_node is not None:
            return bundle.resolve_output(self.output_node)
        if self.output_node_index is not None:
            return bundle.resolve_output(self.output_node_index)
        return bundle.resolve_output(None)

    def _mesh(self):
        """The DP inference mesh over this host's devices (multi-host scoring
        = each host runs its own partition stream, the Spark-executor
        analog — so local devices, not the global mesh)."""
        import jax

        if self.__dict__.get("_mesh_cache") is None:
            spec = self.mesh_spec or mesh_lib.MeshSpec(dp=-1)
            self.__dict__["_mesh_cache"] = mesh_lib.make_mesh(
                spec, jax.local_devices())
        return self.__dict__["_mesh_cache"]

    def _compiled_apply(self, bundle: ModelBundle, node: str):
        """(jitted fn, device params, batch sharding, data extent) — cached
        so repeated transform() calls reuse one compiled program AND one
        host→device param transfer (the broadcast-once analog).

        One entry per (module identity, preprocess, node): the entry pins
        the module + params objects it was built from, and a params
        reassignment refreshes the device copy in place — no id-reuse false
        hits, no unbounded growth of stale device trees. The lock keeps
        concurrent first calls (the bridge's default 2-worker overlap)
        from double-compiling and double-uploading the param tree."""
        import jax

        lock = self.__dict__.get("_jit_lock")
        if lock is None:
            import threading
            lock = self.__dict__.setdefault("_jit_lock", threading.Lock())
        with lock:
            return self._compiled_apply_locked(bundle, node, jax)

    def _compiled_apply_locked(self, bundle: ModelBundle, node: str, jax):
        cache = self.__dict__.setdefault("_jit_cache", {})
        key = (id(bundle.module), bundle.preprocess, node)
        entry = cache.get(key)
        if entry is not None:
            fn, dev_params, data, dp, pinned = entry
            if pinned[0] is bundle.module and pinned[1] is bundle.params:
                return fn, dev_params, data, dp
            if pinned[0] is bundle.module:
                # params swapped (e.g. after a training round): reuse the
                # compiled program, re-upload the new tree onto the old
                # copy's sharding; the old device copy is dropped here
                # instead of pinned forever
                leaves = jax.tree_util.tree_leaves(dev_params)
                target = leaves[0].sharding if leaves else None
                dev_params = jax.device_put(bundle.params, target)
                cache[key] = (fn, dev_params, data, dp,
                              (bundle.module, bundle.params))
                return fn, dev_params, data, dp

        mesh = self._mesh()
        pre = PREPROCESSORS.get(bundle.preprocess) if bundle.preprocess else None

        def fwd(params, x):
            import jax.numpy as jnp
            if x.dtype == jnp.uint8:  # uint8 ships thin, computes as f32
                x = x.astype(jnp.float32)
            if pre is not None:
                x = pre(x)
            return bundle.module.apply({"params": params}, x, output=node)

        if mesh.devices.size == 1:
            # single-device fast path: plain placement avoids the sharded
            # transfer/fetch machinery (which costs a round-trip per shard —
            # pathological through remote-device tunnels)
            dev = mesh.devices.reshape(-1)[0]
            dev_params = jax.device_put(bundle.params, dev)
            fn = jax.jit(fwd)
            cache[key] = (fn, dev_params, dev, 1,
                          (bundle.module, bundle.params))
            return cache[key][:4]

        repl = mesh_lib.replicated(mesh)
        data = mesh_lib.batch_sharding(mesh)
        dev_params = jax.device_put(bundle.params, repl)
        fn = jax.jit(fwd, in_shardings=(repl, data), out_shardings=data)
        cache[key] = (fn, dev_params, data, mesh_dp(mesh),
                      (bundle.module, bundle.params))
        return cache[key][:4]

    def transform(self, table: DataTable) -> DataTable:
        bundle: ModelBundle = self.model
        if bundle is None:
            raise ValueError("JaxModel: no model set")
        node = self._resolve_node(bundle)
        size = self.minibatch_size or config.get("default_minibatch_size")
        if len(table) == 0:
            return table.with_column(self.output_col, [])
        with timed(f"JaxModel[{bundle.name}:{node}]", _log, len(table)):
            batch = coerce_input_matrix(table, self.input_col,
                                        bundle.input_spec)
            fn, dev_params, data, dp = self._compiled_apply(bundle, node)
            # minibatch must divide over the data axes (shared sizing)
            size = dp_rounded_minibatch(size, dp, len(batch))
            # the three-stage upload/compute/fetch software pipeline with
            # the max_inflight HBM bound, shared with fused pipeline
            # segments (core.plan)
            result = pipeline_minibatches(
                fn, dev_params, batch, size, data,
                int(self.max_inflight),
                label=f"JaxModel[{bundle.name}:{node}]")[0]
        if result.ndim == 1:
            out_col: Any = result
        else:
            out_col = list(result)
        return table.with_column(self.output_col, out_col)

    # ---- static schema inference ----

    def infer_schema(self, schema: Any) -> Any:
        """The traced truth: the predicted output layout comes from
        ``jax.eval_shape`` over the same forward ``device_fn`` composes —
        no data, no device execution, no compilation. A provable per-row
        size mismatch against the bundle's ``input_spec`` is rejected here
        instead of as an XLA shape error after the H2D upload."""
        from mmlspark_tpu.analysis.info import (
            KIND_IMAGE, ColumnInfo, SchemaError,
        )
        out = schema.copy()
        info = out.get(self.input_col)
        if info is None:
            if schema.exact:
                raise SchemaError(
                    "missing-input-column",
                    f"JaxModel reads missing column {self.input_col!r}; "
                    f"available: {list(schema)}")
            info = ColumnInfo.unknown()
        bundle: ModelBundle = self.model
        if bundle is None:
            raise SchemaError(
                "model-not-set",
                "JaxModel has no model bundle; set model= or "
                "set_model_location() before running the pipeline")
        try:
            node = self._resolve_node(bundle)
        except Exception as e:
            raise SchemaError("bad-output-node", str(e))
        spec = tuple(bundle.input_spec)
        want = int(np.prod(spec))
        size = info.row_size
        if size is not None and size != want:
            kind_note = ("an image column unrolling to"
                         if info.kind == KIND_IMAGE else "per-row size")
            raise SchemaError(
                "input-size-mismatch",
                f"column {self.input_col!r} is {kind_note} {size} values "
                f"but model {bundle.name!r} expects input_spec {spec} "
                f"({want} values)")
        meta = schema.entry_meta(self.input_col)
        if meta is None or int(np.prod(meta.shape)) != want:
            # layout not statically coercible; trace with the model's own
            # spec (what coerce_input_matrix reshapes to)
            meta = ArrayMeta(spec, "float32")
        from mmlspark_tpu.core.plan import _stage_device_fn
        op = _stage_device_fn(self, meta)  # memoized eval_shape trace
        if op is None:  # pragma: no cover - defensive; sizes matched above
            raise SchemaError(
                "device-fn-declined",
                f"JaxModel.device_fn declined layout {meta}")
        shape = tuple(op.out_meta.shape)
        if shape == ():
            out.columns[self.output_col] = ColumnInfo.scalar(
                op.out_meta.dtype)
        else:
            out.columns[self.output_col] = ColumnInfo.vector(
                int(np.prod(shape)), op.out_meta.dtype)
        return out

    # ---- DeviceStage protocol: lets the pipeline planner fuse this model
    #      with adjacent device stages into one compiled program ----

    def device_cache_token(self) -> Any:
        bundle = self.model
        return (None if bundle is None else
                (id(bundle.module), id(bundle.params), bundle.preprocess),
                self.input_col, self.output_col,
                self.output_node, self.output_node_index,
                self.minibatch_size, repr(self.mesh_spec))

    def device_fingerprint(self) -> Any:
        """Stable content identity for the persistent AOT compile cache
        (core/compile_cache.py): the bundle's weights digest replaces
        the ``id()`` triple of :meth:`device_cache_token`, so two
        processes loading the same artifact key the same programs."""
        bundle = self.model
        if bundle is None:
            return None
        from mmlspark_tpu.core.compile_cache import bundle_digest
        return ("JaxModel", bundle_digest(bundle),
                self.input_col, self.output_col,
                self.output_node, self.output_node_index,
                self.minibatch_size, repr(self.mesh_spec))

    def device_fn(self, meta: ArrayMeta) -> DeviceOp | None:
        """The same forward ``JaxModel.transform`` compiles (uint8 ships
        thin and upcasts on device, then the bundle's preprocess and the
        selected output node) as a composable op. Declines on a per-row
        size mismatch so the host path raises its canonical shape error."""
        bundle: ModelBundle = self.model
        if bundle is None:
            return None
        spec = tuple(bundle.input_spec)
        if int(np.prod(meta.shape)) != int(np.prod(spec)):
            return None
        node = self._resolve_node(bundle)
        pre = (PREPROCESSORS.get(bundle.preprocess)
               if bundle.preprocess else None)

        def fwd(params, x):
            import jax.numpy as jnp
            x = x.reshape((x.shape[0],) + spec)
            if x.dtype == jnp.uint8:  # uint8 ships thin, computes as f32
                x = x.astype(jnp.float32)
            if pre is not None:
                x = pre(x)
            return bundle.module.apply({"params": params}, x, output=node)

        import jax
        out = jax.eval_shape(
            fwd, bundle.params,
            jax.ShapeDtypeStruct((1,) + tuple(meta.shape),
                                 np.dtype(meta.dtype)))
        return DeviceOp(fwd, ArrayMeta(tuple(out.shape[1:]),
                                       str(out.dtype)),
                        params=bundle.params)

    def transform_stream(self, tables: Any) -> Iterator[DataTable]:
        """Score a stream of DataTable chunks with bounded memory.

        The compiled program and device-resident params are shared across
        chunks (the jit cache), so streaming costs no recompiles or
        re-uploads — pair with ``data.readers.stream_images`` for
        ImageNet-shard-scale scoring without materializing the dataset.
        """
        for chunk in tables:
            yield self.transform(chunk)

"""JaxModel — batched DNN inference as a pipeline stage.

The CNTKModel analog (reference: cntk-model/src/main/scala/CNTKModel.scala).
The reference broadcasts serialized model bytes to Spark executors, clones
the graph per task, marshals rows element-by-element into JNI FloatVectors,
evaluates minibatches, and merges outputs back row-wise
(CNTKModel.scala:51-114). The TPU-native redesign:

* the model is a :class:`ModelBundle` (flax module + pytree) — no broadcast
  or per-task clone needed; jit-compiled functions are pure and cached,
* input coercion is one vectorized host copy (``column_matrix`` /
  image stacking) instead of per-element JNI sets,
* the minibatch iterator pads the tail batch to a fixed shape so XLA
  compiles exactly one program per (batch, input) shape,
* dispatch is asynchronous: host marshalling of batch *i+1* overlaps device
  compute of batch *i* (JAX's async dispatch replaces the reference's
  re-batching iterator pipelining),
* output-node selection by name or index matches CNTK node selection
  (CNTKModel.scala:98-108).
"""

from __future__ import annotations

from typing import Any, Iterator

import numpy as np

from mmlspark_tpu.core import config
from mmlspark_tpu.core.logging_utils import get_logger, timed
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import is_image_column
from mmlspark_tpu.core.stage import HasInputCol, HasOutputCol, Transformer
from mmlspark_tpu.data.table import DataTable
from mmlspark_tpu.models.bundle import ModelBundle, PREPROCESSORS

_log = get_logger(__name__)


def coerce_input_matrix(table: DataTable, column: str,
                        input_spec: tuple) -> np.ndarray:
    """Coerce an input column to a float32 [N, *input_spec] array.

    Accepts: image-struct columns (stacked HWC), vector columns (reshaped to
    the model spec), scalar numeric columns. The dtype-coercion analog of
    CNTKModel.scala:228-245, vectorized.
    """
    col = table[column]
    if is_image_column(table, column):
        mats = [np.asarray(v["data"], dtype=np.float32) for v in col]
        batch = np.stack(mats)
    else:
        batch = table.column_matrix(column, dtype=np.float32)
    want = (len(table),) + tuple(input_spec)
    if batch.shape != want:
        if int(np.prod(batch.shape)) != int(np.prod(want)):
            raise ValueError(
                f"column {column!r} has shape {batch.shape[1:]} per row; "
                f"model expects {tuple(input_spec)}")
        batch = batch.reshape(want)
    return batch


def minibatches(batch: np.ndarray, size: int) -> Iterator[tuple[np.ndarray, int]]:
    """Yield fixed-shape minibatches; the tail is zero-padded to ``size``.

    Fixed shapes mean XLA compiles one program total — the analog of the
    reference's re-batching iterator (CNTKModel.scala:51-88) designed for
    the compilation model instead of JNI marshalling.
    """
    n = len(batch)
    for start in range(0, n, size):
        chunk = batch[start:start + size]
        valid = len(chunk)
        if valid < size:
            pad = np.zeros((size - valid,) + chunk.shape[1:], chunk.dtype)
            chunk = np.concatenate([chunk, pad])
        yield chunk, valid


class JaxModel(Transformer, HasInputCol, HasOutputCol):
    """Applies a jit-compiled model to an input column, in minibatches."""

    model = Param(default=None, doc="ModelBundle to apply", is_complex=True)
    minibatch_size = Param(
        default=None, doc="device minibatch size (None = config default)",
        type_=int)
    output_node = Param(
        default=None, doc="output node to select, by name",
        type_=str)
    output_node_index = Param(
        default=None, doc="output node to select, by index", type_=int)

    def __getstate__(self):
        # jitted closures don't pickle; drop the cache on copy/serialize
        d = self.__dict__.copy()
        d.pop("_jit_cache", None)
        return d

    def _resolve_node(self, bundle: ModelBundle) -> str:
        if self.output_node is not None:
            return bundle.resolve_output(self.output_node)
        if self.output_node_index is not None:
            return bundle.resolve_output(self.output_node_index)
        return bundle.resolve_output(None)

    def _compiled_apply(self, bundle: ModelBundle, node: str):
        # cache the jitted fn per (module, preprocess, node) so repeated
        # transform() calls reuse one compiled program instead of re-tracing
        import jax

        cache = self.__dict__.setdefault("_jit_cache", {})
        key = (id(bundle.module), bundle.preprocess, node)
        if key in cache:
            return cache[key]

        pre = PREPROCESSORS.get(bundle.preprocess) if bundle.preprocess else None

        def fwd(params, x):
            if pre is not None:
                x = pre(x)
            return bundle.module.apply({"params": params}, x, output=node)

        cache[key] = jax.jit(fwd)
        return cache[key]

    def transform(self, table: DataTable) -> DataTable:
        bundle: ModelBundle = self.model
        if bundle is None:
            raise ValueError("JaxModel: no model set")
        node = self._resolve_node(bundle)
        size = self.minibatch_size or config.get("default_minibatch_size")
        if len(table) == 0:
            return table.with_column(self.output_col, [])
        with timed(f"JaxModel[{bundle.name}:{node}]", _log, len(table)):
            batch = coerce_input_matrix(table, self.input_col,
                                        bundle.input_spec)
            fn = self._compiled_apply(bundle, node)
            outs = []
            valids = []
            # async dispatch: device computes batch i while host slices i+1
            for chunk, valid in minibatches(batch, min(size, len(batch))):
                outs.append(fn(bundle.params, chunk))
                valids.append(valid)
            host = [np.asarray(o)[:v] for o, v in zip(outs, valids)]
            result = np.concatenate(host) if len(host) > 1 else host[0]
        if result.ndim == 1:
            out_col: Any = result
        else:
            out_col = list(result)
        return table.with_column(self.output_col, out_col)
